#!/usr/bin/env python
"""TPC-H Q1 over a MESH: globally-sharded read + device-parallel
aggregation, with XLA inserting the cross-device reductions.

The sharded sibling of ``examples/tpch_q1.py`` and the end-to-end form
of the scaling recipe this framework follows — pick a mesh, annotate
shardings, let XLA place the collectives:

  1. ``read_sharded_global`` decodes the file into global ``jax.Array``s
     sharded over the mesh's "rg" (row-group/data) axis — each device
     holds only its groups' rows, no host ever holds a full column.
  2. One ``jax.jit`` computes the per-segment sums; reducing over the
     sharded row axis makes XLA emit the all-reduce, and the (6, 7)
     result lands replicated on every device.

Runs on whatever devices exist (the 8-device virtual CPU mesh in tests;
real chips on a pod).  Usage: python examples/tpch_q1_sharded.py [--rows N]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/pftpu_jax_cache")

_FLAGS = [b"A", b"N", b"R"]
_STATUS = [b"O", b"F"]
_CUTOFF_DAYS = 10471  # 1998-09-02


def q1_sharded(out, cutoff=_CUTOFF_DAYS):
    """Q1 aggregates from ``read_sharded_global`` output: one jit over
    the globally-sharded columns; the ``.at[].add`` over the sharded row
    axis is what makes XLA emit the cross-device reduction, and the
    (6, 7) result replicates on every device.  The aggregation body is
    shared with the single-chip example (``tpch_q1.q1_agg``)."""
    import jax
    import jax.numpy as jnp

    from examples.tpch_q1 import q1_agg

    @jax.jit
    def agg(qty, price, disc, tax, ship, rf, ls, rowm):
        return q1_agg(
            qty, price, disc, tax, ship,
            rf[:, 0].astype(jnp.int32), ls[:, 0].astype(jnp.int32),
            row_mask=rowm, cutoff=cutoff,
        )

    return agg(
        out["l_quantity"].values,
        out["l_extendedprice"].values,
        out["l_discount"].values,
        out["l_tax"].values,
        out["l_shipdate"].values,
        out["l_returnflag"].values,
        out["l_linestatus"].values,
        out["l_quantity"].row_mask,  # None for uniform files
    )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=200_000)
    args = ap.parse_args()

    import numpy as np

    import jax

    jax.config.update("jax_enable_x64", True)
    from jax.sharding import Mesh

    from benchmarks.workloads import write_lineitem
    from examples.tpch_q1 import q1_host_reference
    from parquet_floor_tpu.parallel.multihost import read_sharded_global

    path = f"/tmp/pftpu_bench_lineitem_{args.rows}.parquet"
    if not os.path.exists(path):
        write_lineitem(path, args.rows)

    devs = np.array(jax.devices())
    mesh = Mesh(devs.reshape(-1), ("rg",))
    want = [
        "l_quantity", "l_extendedprice", "l_discount", "l_tax",
        "l_shipdate", "l_returnflag", "l_linestatus",
    ]
    t0 = time.perf_counter()
    # 'bits' keeps DOUBLE exact on TPU ("auto" would decode f32 there);
    # q1_sharded bitcasts back on device
    out = read_sharded_global(path, mesh, columns=want,
                              float64_policy="bits")
    acc = np.asarray(q1_sharded(out))
    dt = time.perf_counter() - t0

    ref = q1_host_reference(path)
    np.testing.assert_allclose(acc[:, :6], ref[:, :6], rtol=1e-9)
    n_dev = len(devs)
    print(f"sharded Q1 over {args.rows:,} rows on {n_dev} devices "
          f"(mesh axis 'rg'): {dt:.2f}s cold, aggregates match the host "
          "reference to 1e-9; result replicated on every device")


if __name__ == "__main__":
    main()
