#!/usr/bin/env python
"""TPC-H Q1 ("pricing summary report") computed ENTIRELY on device from a
Parquet file: fused decode → jnp segment aggregation, no decoded bytes
ever crossing back to the host until the 6-group result table.

This is the end-to-end shape the framework exists for: the reference's
row loop would box 1M rows through per-cell virtual dispatch
(``ParquetReader.java:176-212``); here the file becomes device-resident
columns in one fused step per row group and the aggregation is a
handful of XLA segment-sums over the 6 (returnflag × linestatus)
groups the synthetic generator populates.

    select l_returnflag, l_linestatus,
           sum(l_quantity), sum(l_extendedprice),
           sum(l_extendedprice*(1-l_discount)),
           sum(l_extendedprice*(1-l_discount)*(1+l_tax)),
           avg(l_quantity), avg(l_extendedprice), avg(l_discount),
           count(*)
    from lineitem where l_shipdate <= DATE '1998-09-02'
    group by l_returnflag, l_linestatus

Usage: python examples/tpch_q1.py [--rows N]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/pftpu_jax_cache")

# group key space: returnflag ∈ {A,N,R} × linestatus ∈ {O,F} → 6 segments
_FLAGS = [b"A", b"N", b"R"]
_STATUS = [b"O", b"F"]
_CUTOFF_DAYS = 10471  # 1998-09-02 as days since epoch


def q1_agg(qty, price, disc, tax, ship, rf_b, ls_b, row_mask=None,
           cutoff=_CUTOFF_DAYS):
    """The Q1 segment aggregation over raw device arrays — shared by the
    single-chip and mesh-sharded examples (jit-compatible; reducing over
    a sharded row axis makes XLA insert the cross-device combine).

    DOUBLE columns decoded under ``float64_policy='bits'`` arrive as
    int64 bit patterns and are bitcast back here.  Returns a (6, 7)
    array: per (returnflag × linestatus) segment — sum_qty, sum_base,
    sum_disc_price, sum_charge, sum_disc, count, (spare 0).
    """
    import jax
    import jax.numpy as jnp

    if qty.dtype == jnp.int64:  # float64_policy='bits'
        qty = jax.lax.bitcast_convert_type(qty, jnp.float64)
        price = jax.lax.bitcast_convert_type(price, jnp.float64)
        disc = jax.lax.bitcast_convert_type(disc, jnp.float64)
        tax = jax.lax.bitcast_convert_type(tax, jnp.float64)
    flag_ids = jnp.zeros_like(rf_b)
    for i, f in enumerate(_FLAGS):
        flag_ids = jnp.where(rf_b == f[0], i, flag_ids)
    seg = flag_ids * 2 + jnp.where(ls_b == _STATUS[0][0], 0, 1)

    keep = ship <= cutoff
    if row_mask is not None:
        keep = keep & row_mask
    w = keep.astype(qty.dtype)
    disc_price = price * (1.0 - disc)
    charge = disc_price * (1.0 + tax)

    def seg_sum(x):
        return jnp.zeros(6, x.dtype).at[seg].add(x * w)

    return jnp.stack([
        seg_sum(qty),
        seg_sum(price),
        seg_sum(disc_price),
        seg_sum(charge),
        seg_sum(disc),
        seg_sum(jnp.ones_like(qty)),
        jnp.zeros(6, qty.dtype),
    ], axis=1)


def q1_device(cols, cutoff=_CUTOFF_DAYS):
    """One row group's Q1 partial aggregates, fully on device.

    ``cols`` is the TpuRowGroupReader output dict; the group key comes
    from the first byte of each padded single-char string row.
    """
    import jax.numpy as jnp

    return q1_agg(
        cols["l_quantity"].values,
        cols["l_extendedprice"].values,
        cols["l_discount"].values,
        cols["l_tax"].values,
        cols["l_shipdate"].values,
        cols["l_returnflag"].values[:, 0].astype(jnp.int32),
        cols["l_linestatus"].values[:, 0].astype(jnp.int32),
        cutoff=cutoff,
    )


def q1_host_reference(path, cutoff=_CUTOFF_DAYS):
    """Single-thread host truth via the NumPy engine."""
    import numpy as np

    from parquet_floor_tpu.format.file_read import ParquetFileReader

    acc = np.zeros((6, 7))
    with ParquetFileReader(path) as r:
        for batch in r.iter_row_groups():
            by = {c.descriptor.path[0]: c for c in batch.columns}
            qty = by["l_quantity"].values
            price = by["l_extendedprice"].values
            disc = by["l_discount"].values
            tax = by["l_tax"].values
            ship = by["l_shipdate"].values
            rf = np.asarray(
                [v[0] for v in by["l_returnflag"].values.to_list()]
            )
            ls = np.asarray(
                [v[0] for v in by["l_linestatus"].values.to_list()]
            )
            flag_ids = np.zeros(len(qty), np.int64)
            for i, f in enumerate(_FLAGS):
                flag_ids[rf == f[0]] = i
            seg = flag_ids * 2 + (ls != _STATUS[0][0])
            keep = ship <= cutoff
            dp = price * (1.0 - disc)
            ch = dp * (1.0 + tax)
            for col_i, x in enumerate(
                (qty, price, dp, ch, disc, np.ones_like(qty))
            ):
                np.add.at(acc[:, col_i], seg[keep], x[keep])
    return acc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    args = ap.parse_args()

    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp  # noqa: F401
    import numpy as np

    from benchmarks.workloads import write_lineitem
    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader

    path = f"/tmp/pftpu_bench_lineitem_{args.rows}.parquet"
    if not os.path.exists(path):
        write_lineitem(path, args.rows)

    want_cols = [
        "l_quantity", "l_extendedprice", "l_discount", "l_tax",
        "l_shipdate", "l_returnflag", "l_linestatus",
    ]

    def run(reader):
        total = None
        for cols in reader.iter_row_groups(columns=want_cols):
            part = q1_device(cols)
            total = part if total is None else total + part
        return total.block_until_ready()

    with TpuRowGroupReader(path, float64_policy="bits") as reader:
        t0 = time.perf_counter()
        out = run(reader)  # cold (compiles)
        cold = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = run(reader)
        warm = time.perf_counter() - t0

    acc = np.asarray(out)
    t0 = time.perf_counter()
    ref = q1_host_reference(path)
    host_dt = time.perf_counter() - t0
    np.testing.assert_allclose(acc[:, :6], ref[:, :6], rtol=1e-9)

    print("l_returnflag l_linestatus  sum_qty      sum_base_price   "
          "sum_disc_price    sum_charge     avg_qty avg_price avg_disc  count")
    for fi, f in enumerate(_FLAGS):
        for si, s in enumerate(_STATUS):
            row = acc[fi * 2 + si]
            n = row[5]
            if n == 0:
                continue
            print(
                f"{f.decode():>12} {s.decode():>12}  {row[0]:12.1f} "
                f"{row[1]:16.2f} {row[2]:16.2f} {row[3]:16.2f} "
                f"{row[0]/n:7.2f} {row[1]/n:9.2f} {row[4]/n:8.4f} {int(n):6d}"
            )
    print(
        f"\ndevice Q1 over {args.rows:,} rows: cold {cold:.2f}s, warm "
        f"{warm*1e3:.0f} ms (decode + aggregate, nothing fetched but the "
        f"6x7 result); host single-thread reference: {host_dt:.2f}s"
    )


if __name__ == "__main__":
    main()
