#!/usr/bin/env python
"""TPC-H Q1 through the BATCH face of the declarative API: a
``BatchHydrator`` plugin receives each row group's columns as
device-resident arrays from ``ParquetReader.stream_batches`` and folds
them into the Q1 partial aggregates on device — the analytics consumer's
idiomatic shape (no engine internals touched, unlike
``examples/tpch_q1.py`` which drives ``TpuRowGroupReader`` directly).

The plugin boundary is the reference's Hydrator contract lifted to row
groups (``HydratorSupplier.java:10-15`` ordering): the supplier sees the
projected column descriptors once; every ``batch`` call then delivers
arrays in exactly that order.

Usage: python examples/tpch_q1_batches.py [--rows N] [--engine tpu|host|auto]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/pftpu_jax_cache")

WANT = [
    "l_quantity", "l_extendedprice", "l_discount", "l_tax",
    "l_shipdate", "l_returnflag", "l_linestatus",
]


_fold_cache = {}


def _jitted_fold():
    """ONE compiled fold step per group, cached at module level so every
    run (and every hydrator) reuses the same executable.  Shapes are
    HWM-bucketed by the engine, so this compiles once per file shape.
    Eager per-op dispatch over a tunnelled link costs ~ms per op — never
    fold eagerly."""
    fn = _fold_cache.get("fold")
    if fn is None:
        import jax
        import jax.numpy as jnp

        from examples.tpch_q1 import q1_agg

        def fold(total, qty, price, disc, tax, ship, rf, ls):
            return total + q1_agg(
                jnp.asarray(qty), jnp.asarray(price),
                jnp.asarray(disc), jnp.asarray(tax),
                jnp.asarray(ship), rf.astype(jnp.int32),
                ls.astype(jnp.int32),
            )

        fn = _fold_cache["fold"] = jax.jit(fold)
    return fn


class Q1BatchHydrator:
    """Folds each group's arrays into the running (6, 7) aggregate.

    Works on either engine: device arrays (engine="tpu", DOUBLE as bit
    patterns — ``q1_agg`` bitcasts) or NumPy (engine="host", real
    float64 — jnp.asarray lifts them; the same jitted fold serves both).
    """

    def __init__(self, columns):
        self.order = [c.path[0] for c in columns]
        self.total = None

    @staticmethod
    def _first_bytes(col):
        """First byte of each string value as a (n,) array — handles
        both engine layouts (host: ByteArrayColumn offsets+data;
        device: (n, max_len) byte rows, sliced eagerly on device)."""
        v = col.values
        if hasattr(v, "offsets"):  # host ByteArrayColumn
            return v.data[v.offsets[:-1]]
        return v[:, 0]

    def batch(self, group_index, cols):
        by = dict(zip(self.order, cols))
        if self.total is None:
            import jax.numpy as jnp

            self.total = jnp.zeros((6, 7), jnp.float64)
        self.total = _jitted_fold()(
            self.total,
            by["l_quantity"].values, by["l_extendedprice"].values,
            by["l_discount"].values, by["l_tax"].values,
            by["l_shipdate"].values,
            self._first_bytes(by["l_returnflag"]),
            self._first_bytes(by["l_linestatus"]),
        )
        return group_index


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--engine", default="tpu",
                    choices=["host", "tpu", "auto"])
    args = ap.parse_args()

    import jax

    jax.config.update("jax_enable_x64", True)
    import numpy as np

    from benchmarks.workloads import write_lineitem
    from examples.tpch_q1 import q1_host_reference
    from parquet_floor_tpu import ParquetReader

    path = f"/tmp/pftpu_bench_lineitem_{args.rows}.parquet"
    if not os.path.exists(path):
        write_lineitem(path, args.rows)

    def run():
        hyd = {}

        def supplier(columns):
            hyd["h"] = Q1BatchHydrator(columns)
            return hyd["h"]

        for _ in ParquetReader.stream_batches(
            path, supplier, columns=WANT, engine=args.engine
        ):
            pass
        return jax.block_until_ready(hyd["h"].total)

    run()
    run()  # two warm passes: compile, then executable/runtime load
    best = float("inf")
    dev_total = None
    for _ in range(3):
        t0 = time.perf_counter()
        dev_total = run()
        best = min(best, time.perf_counter() - t0)
    # fetch the 6x7 result ONCE, after all timing: on tunnelled links
    # the first device->host fetch costs seconds of fixed latency and
    # degrades subsequent transfers — keep it out of the decode wall
    # (a locally-attached host pays ~nothing here)
    table = np.asarray(dev_total)
    print(f"engine={args.engine}: Q1 over {args.rows:,} rows in "
          f"{best * 1e3:.1f} ms (warm, best of 3; decode+aggregate on "
          f"device, result table fetched once after timing)")

    ref = q1_host_reference(path)
    rel = np.abs(table[:, :6] - ref[:, :6]) / np.maximum(
        np.abs(ref[:, :6]), 1e-12
    )
    print(f"max relative delta vs host reference: {rel.max():.2e}")
    assert rel.max() < 1e-9
    hdr = ["sum_qty", "sum_base", "sum_disc_price", "sum_charge",
           "sum_disc", "count"]
    print(" seg  " + "  ".join(f"{h:>14s}" for h in hdr))
    for s in range(6):
        print(f"  {s}   " + "  ".join(f"{table[s, i]:14.2f}" for i in range(6)))


if __name__ == "__main__":
    main()
