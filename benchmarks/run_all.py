#!/usr/bin/env python
"""Measure all five BASELINE.json configs: single-thread CPU host decode
(the reference-equivalent engine; the reference itself publishes no
numbers — SURVEY.md §6) vs the TPU decode engine.

Per config this reports the full north-star metric set: rows/s, GB/s
decoded (decompressed bytes / wall time), and p50/p99 page-decode latency
(fused device decode of one staged+shipped row group, divided across its
data pages).  A raw link-bandwidth probe (device_put of a 64 MB buffer)
anchors the transfer-floor analysis for config #1.

Usage: python benchmarks/run_all.py [--rows N] [--reps K] [--json OUT]
       [--rows-api]

--rows-api additionally times the declarative row API (stream_content with
a tuple-building hydrator) through both engines — the one-front-door
comparison: same rows, host cursor vs device decode.

Prints a markdown table and (with --json) a machine-readable report.
bench.py remains the driver's single-line headline metric (config #2).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/pftpu_jax_cache")


def _host_decode(path):
    from parquet_floor_tpu.format.file_read import ParquetFileReader

    with ParquetFileReader(path) as r:
        rows = 0
        for batch in r.iter_row_groups():
            for col in batch.columns:
                _ = col.values
                _ = col.def_levels
                _ = col.rep_levels
            rows += batch.num_rows
        return rows


def _tpu_decode(reader):
    import jax

    for cols in reader.iter_row_groups():
        arrs = [c.values for c in cols.values()]
        arrs += [c.def_levels for c in cols.values() if c.def_levels is not None]
        arrs += [c.rep_levels for c in cols.values() if c.rep_levels is not None]
        jax.block_until_ready(arrs)


def link_bandwidth_gbps(mb: int = 64, reps: int = 5) -> float:
    """Raw host→device link throughput: device_put of one contiguous
    buffer, best of ``reps`` (the transfer floor any shipped-bytes
    pipeline is bounded by)."""
    import jax
    import numpy as np

    buf = np.random.default_rng(0).integers(
        0, 255, mb << 20, dtype=np.uint8
    )
    jax.block_until_ready(jax.device_put(buf))  # warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(jax.device_put(buf))
        best = min(best, time.perf_counter() - t0)
    return buf.nbytes / best / 1e9


def measure(name, path, reps, nested_rows=None):
    import bench as headline
    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader
    from parquet_floor_tpu.utils import trace

    size = os.path.getsize(path)
    _host_decode(path)  # warm page cache
    t0 = time.perf_counter()
    rows = _host_decode(path)
    cpu_dt = time.perf_counter() - t0
    n_rows = nested_rows if nested_rows is not None else rows

    reader = TpuRowGroupReader(path, float64_policy="bits")
    decoded_bytes = headline._decoded_bytes(reader.reader)
    best = float("inf")
    try:
        _tpu_decode(reader)  # compile warmup
        trace.enable()
        trace.reset()
        for _ in range(reps):
            t0 = time.perf_counter()
            _tpu_decode(reader)
            best = min(best, time.perf_counter() - t0)
        stages = trace.stats()
        trace.disable()
        latency = headline.page_decode_latency(reader, reps=15)
    finally:
        reader.close()

    ship = stages.get("ship", {})
    ship_gbps = (
        ship["bytes"] / ship["seconds"] / 1e9 if ship.get("seconds") else None
    )
    # engine="auto" routing for this file: what the cost model picks, and
    # the measured rows/s of the engine it picked (auto matches-or-beats
    # host everywhere iff every row here is >= 1.0x vs host)
    from parquet_floor_tpu.format.file_read import ParquetFileReader
    from parquet_floor_tpu.tpu import cost as tcost

    with ParquetFileReader(path) as fr:
        choice = tcost.choose_engine(fr, purpose="batch")
    auto_rows_per_s = (
        n_rows / best if choice.engine == "tpu" else n_rows / cpu_dt
    )
    return {
        "auto_engine": choice.engine,
        "auto_rows_per_s": round(auto_rows_per_s, 1),
        "auto_vs_host": round(auto_rows_per_s / (n_rows / cpu_dt), 2),
        "config": name,
        "rows": n_rows,
        "file_mb": round(size / 1e6, 2),
        "cpu_rows_per_s": round(n_rows / cpu_dt, 1),
        "tpu_rows_per_s": round(n_rows / best, 1),
        "speedup": round(cpu_dt / best, 2),
        "cpu_s": round(cpu_dt, 4),
        "tpu_s": round(best, 4),
        "decoded_bytes": decoded_bytes,
        "decoded_GB_per_s": round(decoded_bytes / best / 1e9, 3),
        "cpu_decoded_GB_per_s": round(decoded_bytes / cpu_dt / 1e9, 3),
        "shipped_bytes_per_pass": ship.get("bytes", 0) // max(reps, 1),
        "ship_GB_per_s": round(ship_gbps, 3) if ship_gbps else None,
        **latency,
    }


def measure_rows_api(path, reps=3, engines=("host", "tpu", "auto")):
    """The one-front-door comparison: hydrated row stream through the host
    cursor vs the device engine vs cost-model routing (identical rows;
    engine selection is the variable)."""
    from parquet_floor_tpu import ParquetReader
    from parquet_floor_tpu.utils import trace

    class _Rows:
        def start(self):
            return []

        def add(self, t, h, v):
            t.append(v)
            return t

        def finish(self, t):
            return tuple(t)

    out = {}
    for engine in engines:
        n = 0
        best = float("inf")
        trace.enable()
        trace.reset()
        for _ in range(reps):
            t0 = time.perf_counter()
            n = sum(
                1
                for _ in ParquetReader.stream_content(
                    path, lambda c: _Rows(), engine=engine
                )
            )
            best = min(best, time.perf_counter() - t0)
        routed = [
            d for d in trace.decisions() if d["decision"] == "engine.auto"
        ]
        trace.disable()
        out[engine] = {"rows": n, "s": round(best, 4),
                       "rows_per_s": round(n / best, 1)}
        if engine == "auto" and routed:
            out[engine]["routed_to"] = routed[-1]["engine"]
            out[engine]["route_reason"] = routed[-1]["reason"]
    if "host" in out and "tpu" in out:
        out["speedup"] = round(out["host"]["s"] / out["tpu"]["s"], 2)
    if "host" in out and "auto" in out:
        out["auto_vs_host"] = round(out["host"]["s"] / out["auto"]["s"], 2)
    return out


def measure_batch_api(path, reps=3):
    """The batch face vs the raw engine: stream_batches(engine="tpu")
    must stay within ~2x of TpuRowGroupReader.iter_row_groups (it wraps
    the same fused decode — arrays stay on device, no cell loop)."""
    import jax

    from parquet_floor_tpu import ParquetReader
    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader

    def raw_once():
        r = TpuRowGroupReader(path, float64_policy="bits", dict_form="gather")
        try:
            t0 = time.perf_counter()
            for cols in r.iter_row_groups():
                jax.block_until_ready([c.values for c in cols.values()])
            return time.perf_counter() - t0
        finally:
            r.close()

    def batch_once():
        t0 = time.perf_counter()
        for cols in ParquetReader.stream_batches(path, engine="tpu"):
            jax.block_until_ready([c.values for c in cols])
        return time.perf_counter() - t0

    raw_once(), batch_once()  # warm
    raw = min(raw_once() for _ in range(reps))
    batch = min(batch_once() for _ in range(reps))
    return {
        "raw_s": round(raw, 4),
        "batch_s": round(batch, 4),
        "batch_vs_raw": round(batch / raw, 2),
    }


def measure_write(n: int, reps: int = 3) -> dict:
    """Write-path walls (VERDICT r4 #5): configs #1 and #2 shapes
    through this repo's writer, single thread, against pyarrow writing
    the SAME data with equivalent settings.  The reference publishes no
    write numbers (its writer rides parquet-mr, reference
    ParquetWriter.java:26-77), so pyarrow single-thread is the stated
    proxy baseline (BASELINE.md).  Data is generated once outside the
    timers; each wall covers encode + compress + file I/O to /tmp."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from benchmarks import workloads as w
    from parquet_floor_tpu import ParquetFileWriter, WriterOptions, types
    from parquet_floor_tpu.format.encodings.plain import ByteArrayColumn
    from parquet_floor_tpu.format.parquet_thrift import CompressionCodec

    out = {}

    def best_of(fn):
        b = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            b = min(b, time.perf_counter() - t0)
        return b

    # --- config #1 shape: one INT64 PLAIN column, uncompressed ----------
    rng = np.random.default_rng(0)
    vals = rng.integers(-(2**62), 2**62, n).astype(np.int64)
    p_ours = "/tmp/pftpu_write_cfg1.parquet"
    p_pa = "/tmp/pftpu_write_cfg1_pa.parquet"
    schema1 = types.message("t", types.required(types.INT64).named("v"))
    opts1 = WriterOptions(
        codec=CompressionCodec.UNCOMPRESSED, enable_dictionary=False,
        page_version=2, data_page_values=100_000,
    )

    def ours1():
        with ParquetFileWriter(p_ours, schema1, opts1) as wr:
            wr.write_columns({"v": vals})

    def pa1():
        pq.write_table(
            pa.table({"v": vals}), p_pa, use_dictionary=False,
            compression="NONE", write_statistics=True,
        )

    t_ours, t_pa = best_of(ours1), best_of(pa1)
    out["cfg1_int64_plain"] = {
        "rows": n,
        "pftpu_rows_per_s": round(n / t_ours, 1),
        "pftpu_MB_per_s": round(os.path.getsize(p_ours) / t_ours / 1e6, 1),
        "pyarrow_rows_per_s": round(n / t_pa, 1),
        "vs_pyarrow": round(t_pa / t_ours, 3),
        "file_mb": round(os.path.getsize(p_ours) / 1e6, 2),
    }

    # --- config #2 shape: 16-column lineitem, Snappy + dictionary -------
    cols = w.lineitem_columns(n, seed=0)
    p_ours = "/tmp/pftpu_write_cfg2.parquet"
    p_pa = "/tmp/pftpu_write_cfg2_pa.parquet"
    opts2 = WriterOptions(
        codec=CompressionCodec.SNAPPY, page_version=2,
        data_page_values=50_000,
    )
    schema2 = w.lineitem_schema()

    def ours2():
        with ParquetFileWriter(p_ours, schema2, opts2) as wr:
            wr.write_columns(cols)

    pa_cols = {
        k: (
            v.to_list() if isinstance(v, ByteArrayColumn)
            else v
        )
        for k, v in cols.items()
    }
    pa_table = pa.table(pa_cols)

    def pa2():
        pq.write_table(
            pa_table, p_pa, use_dictionary=True, compression="SNAPPY",
        )

    t_ours, t_pa = best_of(ours2), best_of(pa2)
    out["cfg2_lineitem_snappy_dict"] = {
        "rows": n,
        "pftpu_rows_per_s": round(n / t_ours, 1),
        "pftpu_MB_per_s": round(os.path.getsize(p_ours) / t_ours / 1e6, 1),
        "pyarrow_rows_per_s": round(n / t_pa, 1),
        "vs_pyarrow": round(t_pa / t_ours, 3),
        "file_mb": round(os.path.getsize(p_ours) / 1e6, 2),
    }
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--json", default=None)
    ap.add_argument("--rows-api", action="store_true")
    ap.add_argument("--batch-api", action="store_true")
    ap.add_argument("--write", action="store_true",
                    help="also time the write path (configs #1/#2 shapes "
                         "vs pyarrow single-thread)")
    ap.add_argument(
        "--engine", dest="engines", action="append",
        choices=["host", "tpu", "auto"],
        help="rows-api engines to time (repeatable; default: all three)",
    )
    args = ap.parse_args()
    if not args.engines:
        args.engines = ["host", "tpu", "auto"]

    import jax

    jax.config.update("jax_enable_x64", True)

    from benchmarks import workloads as w

    n = args.rows
    cfgs = []

    p = f"/tmp/pftpu_cfg1_{n}.parquet"
    if not os.path.exists(p):
        w.write_int64_plain(p, n)
    cfgs.append(("1 INT64 PLAIN uncompressed", p, None))

    p = f"/tmp/pftpu_bench_lineitem_{n}.parquet"
    if not os.path.exists(p):
        w.write_lineitem(p, n)
    lineitem_path = p
    cfgs.append(("2 TPC-H lineitem Snappy+dict", p, None))

    p = f"/tmp/pftpu_cfg3_{n}.parquet"
    if not os.path.exists(p):
        w.write_taxi_like(p, n)
    cfgs.append(("3 taxi ZSTD mixed/optional", p, None))

    p = "/tmp/pftpu_cfg4.parquet"
    if not os.path.exists(p):
        w.write_wide_delta(p)
    cfgs.append(("4 wide 1000col DELTA", p, 20_000))

    p = f"/tmp/pftpu_cfg5_{n // 10}.parquet"
    if not os.path.exists(p):
        w.write_nested_list(p, n // 10)
    cfgs.append(("5 nested LIST<STRUCT> Snappy", p, n // 10))

    link = link_bandwidth_gbps()
    print(f"link bandwidth (64 MB device_put, best of 5): {link:.3f} GB/s",
          flush=True)

    results = []
    for name, path, nested_rows in cfgs:
        r = measure(name, path, args.reps, nested_rows)
        r["link_GB_per_s"] = round(link, 3)
        results.append(r)
        print(
            f"| {r['config']:<30} | {r['rows']:>9} | {r['file_mb']:>7.2f} "
            f"| {r['cpu_rows_per_s']:>12,.0f} | {r['tpu_rows_per_s']:>12,.0f} "
            f"| {r['speedup']:>6.2f}x | {r['decoded_GB_per_s']:>6.3f} GB/s "
            f"| p50 {r['page_decode_p50_us_derived']:>7.2f} us/page (derived) "
            f"| auto->{r['auto_engine']} {r['auto_vs_host']:>5.2f}x vs host |",
            flush=True,
        )

    batch_api = None
    if args.batch_api:
        batch_api = measure_batch_api(lineitem_path, reps=args.reps)
        print(
            f"batch-api (lineitem): raw {batch_api['raw_s'] * 1e3:.1f} ms vs "
            f"stream_batches {batch_api['batch_s'] * 1e3:.1f} ms "
            f"({batch_api['batch_vs_raw']}x)",
            flush=True,
        )

    write_bench = None
    if args.write:
        write_bench = measure_write(args.rows, reps=min(args.reps, 3))
        for cfg, r in write_bench.items():
            print(
                f"write {cfg}: {r['pftpu_rows_per_s']:,.0f} rows/s "
                f"({r['pftpu_MB_per_s']:.1f} MB/s to disk) vs pyarrow "
                f"{r['pyarrow_rows_per_s']:,.0f} rows/s "
                f"({r['vs_pyarrow']}x)",
                flush=True,
            )

    rows_api = None
    if args.rows_api:
        rows_api = measure_rows_api(
            lineitem_path, reps=args.reps, engines=args.engines
        )
        host = rows_api.get("host")
        parts = [
            f"{e} {rows_api[e]['rows_per_s']:,.0f} rows/s"
            + (
                f" (routed {rows_api[e].get('routed_to', '?')})"
                if e == "auto"
                else ""
            )
            for e in args.engines
            if e in rows_api
        ]
        print("rows-api (lineitem, hydrated rows): " + " vs ".join(parts),
              flush=True)
        if host and "auto" in rows_api:
            print(f"  auto vs host: {rows_api['auto_vs_host']}x", flush=True)

    if args.json:
        with open(args.json, "w") as f:
            json.dump(
                {
                    "backend": jax.devices()[0].platform,
                    "link_GB_per_s": round(link, 3),
                    "results": results,
                    "rows_api": rows_api,
                    "batch_api": batch_api,
                    "write": write_bench,
                },
                f,
                indent=2,
            )
    return results


if __name__ == "__main__":
    main()
