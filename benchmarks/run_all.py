#!/usr/bin/env python
"""Measure all five BASELINE.json configs: single-thread CPU host decode
(the reference-equivalent engine; the reference itself publishes no
numbers — SURVEY.md §6) vs the TPU decode engine.

Usage: python benchmarks/run_all.py [--rows N] [--reps K] [--json OUT]

Prints a markdown table and (with --json) a machine-readable report.
bench.py remains the driver's single-line headline metric (config #2).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/pftpu_jax_cache")


def _host_decode(path):
    from parquet_floor_tpu.format.file_read import ParquetFileReader

    with ParquetFileReader(path) as r:
        rows = 0
        for batch in r.iter_row_groups():
            for col in batch.columns:
                _ = col.values
                _ = col.def_levels
                _ = col.rep_levels
            rows += batch.num_rows
        return rows


def _tpu_decode(reader):
    import jax

    for cols in reader.iter_row_groups():
        arrs = [c.values for c in cols.values()]
        arrs += [c.def_levels for c in cols.values() if c.def_levels is not None]
        arrs += [c.rep_levels for c in cols.values() if c.rep_levels is not None]
        jax.block_until_ready(arrs)


def measure(name, path, reps, nested_rows=None):
    import jax

    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader

    size = os.path.getsize(path)
    _host_decode(path)  # warm page cache
    t0 = time.perf_counter()
    rows = _host_decode(path)
    cpu_dt = time.perf_counter() - t0
    n_rows = nested_rows if nested_rows is not None else rows

    reader = TpuRowGroupReader(path)
    best = float("inf")
    try:
        _tpu_decode(reader)  # compile warmup
        for _ in range(reps):
            t0 = time.perf_counter()
            _tpu_decode(reader)
            best = min(best, time.perf_counter() - t0)
    finally:
        reader.close()

    return {
        "config": name,
        "rows": n_rows,
        "file_mb": round(size / 1e6, 2),
        "cpu_rows_per_s": round(n_rows / cpu_dt, 1),
        "tpu_rows_per_s": round(n_rows / best, 1),
        "speedup": round(cpu_dt / best, 2),
        "cpu_s": round(cpu_dt, 4),
        "tpu_s": round(best, 4),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    import jax

    jax.config.update("jax_enable_x64", True)

    from benchmarks import workloads as w

    n = args.rows
    cfgs = []

    p = f"/tmp/pftpu_cfg1_{n}.parquet"
    if not os.path.exists(p):
        w.write_int64_plain(p, n)
    cfgs.append(("1 INT64 PLAIN uncompressed", p, None))

    p = f"/tmp/pftpu_bench_lineitem_{n}.parquet"
    if not os.path.exists(p):
        w.write_lineitem(p, n)
    cfgs.append(("2 TPC-H lineitem Snappy+dict", p, None))

    p = f"/tmp/pftpu_cfg3_{n}.parquet"
    if not os.path.exists(p):
        w.write_taxi_like(p, n)
    cfgs.append(("3 taxi ZSTD mixed/optional", p, None))

    p = "/tmp/pftpu_cfg4.parquet"
    if not os.path.exists(p):
        w.write_wide_delta(p)
    cfgs.append(("4 wide 1000col DELTA", p, 20_000))

    p = f"/tmp/pftpu_cfg5_{n // 10}.parquet"
    if not os.path.exists(p):
        w.write_nested_list(p, n // 10)
    cfgs.append(("5 nested LIST<STRUCT> Snappy", p, n // 10))

    results = []
    for name, path, nested_rows in cfgs:
        r = measure(name, path, args.reps, nested_rows)
        results.append(r)
        print(
            f"| {r['config']:<30} | {r['rows']:>9} | {r['file_mb']:>7.2f} "
            f"| {r['cpu_rows_per_s']:>12,.0f} | {r['tpu_rows_per_s']:>12,.0f} "
            f"| {r['speedup']:>6.2f}x |",
            flush=True,
        )
    if args.json:
        with open(args.json, "w") as f:
            json.dump(
                {"backend": jax.devices()[0].platform, "results": results}, f,
                indent=2,
            )
    return results


if __name__ == "__main__":
    main()
