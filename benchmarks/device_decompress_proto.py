#!/usr/bin/env python
"""Device-decompression prototype: ship Snappy pages *compressed*, decode
on the TPU (docs/DESIGN_DECOMPRESSION.md "what would change the
decision"; VERDICT round-2 next #4).

The formulation is the doc's named one — host scans token boundaries
(cheap, linear, no byte copies: strictly less host work than host
decompression), device does the actual byte production:

  host:   Snappy tags → segment table (literal/copy, length, offset) +
          the literal pool (a contiguous slice-out of the compressed
          stream).  Shipped bytes = literal pool + 12·segments, always
          less than the decompressed output for match-bearing data.
  device: one fused jnp program — segment cumsum, searchsorted to map
          each output byte to its segment, then log₂-depth pointer
          doubling to resolve copy-of-copy chains (overlapping copies
          included), and a final literal-pool gather.

This is measured as a standalone prototype over the TPC-H lineitem
column chunks (the headline config's real bytes), not wired into the
engine: the point is to quantify the ship+stage delta device
decompression buys, now that trace shows every config is *stage*-bound
(host read+decompress+plan) with ship second — see the table in
docs/DESIGN_DECOMPRESSION.md.

Usage: python benchmarks/device_decompress_proto.py [--rows N]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/pftpu_jax_cache")

# pointer-doubling rounds: resolves copy chains up to depth 2^K; segment
# counts per page are < 2^18, so 20 rounds cover any legal block
K_ROUNDS = 20


def scan_tokens(data: bytes):
    """Host pass: Snappy block → (is_lit u8[S], seg_len i32[S],
    seg_off i32[S], lit_pool u8[L], n_out).  No output bytes are
    produced — this is the 'host scans token boundaries' half."""
    from parquet_floor_tpu.format.snappy import SnappyError, _read_varint

    data = bytes(data)
    expected, pos = _read_varint(data, 0)
    dlen = len(data)
    is_lit, seg_len, seg_off = [], [], []
    lit_slices = []
    opos = 0
    while pos < dlen:
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:
            ln = tag >> 2
            if ln >= 60:
                nb = ln - 59
                ln = int.from_bytes(data[pos : pos + nb], "little")
                pos += nb
            ln += 1
            if pos + ln > dlen or opos + ln > expected:
                raise SnappyError("literal overruns buffer")
            is_lit.append(1)
            seg_len.append(ln)
            seg_off.append(0)
            lit_slices.append((pos, ln))
            pos += ln
            opos += ln
            continue
        if kind == 1:
            ln = ((tag >> 2) & 0x7) + 4
            off = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos : pos + 2], "little")
            pos += 2
        else:
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos : pos + 4], "little")
            pos += 4
        if off == 0 or off > opos or opos + ln > expected:
            raise SnappyError("bad copy")
        is_lit.append(0)
        seg_len.append(ln)
        seg_off.append(off)
        opos += ln
    if opos != expected:
        raise SnappyError("short stream")
    pool = b"".join(data[p : p + ln] for p, ln in lit_slices)
    return (
        np.asarray(is_lit, np.int32),
        np.asarray(seg_len, np.int32),
        np.asarray(seg_off, np.int32),
        np.frombuffer(pool, np.uint8),
        expected,
    )


def make_device_decoder(n_out: int, n_segs: int):
    import jax
    import jax.numpy as jnp
    from functools import partial

    @partial(jax.jit, static_argnames=())
    def decode(is_lit, seg_len, seg_off, lit_pool):
        seg_end = jnp.cumsum(seg_len)
        lit_start = jnp.cumsum(jnp.where(is_lit == 1, seg_len, 0)) - jnp.where(
            is_lit == 1, seg_len, 0
        )
        i = jnp.arange(n_out, dtype=jnp.int32)
        s = jnp.searchsorted(seg_end, i, side="right").astype(jnp.int32)
        s = jnp.minimum(s, n_segs - 1)
        start = seg_end[s] - seg_len[s]
        within = i - start
        # src < 0 encodes "resolved into the literal pool at -(src+1)";
        # src >= 0 encodes "copy of output byte src"
        src = jnp.where(
            is_lit[s] == 1,
            -(lit_start[s] + within) - 1,
            i - seg_off[s],
        )
        # pointer doubling: after k rounds every chain of depth < 2^k is
        # resolved; legal blocks cannot exceed segment-count depth
        for _ in range(K_ROUNDS):
            nxt = jnp.take(src, jnp.maximum(src, 0))
            src = jnp.where(src < 0, src, nxt)
        return jnp.take(lit_pool, -src - 1)

    return decode


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    args = ap.parse_args()

    import jax

    jax.config.update("jax_enable_x64", True)

    from benchmarks.workloads import write_lineitem
    from parquet_floor_tpu.format import codecs, snappy
    from parquet_floor_tpu.format.file_read import ParquetFileReader
    from parquet_floor_tpu.format.parquet_thrift import CompressionCodec

    path = f"/tmp/pftpu_bench_lineitem_{args.rows}.parquet"
    if not os.path.exists(path):
        write_lineitem(path, args.rows)

    # real compressed bytes: each column chunk of row group 0, its pages'
    # decompressed payloads re-blocked as ONE snappy block per chunk (the
    # restricted 'fixed-window blocks' layout the doc names — one block
    # per chunk keeps the prototype simple; pages would work identically)
    blocks = []
    with ParquetFileReader(path) as r:
        rg = r.row_groups[0]
        for chunk in rg.columns:
            raw_pages = r.read_raw_column_chunk(chunk)
            parts = []
            for page in raw_pages:
                h = page.header
                pay = bytes(page.payload)
                codec = chunk.meta_data.codec
                v2 = h.data_page_header_v2
                if v2 is not None:
                    # v2 pages: levels ride uncompressed ahead of values
                    lv = (v2.repetition_levels_byte_length or 0) + (
                        v2.definition_levels_byte_length or 0
                    )
                    if not codec or v2.is_compressed is False:
                        parts.append(pay)
                    else:
                        parts.append(pay[:lv] + codecs.decompress(
                            codec, pay[lv:], h.uncompressed_page_size - lv
                        ))
                elif codec:
                    parts.append(codecs.decompress(
                        codec, pay, h.uncompressed_page_size
                    ))
                else:
                    parts.append(pay)
            raw = b"".join(parts)
            blocks.append(codecs.compress(CompressionCodec.SNAPPY, raw))

    total_comp = sum(len(b) for b in blocks)
    results = []
    dev_total = 0.0
    scan_total = 0.0
    ship_proto = 0
    total_out = 0
    for b in blocks:
        t0 = time.perf_counter()
        is_lit, seg_len, seg_off, pool, n_out = scan_tokens(b)
        scan_total += time.perf_counter() - t0
        total_out += n_out
        n_segs = len(seg_len)
        ship_proto += pool.nbytes + 12 * n_segs
        decode = make_device_decoder(n_out, n_segs)
        d_args = [jax.device_put(np.asarray(a)) for a in
                  (is_lit, seg_len, seg_off, pool)]
        out = decode(*d_args)
        out.block_until_ready()
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            decode(*d_args).block_until_ready()
            best = min(best, time.perf_counter() - t0)
        dev_total += best
        # correctness vs the first-party host decoder
        want = np.frombuffer(snappy.decompress(b), np.uint8)
        np.testing.assert_array_equal(np.asarray(out), want)
        results.append((n_out, n_segs, len(b), best))

    print(f"blocks: {len(blocks)}  decompressed {total_out/1e6:.1f} MB  "
          f"compressed {total_comp/1e6:.1f} MB "
          f"(ratio {total_out/total_comp:.2f}x)")
    print(f"shipped (prototype: literals + 12B/segment): "
          f"{ship_proto/1e6:.1f} MB  ({total_out/ship_proto:.2f}x less "
          "than shipping decompressed)")
    print(f"host token scan (pure Python here): {scan_total*1e3:.0f} ms — "
          "the same walk the native decoder does minus all byte copies")
    print(f"device decode total (best-of-5 per block, compiled): "
          f"{dev_total*1e3:.1f} ms  "
          f"({total_out/dev_total/1e9:.2f} GB/s decompressed on device)")
    link = 1.25e9  # measured by benchmarks/run_all.py on this host
    t_ship_decomp = total_out / link
    t_ship_proto = ship_proto / link
    print("pipeline arithmetic at the measured 1.25 GB/s link:")
    print(f"  ship decompressed: {t_ship_decomp*1e3:.1f} ms")
    print(f"  ship compressed + device decode: "
          f"{t_ship_proto*1e3:.1f} + {dev_total*1e3:.1f} = "
          f"{(t_ship_proto + dev_total)*1e3:.1f} ms")


if __name__ == "__main__":
    main()
