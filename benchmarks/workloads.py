"""Benchmark workload generators for the five BASELINE.json configs.

Config #2's TPC-H lineitem shape follows the public TPC-H spec's column
domains (16 columns: 4 int keys, 4 decimals-as-double, 2 flag strings,
3 dates, 2 instruction strings, 1 freeform comment).
"""

from __future__ import annotations


import numpy as np

from parquet_floor_tpu import ParquetFileWriter, WriterOptions, types
from parquet_floor_tpu.format.encodings.plain import ByteArrayColumn
from parquet_floor_tpu.format.parquet_thrift import CompressionCodec


def lineitem_schema():
    t = types
    s = lambda b: b.as_(t.string())  # noqa: E731
    return t.message(
        "lineitem",
        t.required(t.INT64).named("l_orderkey"),
        t.required(t.INT64).named("l_partkey"),
        t.required(t.INT64).named("l_suppkey"),
        t.required(t.INT32).named("l_linenumber"),
        t.required(t.DOUBLE).named("l_quantity"),
        t.required(t.DOUBLE).named("l_extendedprice"),
        t.required(t.DOUBLE).named("l_discount"),
        t.required(t.DOUBLE).named("l_tax"),
        s(t.required(t.BYTE_ARRAY)).named("l_returnflag"),
        s(t.required(t.BYTE_ARRAY)).named("l_linestatus"),
        t.required(t.INT32).as_(t.date()).named("l_shipdate"),
        t.required(t.INT32).as_(t.date()).named("l_commitdate"),
        t.required(t.INT32).as_(t.date()).named("l_receiptdate"),
        s(t.required(t.BYTE_ARRAY)).named("l_shipinstruct"),
        s(t.required(t.BYTE_ARRAY)).named("l_shipmode"),
        s(t.required(t.BYTE_ARRAY)).named("l_comment"),
    )


_WORDS = (
    "carefully final deposits detect slyly regular accounts sleep furiously "
    "ironic requests wake quickly blithely even packages cajole express "
    "pending foxes among theodolites nag bold pinto beans above the"
).split()


def lineitem_columns(n: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    orderkey = np.sort(rng.integers(1, n, n)).astype(np.int64)
    date_base = 8035  # ~1992-01-01 in days-since-epoch
    comments = np.array(
        [" ".join(rng.choice(_WORDS, rng.integers(4, 9))) for _ in range(2048)]
    )
    comment_col = ByteArrayColumn.from_list(
        [c.encode() for c in comments[rng.integers(0, len(comments), n)]]
    )
    return {
        "l_orderkey": orderkey,
        "l_partkey": rng.integers(1, n // 4 + 2, n).astype(np.int64),
        "l_suppkey": rng.integers(1, n // 200 + 2, n).astype(np.int64),
        "l_linenumber": rng.integers(1, 8, n).astype(np.int32),
        "l_quantity": rng.integers(1, 51, n).astype(np.float64),
        "l_extendedprice": np.round(rng.uniform(900, 105000, n), 2),
        "l_discount": np.round(rng.integers(0, 11, n) * 0.01, 2),
        "l_tax": np.round(rng.integers(0, 9, n) * 0.01, 2),
        "l_returnflag": [("A", "N", "R")[i] for i in rng.integers(0, 3, n)],
        "l_linestatus": [("O", "F")[i] for i in rng.integers(0, 2, n)],
        "l_shipdate": (date_base + rng.integers(0, 2526, n)).astype(np.int32),
        "l_commitdate": (date_base + rng.integers(0, 2526, n)).astype(np.int32),
        "l_receiptdate": (date_base + rng.integers(0, 2526, n)).astype(np.int32),
        "l_shipinstruct": [
            ("DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN")[i]
            for i in rng.integers(0, 4, n)
        ],
        "l_shipmode": [
            ("AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK")[i]
            for i in rng.integers(0, 7, n)
        ],
        "l_comment": comment_col,
    }


def write_lineitem(path, n_rows: int, row_group_rows: int = 250_000, seed: int = 0):
    """Write the config-#2 file: Snappy + dictionary, v2 pages."""
    schema = lineitem_schema()
    opts = WriterOptions(
        codec=CompressionCodec.SNAPPY, page_version=2,
        data_page_values=50_000,
    )
    with ParquetFileWriter(path, schema, opts) as w:
        done = 0
        chunk = 0
        while done < n_rows:
            take = min(row_group_rows, n_rows - done)
            w.write_columns(
                {k: _slice_col(v, 0, take) for k, v in lineitem_columns(take, seed + chunk).items()}
            )
            done += take
            chunk += 1
    return path


def _slice_col(v, lo, hi):
    if isinstance(v, ByteArrayColumn):
        return v
    return v[lo:hi] if not isinstance(v, list) else v[lo:hi]


def write_int64_plain(path, n_rows: int = 1_000_000, seed: int = 0):
    """Config #1: single INT64 PLAIN column, uncompressed."""
    rng = np.random.default_rng(seed)
    schema = types.message("t", types.required(types.INT64).named("v"))
    opts = WriterOptions(
        codec=CompressionCodec.UNCOMPRESSED, enable_dictionary=False,
        page_version=2, data_page_values=100_000,
    )
    with ParquetFileWriter(path, schema, opts) as w:
        w.write_columns({"v": rng.integers(-(2**62), 2**62, n_rows).astype(np.int64)})
    return path


def write_taxi_like(path, n_rows: int = 1_000_000, seed: int = 0):
    """Config #3: NYC-taxi-like — mixed DOUBLE/BYTE_ARRAY, ZSTD, optional."""
    rng = np.random.default_rng(seed)
    t = types
    schema = t.message(
        "trips",
        t.required(t.DOUBLE).named("fare"),
        t.optional(t.DOUBLE).named("tip"),
        t.required(t.DOUBLE).named("distance"),
        t.optional(t.BYTE_ARRAY).as_(t.string()).named("payment_type"),
        t.required(t.INT64).named("pickup_ts"),
        t.optional(t.INT32).named("passengers"),
    )
    mask = rng.random(n_rows)
    opts = WriterOptions(codec=CompressionCodec.ZSTD, page_version=2,
                         data_page_values=50_000)
    pay = ("CASH", "CREDIT", "DISPUTE", "NOCHARGE")
    with ParquetFileWriter(path, schema, opts) as w:
        w.write_columns(
            {
                "fare": np.round(rng.uniform(2.5, 200, n_rows), 2),
                "tip": [None if m < 0.3 else round(f, 2)
                        for m, f in zip(mask, rng.uniform(0, 40, n_rows))],
                "distance": np.round(rng.uniform(0.1, 40, n_rows), 2),
                "payment_type": [None if m < 0.05 else pay[i]
                                 for m, i in zip(mask, rng.integers(0, 4, n_rows))],
                "pickup_ts": (
                    1_600_000_000 + np.sort(rng.integers(0, 30_000_000, n_rows))
                ).astype(np.int64),
                "passengers": [None if m < 0.1 else int(i)
                               for m, i in zip(mask, rng.integers(1, 7, n_rows))],
            }
        )
    return path


def write_wide_delta(path, n_rows: int = 20_000, n_cols: int = 1000, seed: int = 0):
    """Config #4: 1000 INT32 columns, DELTA_BINARY_PACKED."""
    rng = np.random.default_rng(seed)
    t = types
    schema = t.message(
        "wide", *[t.required(t.INT32).named(f"c{i}") for i in range(n_cols)]
    )
    opts = WriterOptions(
        codec=CompressionCodec.UNCOMPRESSED, enable_dictionary=False,
        delta_integers=True, page_version=2, data_page_values=n_rows,
    )
    base = np.cumsum(rng.integers(-3, 60, n_rows)).astype(np.int32)
    with ParquetFileWriter(path, schema, opts) as w:
        w.write_columns({f"c{i}": base + i for i in range(n_cols)})
    return path


def write_nested_list(path, n_rows: int = 100_000, seed: int = 0):
    """Config #5: LIST<STRUCT> repeated groups (written via pyarrow; the
    engine-level Dremel read path is exercised against it)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(seed)
    lengths = rng.integers(0, 5, n_rows)
    total = int(lengths.sum())
    item_ids = rng.integers(0, 1000, total)
    qtys = rng.integers(1, 50, total)
    offsets = np.zeros(n_rows + 1, np.int32)
    np.cumsum(lengths, out=offsets[1:])
    structs = pa.StructArray.from_arrays(
        [pa.array(item_ids, type=pa.int64()), pa.array(qtys, type=pa.int32())],
        ["item", "qty"],
    )
    lists = pa.ListArray.from_arrays(pa.array(offsets), structs)
    table = pa.table({"order_id": pa.array(np.arange(n_rows), type=pa.int64()),
                      "items": lists})
    pq.write_table(table, path, compression="SNAPPY")
    return path
