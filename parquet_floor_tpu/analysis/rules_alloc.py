"""FL-ALLOC — allocation sizes derived from parsed file fields must flow
through the checked i32 size-cap helper ``errors.checked_alloc_size``.

The PR 1 bug class: a flipped size bit in a header (page size, varint
count, delta block geometry) drives ``np.empty(n)`` straight into a
multi-GiB allocation whose ``MemoryError`` is then — correctly! — passed
through as *host pressure* instead of surfacing as corruption.  The fix
is a single helper that validates ``0 <= n < 2**31`` and raises
``CorruptPageError`` with context; this rule makes the helper mandatory.

**FL-ALLOC001** fires on ``np.empty/zeros/ones/full(size, ...)``, on
``ctypes.create_string_buffer(size)`` (the native binding's output
buffers — the ctypes boundary re-raw-ifies sizes the format layer
already blessed, so the discipline repeats there), and on
``bytes(e)``/``bytearray(e)`` when ``e`` is visibly
integer-producing (arithmetic, ``int(...)``, ``int.from_bytes``) —
whenever the size expression is not provably *safe*.  Safe means built
from:

* integer literals and ``ALL_CAPS`` constants;
* ``len(...)`` and ``.shape``/``.itemsize``/``.ndim`` (sizes of data
  already in memory);
* ``x % c`` / ``x & c`` with a literal ``c`` (bounded);
* ``min(...)`` with at least one safe operand (clamped);
* a direct ``checked_alloc_size(...)`` call;
* names every one of whose assignments is safe (a conservative in-function
  fixpoint; loop targets, parameters, and nonlocals are never safe —
  bless them through the helper under a NEW name, e.g.
  ``nv = checked_alloc_size(num_values, "...")``, so the raw and checked
  values cannot be confused).

``bytes(buf)``/``bytes(view[a:b])`` conversions are not flagged (their
size is the size of data already held).  The rule is deliberately
conservative-by-construction: it cannot prove a guard like
``if n > cap: raise`` — route the value through the helper instead; that
is the point (one blessed spelling, greppable, carrying error context).

Scope: files under ``parquet_floor_tpu/format/`` — the layer that parses
wire bytes — plus ``tpu/engine.py`` (footer-derived staging sizes) and
``native/binding.py`` (the ctypes boundary: output buffers for the C
decompressors/scanners, where an unchecked size becomes a raw
``create_string_buffer``/``np.empty`` of attacker-controlled bytes).
The C scanners themselves are allocation-free by design — the audit in
docs/static_analysis.md records why.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import FileContext, enclosing_function, last_part

RULES = [
    ("FL-ALLOC001",
     "allocation size derived from parsed data must flow through "
     "errors.checked_alloc_size"),
]

_NP_ALLOCS = {"empty", "zeros", "ones", "full"}
_NP_MODULES = {"np", "numpy"}
_SAFE_ATTRS = {"shape", "itemsize", "ndim"}
_BLESS = "checked_alloc_size"
_TAINT = object()  # marker for never-safe bindings


class _Scope:
    """Flow-insensitive safety of local names in one function (or module).

    ``assignments[name]`` collects every bound value; a name is safe when
    all of them are safe expressions (greatest fixpoint), and never safe
    once any binding is a taint marker (loop target, parameter, ...).
    """

    def __init__(self, fn: ast.AST):
        self.assignments: Dict[str, List[object]] = {}
        self._collect(fn)
        self.safe = self._fixpoint()

    def _bind(self, name: str, value: object) -> None:
        self.assignments.setdefault(name, []).append(value)

    def _bind_target(self, target: ast.AST, value: object) -> None:
        if isinstance(target, ast.Name):
            self._bind(target.id, value)
        elif isinstance(target, (ast.Tuple, ast.List)):
            if isinstance(value, ast.Attribute) and \
                    value.attr in _SAFE_ATTRS:
                for elt in target.elts:
                    self._bind_target(elt, value)
            elif isinstance(value, ast.Tuple) and \
                    len(value.elts) == len(target.elts):
                for elt, v in zip(target.elts, value.elts):
                    self._bind_target(elt, v)
            else:
                for elt in target.elts:
                    self._bind_target(elt, _TAINT)

    def _collect(self, fn: ast.AST) -> None:
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = fn.args
            for a in (args.posonlyargs + args.args + args.kwonlyargs
                      + ([args.vararg] if args.vararg else [])
                      + ([args.kwarg] if args.kwarg else [])):
                self._bind(a.arg, _TAINT)
            body = fn.body
        else:
            body = getattr(fn, "body", [])
        stack = list(body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scopes analyzed separately
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    self._bind_target(t, node.value)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                self._bind_target(node.target, node.value)
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, ast.Name):
                    # treat `x op= v` as `x = x op v`
                    self._bind(node.target.id, ast.BinOp(
                        left=ast.Name(id=node.target.id, ctx=ast.Load()),
                        op=node.op, right=node.value))
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                self._bind_target(node.target, _TAINT)
            elif isinstance(node, ast.With):
                for item in node.items:
                    if item.optional_vars is not None:
                        self._bind_target(item.optional_vars, _TAINT)
            elif isinstance(node, ast.ExceptHandler) and node.name:
                self._bind(node.name, _TAINT)
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                for n in node.names:
                    self._bind(n, _TAINT)
            elif isinstance(node, ast.NamedExpr):
                self._bind_target(node.target, _TAINT)
            for child in ast.iter_child_nodes(node):
                stack.append(child)
            if isinstance(node, (ast.comprehension,)):
                self._bind_target(node.target, _TAINT)

    def _fixpoint(self) -> Set[str]:
        safe = {
            n for n, vals in self.assignments.items()
            if all(v is not _TAINT for v in vals)
        }
        changed = True
        while changed:
            changed = False
            for n in list(safe):
                if not all(_safe_expr(v, safe) for v in self.assignments[n]):
                    safe.discard(n)
                    changed = True
        return safe


def _safe_expr(e: object, safe: Set[str]) -> bool:
    if e is _TAINT or not isinstance(e, ast.AST):
        return False
    if isinstance(e, ast.Constant):
        return True
    if isinstance(e, ast.Name):
        return e.id in safe or (e.id.upper() == e.id and e.id.lower() != e.id)
    if isinstance(e, ast.UnaryOp):
        return _safe_expr(e.operand, safe)
    if isinstance(e, ast.BinOp):
        if isinstance(e.op, (ast.Mod, ast.BitAnd)) and \
                isinstance(e.right, ast.Constant):
            return True  # bounded by the literal
        return _safe_expr(e.left, safe) and _safe_expr(e.right, safe)
    if isinstance(e, ast.BoolOp):
        return all(_safe_expr(v, safe) for v in e.values)
    if isinstance(e, ast.IfExp):
        return _safe_expr(e.body, safe) and _safe_expr(e.orelse, safe)
    if isinstance(e, (ast.Tuple, ast.List)):
        if any(isinstance(x, ast.Constant) and x.value == 0 for x in e.elts):
            return True  # a zero dimension: the allocation is empty
        return all(_safe_expr(x, safe) for x in e.elts)
    if isinstance(e, ast.Call):
        name = last_part(e.func)
        if name == _BLESS:
            return True
        if name == "len":
            return True
        if name == "min" and e.args:
            return any(_safe_expr(a, safe) for a in e.args)
        if name in ("max", "int") and e.args:
            return all(_safe_expr(a, safe) for a in e.args)
        if name and e.args and name.lower().replace("_", "").endswith(
                "maxcompressedsize"):
            # a codec's worst-case bound (pftpu_*_max_compressed_size,
            # BrotliEncoderMaxCompressedSize): an affine function of an
            # in-memory length — safe whenever its input is
            return all(_safe_expr(a, safe) for a in e.args)
        return False
    if isinstance(e, ast.Attribute):
        return e.attr in _SAFE_ATTRS
    if isinstance(e, ast.Subscript):
        return isinstance(e.value, ast.Attribute) and \
            e.value.attr in _SAFE_ATTRS
    return False


def _int_producing(e: ast.AST) -> bool:
    """Is `e` visibly an integer (vs a buffer being copied)?  Used to
    decide whether bytes()/bytearray() get the size check at all."""
    if isinstance(e, ast.BinOp):
        return True
    if isinstance(e, ast.Call):
        name = last_part(e.func)
        return name in ("int", "from_bytes", "min", "max")
    return False


def check(ctx: FileContext, project=None):
    # format/ parses wire bytes; tpu/engine.py sizes its staging arenas
    # and decode buffers from the same footer/page fields (group byte
    # estimates, padded string widths, chunk row counts);
    # native/binding.py is the ctypes boundary where those sizes become
    # raw output buffers for the C decompressors; and write/ sizes its
    # compaction carry buffers and device encode inputs from footer row
    # counts of FOREIGN files (the compactor reads corpora it did not
    # write) — all the SAME bug class, all in scope.
    in_default = (
        ctx.under("parquet_floor_tpu", "format")
        or ctx.under("parquet_floor_tpu", "write")
        or ctx.is_module("tpu/engine.py", "native/binding.py",
                         "tpu/encode_kernels.py")
    )
    if not ctx.in_scope("FL-ALLOC", in_default):
        return
    scopes: Dict[Optional[ast.AST], _Scope] = {}
    for node in ctx.nodes:
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        size: Optional[ast.AST] = None
        what = None
        if isinstance(f, ast.Attribute) and f.attr in _NP_ALLOCS and \
                last_part(f.value) in _NP_MODULES:
            what = f"np.{f.attr}"
            if node.args:
                size = node.args[0]
            else:
                size = next((kw.value for kw in node.keywords
                             if kw.arg == "shape"), None)
        elif last_part(f) == "create_string_buffer" and node.args:
            what = "ctypes.create_string_buffer"
            size = node.args[0]
        elif isinstance(f, ast.Name) and f.id in ("bytes", "bytearray") and \
                len(node.args) == 1 and _int_producing(node.args[0]):
            what = f.id
            size = node.args[0]
        if size is None:
            continue
        fn = enclosing_function(ctx, node)
        if fn not in scopes:
            scopes[fn] = _Scope(fn if fn is not None else ctx.tree)
        if not _safe_expr(size, scopes[fn].safe):
            yield (node.lineno, "FL-ALLOC001",
                   f"{what} size comes from parsed data without flowing "
                   "through errors.checked_alloc_size — a corrupt length "
                   "field becomes a giant allocation instead of "
                   "CorruptPageError")
