"""SARIF 2.1.0 output for floorlint (``--format=sarif``).

One run, one driver (``floorlint``), every registered rule in
``tool.driver.rules`` (CI annotates findings by ``ruleIndex``), one
``result`` per violation.  The resolved call chain of graph-aware
findings (FL-TPU chain mode, FL-LOCK, FL-RACE, FL-ASYNC) rides in
``relatedLocations`` — one entry per hop, in root→sink order, the hop's
function name as the location message.  floorlint chains carry hop
*names* (the chain is a call-graph path, not a token stream), so each
hop anchors to the violation's own artifact; the message text is the
round-trippable payload.

Schema shape is pinned by ``test_floorlint.py::test_cli_sarif_format``:
version string, driver rules, result/location/region nesting, and the
chain round-trip.
"""

from __future__ import annotations

from typing import List

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"


def _location(path: str, line: int, message: str = "") -> dict:
    loc = {
        "physicalLocation": {
            "artifactLocation": {"uri": path},
            "region": {"startLine": line},
        },
    }
    if message:
        loc["message"] = {"text": message}
    return loc


def to_sarif(result, all_rules) -> dict:
    """The SARIF document for one :class:`RunResult` — ``all_rules`` is
    the ``(id, doc)`` registry (``analysis.ALL_RULES`` plus the
    synthetic FL-SYNTAX arm for unparsable files)."""
    rules: List[dict] = [
        {
            "id": rule,
            "shortDescription": {"text": doc},
            "defaultConfiguration": {"level": "error"},
        }
        for rule, doc in all_rules
    ]
    index = {r["id"]: i for i, r in enumerate(rules)}
    results: List[dict] = []
    for v in result.violations:
        entry = {
            "ruleId": v.rule,
            "level": "error",
            "message": {"text": v.message},
            "locations": [_location(v.path, v.line)],
        }
        if v.rule in index:
            entry["ruleIndex"] = index[v.rule]
        if v.chain:
            entry["relatedLocations"] = [
                _location(v.path, v.line, hop) for hop in v.chain
            ]
        results.append(entry)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "floorlint",
                    "informationUri": "docs/static_analysis.md",
                    "rules": rules,
                },
            },
            "results": results,
        }],
    }
