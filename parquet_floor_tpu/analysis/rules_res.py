"""FL-RES — resource acquisition guards (the PR 1 fd-leak shape).

PR 1 fixed an fd/mmap leak where ``ParquetFileReader.__init__`` opened a
``FileSource`` and a corrupt footer raised before anyone owned the close.
This rule makes the whole shape unrepresentable: every acquisition of
``open()`` / ``FileSource()`` / ``FileSink()`` / ``mmap.mmap()`` must be
managed on **all exception paths**.

The scan scheduler added two THREAD-backed resource shapes with the same
hazard (a raise between construction and release leaks worker threads,
not just an fd): ``ThreadPoolExecutor(...)`` and scan handles
(``DatasetScanner(...)``).  Both are acquisitions here; ``shutdown()``
counts as their release verb alongside ``close()``.

The remote-storage layer (``io/remote.py``, docs/remote.md) added the
SESSION/POOL shape: ``RemoteSource``, ``SimulatedRemoteSource``, and
``ParallelRangeReader`` each own a fetch thread pool (and a transport
connection), so an unreleased handle leaks threads AND a remote session.

The serving layer (``serve/``, docs/serving.md) added the CACHE/CONTEXT
shape: ``SharedBufferCache`` pins the process's buffer memory,
``Serving``/``Tenant`` hold registrations against it, and a lookup
``Dataset`` keeps its files (fds, mmaps) open by design — all release
with ``close()`` and follow the same contract.
They follow the same contract: with-managed, ownership-transferred
(e.g. into a reader or a scan chain), or closed-in-finally.  A zero-arg
**factory lambda** returning one (the scan scheduler's lazy-open
protocol: ``lambda: RemoteSource(...)``) is ownership transfer too —
the lambda's body IS its return value, and the executor that calls the
factory closes what it opened.

**FL-RES001** fires unless the acquisition is one of:

* a ``with`` item (directly or wrapped, e.g. ``closing(open(p))``);
* an argument to another call (ownership transfer —
  ``RetryingSource(FileSource(p))``);
* returned / yielded, directly or via a local that is later returned;
* stored on ``self`` in a class that defines ``close``/``__exit__``
  (the owning-wrapper pattern: ``FileSource`` itself);
* bound to a local whose ``.close()``/``.shutdown()`` is reachable on
  error — i.e. a ``try`` in the same function releases it in a
  ``finally`` or an ``except`` handler (the constructor-guard shape
  PR 1 landed).

Linear ``f = open(p); use(f); f.close()`` is deliberately flagged: any
exception in ``use`` leaks ``f`` — exactly the bug class this rule
retires.  ``open(p).read()`` chains are flagged too (fd lives until GC).

Scope: every analyzed file (package, tests, scripts).
"""

from __future__ import annotations

import ast

from .core import (
    FileContext,
    ancestors,
    enclosing_class,
    enclosing_function,
    last_part,
)

RULES = [
    ("FL-RES001",
     "open()/FileSource()/FileSink()/mmap.mmap()/ThreadPoolExecutor()/"
     "scan handles must be context-managed, transferred, or "
     "closed/shut down on all exception paths"),
]

_ACQUIRERS = {
    "FileSource", "FileSink", "ThreadPoolExecutor", "DatasetScanner",
    # remote sessions/pools (io/remote.py): each owns a fetch pool and
    # a transport connection — same leak shape, same release contract
    "RemoteSource", "SimulatedRemoteSource", "ParallelRangeReader",
    # the serving layer (serve/, docs/serving.md): a SharedBufferCache
    # holds the process's buffer memory, a Serving context registers
    # tenants against it, a Tenant holds a fair-share seat, and a
    # lookup Dataset keeps its files (and their fds) OPEN by design —
    # all four release with close() and leak exactly like an fd if a
    # raise lands between acquisition and release
    "SharedBufferCache", "Serving", "Tenant", "Dataset",
    # process-scale serving (serve/shm_cache.py, serve/daemon.py): a
    # ShmCacheTier maps a SHARED MEMORY segment (+ a lock-file fd; the
    # creator's close() is also the segment's unlink — leaking one
    # leaks host-wide memory, not just a process resource), a
    # ServeDaemon owns a listening socket + an event-loop thread + a
    # worker pool, and a DaemonClient holds a live connection a server
    # drain then has to wait out
    "ShmCacheTier", "ServeDaemon", "DaemonClient",
    # the fleet fabric (serve/fleet.py, docs/serving.md): a FleetCache
    # owns every PeerClient connection it was installed with plus its
    # local byte store, and a bare PeerClient holds a live socket a
    # peer daemon's drain then has to wait out — both release with
    # close() and leak sockets exactly like an fd
    "FleetCache", "PeerClient",
    # the write path (write/, docs/write.md): a DeviceFileWriter owns a
    # sink fd AND a compression pool (close() finalizes the footer,
    # abort() releases without one — both are releases), and the
    # resolve_writer factory returns one; plain ParquetFileWriter owns
    # its sink the same way
    "DeviceFileWriter", "ParquetFileWriter", "resolve_writer",
    # the multi-chip mesh (parallel/mesh.py, docs/multichip.md): a
    # DevicePools owns one ThreadPoolExecutor PER mesh device — leaking
    # it leaks k worker threads at once; releases with shutdown()
    "DevicePools",
    # the query subsystem (query/join.py, docs/query.md): a JoinCursor
    # holds TWO live corpus iterators, each pinning open readers of its
    # side's files mid-scan — abandoning one without close() leaks
    # every fd of both corpora for the cursor's lifetime
    "JoinCursor",
}

# the verbs that count as releasing an acquisition (executors release
# with shutdown(), writers also with abort() — the no-footer release —
# everything else with close())
_RELEASERS = ("close", "shutdown", "abort")

# classmethod constructors on an acquirer are acquisitions too:
# ``ShmCacheTier.create(...)`` maps the segment and
# ``ShmCacheTier.attach(...)`` opens the lock-file fd just as surely
# as the bare constructor would
_FACTORY_VERBS = ("create", "attach")


def _is_acquisition(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Name) and f.id == "open":
        return True
    if last_part(f) in _ACQUIRERS:
        return True
    if isinstance(f, ast.Attribute) and f.attr in _FACTORY_VERBS and \
            last_part(f.value) in _ACQUIRERS:
        return True
    if isinstance(f, ast.Attribute) and f.attr == "mmap" and \
            last_part(f.value) == "mmap":
        return True
    return False


def _class_manages(ctx: FileContext, node: ast.AST) -> bool:
    cls = enclosing_class(ctx, node)
    if cls is None:
        return False
    return any(
        isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
        and item.name in ("close", "__exit__", "__del__")
        for item in cls.body
    )


def _name_in(tree: ast.AST, name: str) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id == name for n in ast.walk(tree)
    )


def _scope_body(ctx: FileContext, node: ast.AST):
    fn = enclosing_function(ctx, node)
    return fn if fn is not None else ctx.tree


def _local_is_managed(ctx: FileContext, site: ast.AST, name: str) -> bool:
    scope = _scope_body(ctx, site)
    for node in ast.walk(scope):
        # returned / yielded (possibly wrapped in another expression)
        if isinstance(node, (ast.Return, ast.Yield)) and node.value is not None:
            if _name_in(node.value, name):
                return True
        # ownership transferred onto an object that manages it
        if isinstance(node, ast.Assign) and _name_in(node.value, name):
            if any(isinstance(t, ast.Attribute) for t in node.targets) and \
                    _class_manages(ctx, node):
                return True
        # released on an exception path: name.close()/name.shutdown()
        # inside a finally block or an except handler of some try in
        # this function
        if isinstance(node, ast.Try):
            regions = list(node.finalbody)
            for h in node.handlers:
                regions.extend(h.body)
            for stmt in regions:
                for c in ast.walk(stmt):
                    if isinstance(c, ast.Call) and \
                            isinstance(c.func, ast.Attribute) and \
                            c.func.attr in _RELEASERS and \
                            isinstance(c.func.value, ast.Name) and \
                            c.func.value.id == name:
                        return True
                    # the per-device pool shape (DevicePools.shutdown,
                    # docs/multichip.md): acquisitions collected into a
                    # local container, every member released by
                    # ITERATING it — `for p in pools.values():
                    # p.shutdown()` in a finally/except guard
                    if isinstance(c, ast.For) and \
                            isinstance(c.target, ast.Name) and \
                            _name_in(c.iter, name) and \
                            _releases_loop_var(c):
                        return True
    return False


def _releases_loop_var(loop: ast.For) -> bool:
    """True when the loop body calls a release verb on the loop var."""
    tgt = loop.target.id
    return any(
        isinstance(c, ast.Call)
        and isinstance(c.func, ast.Attribute)
        and c.func.attr in _RELEASERS
        and isinstance(c.func.value, ast.Name)
        and c.func.value.id == tgt
        for stmt in loop.body for c in ast.walk(stmt)
    )


def _classify(ctx: FileContext, call: ast.Call):
    """Walk up from the acquisition; return a violation message or None."""
    child: ast.AST = call
    for anc in ancestors(ctx, call):
        if isinstance(anc, ast.withitem):
            return None
        if isinstance(anc, (ast.Return, ast.Yield)):
            return None
        if isinstance(anc, ast.Lambda):
            # a lambda's body IS its return value: factory lambdas
            # (`lambda: RemoteSource(...)`) transfer ownership to
            # whoever calls them — the scan scheduler's lazy-open shape
            return None
        if isinstance(anc, ast.Attribute) and anc.value is child:
            return ("result used via attribute chain without binding "
                    "(e.g. open(p).read()) — the handle leaks until GC; "
                    "use `with` or pathlib read_bytes/read_text")
        if isinstance(anc, ast.Call) and child is not anc.func:
            return None  # argument to another call: ownership transferred
        if isinstance(anc, ast.Assign):
            for t in anc.targets:
                if isinstance(t, ast.Attribute):
                    if _class_manages(ctx, anc):
                        return None
                    return ("stored on an attribute of a class with no "
                            "close()/__exit__ — nothing ever releases it")
                if isinstance(t, ast.Name):
                    if _local_is_managed(ctx, anc, t.id):
                        return None
                    return (f"bound to `{t.id}` but no exception path "
                            "releases it — use `with`, or close()/"
                            "shutdown() it in a finally/except guard")
                # the per-device pool shape: acquisition stored INTO a
                # container (`pools[dev] = ThreadPoolExecutor(...)`) —
                # the container must be managed like the handle itself
                if isinstance(t, ast.Subscript):
                    base = t.value
                    if isinstance(base, ast.Name):
                        if _local_is_managed(ctx, anc, base.id):
                            return None
                        return (f"stored into container `{base.id}` but "
                                "no exception path releases its members "
                                "— iterate it and close()/shutdown() "
                                "each in a finally/except guard")
                    if isinstance(base, ast.Attribute):
                        if _class_manages(ctx, anc):
                            return None
                        return ("stored into a container attribute of a "
                                "class with no close()/__exit__ — "
                                "nothing ever releases its members")
            return None
        if isinstance(anc, ast.Expr):
            return "result discarded — the handle leaks immediately"
        if isinstance(anc, ast.For) and anc.iter is child:
            return ("iterated directly (for ... in open(p)) — the handle "
                    "leaks until GC; use `with`")
        if isinstance(anc, ast.stmt):
            return None  # some other statement shape: give it the benefit
        child = anc
    return None


def check(ctx: FileContext, project=None):
    if not ctx.in_scope("FL-RES", True):
        return
    for node in ctx.nodes:
        if isinstance(node, ast.Call) and _is_acquisition(node):
            msg = _classify(ctx, node)
            if msg is not None:
                what = last_part(node.func) or "open"
                yield (node.lineno, "FL-RES001", f"{what}(...) {msg}")
