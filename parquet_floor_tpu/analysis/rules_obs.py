"""FL-OBS — metric-name registry guard for the observability layer.

``utils.trace`` keeps the central registry of every metric the package
emits (:class:`parquet_floor_tpu.utils.trace.names`: counters, gauges,
decisions, span stages — the table in ``docs/observability.md``).  A
typo'd name literal (``trace.count("scan.bytes_raed", n)``) would not
fail anything at runtime: it silently splits one metric into two and
every dashboard/report built on the real name goes quietly wrong.

**FL-OBS001** fires when a call to ``trace.count`` / ``trace.gauge_max``
/ ``trace.decision`` / ``trace.span`` / ``trace.add`` /
``trace.observe`` (or the same methods on a ``Tracer`` object —
``tracer.…`` / ``self._tracer.…``) passes a string *literal* name
that is not registered for that kind in ``trace.names``.  Dynamic
names (variables, f-strings) are not checked — the rule guards the
common literal case, not reflection.

Scope: package code (``parquet_floor_tpu/``) except ``utils/trace.py``
itself (the registry's home, and the one module allowed to manipulate
internals).  Tests and scripts may emit synthetic names freely; fixtures
opt in via ``# floorlint: scope=FL-OBS``.
"""

from __future__ import annotations

import ast
from typing import Iterator, Tuple

from ..utils.trace import names as _names
from .core import FileContext, dotted

RULES = [
    ("FL-OBS001",
     "trace metric/decision/span name literals outside utils/trace.py "
     "must come from the trace.names registry"),
]

# call attribute → (kind label, registered set).  span/add share the
# stage namespace: add() is span accumulation without the timer;
# observe() feeds the log-bucketed latency histograms (PR 14).
_KINDS = {
    "count": ("counter", _names.COUNTERS),
    "gauge_max": ("gauge", _names.GAUGES),
    "decision": ("decision", _names.DECISIONS),
    "span": ("span stage", _names.SPANS),
    "add": ("span stage", _names.SPANS),
    "observe": ("histogram", _names.HISTOGRAMS),
}

# receivers that mean "the trace module or a Tracer object"
_RECEIVERS = {"trace", "tracer", "_tracer"}


def check(ctx: FileContext,
          project=None) -> Iterator[Tuple[int, str, str]]:
    in_package = (
        ctx.under("parquet_floor_tpu")
        and not ctx.is_module("utils/trace.py")
    )
    if not ctx.in_scope("FL-OBS", in_package):
        return
    for node in ctx.nodes:
        if not isinstance(node, ast.Call) or not node.args:
            continue
        path = dotted(node.func)
        if path is None:
            continue
        parts = path.split(".")
        if len(parts) < 2 or parts[-1] not in _KINDS:
            continue
        if parts[-2] not in _RECEIVERS:
            continue
        checks = [(node.args[0], _KINDS[parts[-1]])]
        if parts[-1] == "span":
            # span(..., observe="name") records into a histogram on
            # exit: that literal obeys the registry like any other
            for kw in node.keywords:
                if kw.arg == "observe":
                    checks.append(
                        (kw.value, ("histogram", _names.HISTOGRAMS))
                    )
        for arg, (kind, registered) in checks:
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue  # dynamic name: out of the rule's reach
            if arg.value not in registered:
                yield (
                    node.lineno,
                    "FL-OBS001",
                    f"unregistered {kind} name {arg.value!r} — register "
                    "it in trace.names (and docs/observability.md) or "
                    "fix the typo",
                )
