"""CLI: ``python -m parquet_floor_tpu.analysis [paths ...]``.

Exit status: 0 clean, 1 violations, 2 usage error.  ``--format=text``
(default) prints ``file:line: RULE-ID message`` — the same shape
scripts/lint.py emits, so editors and CI parse both identically.
``--format=json`` emits one JSON document (rule id, path, line,
message, call chain per violation, plus run totals) for CI dashboards
and editor integrations; ``scripts/check.sh`` keeps gating on the text
form.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from . import ALL_RULES, load_baseline, run, write_baseline

DEFAULT_TARGETS = ("parquet_floor_tpu", "tests", "scripts")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m parquet_floor_tpu.analysis",
        description="floorlint: project-invariant static analysis",
    )
    ap.add_argument("paths", nargs="*",
                    help="files or directories (default: "
                         + " ".join(DEFAULT_TARGETS) + ", where present)")
    ap.add_argument("--baseline", type=pathlib.Path,
                    default=pathlib.Path("floorlint.baseline"),
                    help="baseline file of accepted fingerprints "
                         "(default: ./floorlint.baseline when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline file")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept every current violation into --baseline "
                         "and exit 0")
    ap.add_argument("--update-baseline", action="store_true",
                    help="regenerate --baseline in the current "
                         "(path:RULE:span) fingerprint format: violations "
                         "the OLD baseline accepted — legacy message-keyed "
                         "entries included — are rewritten as span "
                         "fingerprints; everything else still reports")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text",
                    help="output format (json: machine-readable findings "
                         "with call chains; sarif: SARIF 2.1.0 for CI "
                         "inline annotation)")
    ap.add_argument("--cache", nargs="?", const=".floorlint_cache",
                    default=None, metavar="DIR",
                    help="incremental cache dir (default when the flag is "
                         "given bare: .floorlint_cache); warm runs "
                         "re-analyze only changed files")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, doc in ALL_RULES:
            print(f"{rule}  {doc}")
        return 0

    paths = args.paths or [t for t in DEFAULT_TARGETS
                           if pathlib.Path(t).exists()]
    if not paths:
        ap.error("no paths given and no default targets found")

    baseline = None if args.no_baseline else load_baseline(args.baseline)
    cache = None
    if args.cache is not None:
        from .cache import LintCache

        cache = LintCache(args.cache)
    result = run(paths, baseline=baseline, cache=cache)

    if args.write_baseline:
        write_baseline(args.baseline, result.violations)
        print(f"floorlint: wrote {len(result.violations)} fingerprint(s) "
              f"to {args.baseline}")
        return 0
    if args.update_baseline:
        # keep exactly what the old baseline accepted (now re-keyed to
        # span fingerprints), drop stale entries, leave new violations
        # reporting — regeneration must not silently bless them
        accepted = [v for v in result.all_kept if v not in result.violations]
        write_baseline(args.baseline, accepted)
        print(f"floorlint: rewrote {len(accepted)} fingerprint(s) to "
              f"{args.baseline} (span format)")

    if args.format == "sarif":
        from .sarif import to_sarif

        syntax_rule = ("FL-SYNTAX", "file does not parse")
        print(json.dumps(to_sarif(result, list(ALL_RULES) + [syntax_rule]),
                         indent=1))
        return 1 if result.violations else 0

    if args.format == "json":
        print(json.dumps({
            "violations": [v.to_dict() for v in result.violations],
            "files": result.files,
            "suppressed": result.suppressed,
            "baselined": result.baselined,
            "stale_baseline": result.stale_baseline,
            "ok": result.ok,
        }, indent=1))
        return 1 if result.violations else 0

    for v in result.violations:
        print(v.render())
    extras = []
    if result.suppressed:
        extras.append(f"{result.suppressed} suppressed")
    if result.baselined:
        extras.append(f"{result.baselined} baselined")
    if result.stale_baseline:
        extras.append(f"{result.stale_baseline} STALE baseline entr(y/ies) "
                      "— prune the baseline")
    suffix = f" ({', '.join(extras)})" if extras else ""
    print(f"floorlint: {len(result.violations)} problem(s) in "
          f"{result.files} file(s){suffix}")
    return 1 if result.violations else 0


if __name__ == "__main__":
    sys.exit(main())
