"""FL-RACE — RacerD-style lockset race detection for the serving fabric.

The FL-LOCK family (PR 10) checks lock *hygiene*: with-managed
acquires, no blocking under a lock, consistent ordering.  It never
answers the question that actually bites a fleet under load: *is this
shared field ever touched without its guard?*  These rules infer a
per-field guard from how the code itself uses its locks, then flag the
accesses that escape it:

* **FL-RACE001** — a class field whose writes are guarded by one
  ``self``-attached lock (``with self._lock: self.field = ...`` on >= 2
  distinct sites, or on one site inside a method reachable from a
  thread entry point) acquires that lock as its **inferred guard**;
  any read or write of the field outside an acquisition of the guard
  is flagged, with the thread-entry call chain in the message when the
  accessing method is thread-reachable.
* **FL-RACE002** — check-then-act: an ``if`` whose test *reads* a
  guarded field and whose branch *writes* it, without the guard held
  across the whole statement.  Taking the lock only around the write
  (or only around the read) leaves the classic lost-update window —
  the sequence must be atomic, not its halves.

**Thread entries** are inferred from the spawn shapes the package
uses: ``Thread(target=fn)``, ``pool.submit(fn, ...)``,
``loop.run_in_executor(pool, fn, ...)``, ``asyncio.to_thread(fn)``,
``start_server(handler)`` and ``call_soon_threadsafe(fn)`` — each
resolved through the project call graph, then closed over
:data:`~parquet_floor_tpu.analysis.project.CALL_DEPTH` hops.

**Lock context is inter-procedural** in the suppressing direction: a
helper whose every *resolved* call site sits inside ``with
self._lock`` is analyzed as holding that lock (the ``_locked``-helper
idiom), so moving guarded code into a private method does not
fabricate findings.

**Blessed escapes** (all pinned by fixtures):

* ``__init__``-only writes — construction happens before publication;
* assign-once-after-init — a field with at most ONE post-init write
  site is an immutable-after-publish value (the epoch-fenced
  membership-snapshot pattern): the publish is atomic in CPython and
  readers see either the old or the new snapshot, never a torn one;
* ``# floorlint: unguarded=<why>`` on the field's write (or the line
  above) — a justified opt-out, e.g. a field owned by one event-loop
  thread; every live-tree use gets a rationale row in
  ``docs/static_analysis.md``'s suppression table.

Blind spots (documented, deliberate): accesses through receivers other
than ``self`` (``other._field``), fields of nested functions, guard
locks held via bare ``acquire()`` (FL-LOCK001 forces ``with`` anyway),
module-global guards, and call sites the graph cannot resolve (a
helper with one unresolved caller loses its inherited lock context —
under-approximate both ways).

Scope: the concurrency-bearing subtrees — ``serve/``, ``io/``,
``scan/``, ``tpu/`` and ``utils/trace.py``.  Fixtures opt in via
``# floorlint: scope=FL-RACE``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import FileContext, ancestors, last_part
from .project import CALL_DEPTH, LockId, Project, short

RULES = [
    ("FL-RACE001",
     "a lock-guarded class field (written under `with self._lock` on >=2 "
     "sites, or once in a thread-reachable method) must never be read or "
     "written outside an acquisition of its inferred guard"),
    ("FL-RACE002",
     "check-then-act on a guarded field must hold the guard across the "
     "whole read-branch-write sequence, not drop it between the check "
     "and the act"),
]

_UNGUARDED = re.compile(r"#\s*floorlint:\s*unguarded=\s*(\S[^#]*)")

#: spawn-shape attribute calls whose N-th positional argument is the
#: callable that runs on another thread / the event loop
_SPAWN_ARG_INDEX = {
    "submit": 0,
    "run_in_executor": 1,
    "to_thread": 0,
    "start_server": 0,
    "call_soon_threadsafe": 0,
}


def _in_scope(ctx: FileContext) -> bool:
    default = (
        ctx.under("parquet_floor_tpu", "serve")
        or ctx.under("parquet_floor_tpu", "io")
        or ctx.under("parquet_floor_tpu", "scan")
        or ctx.under("parquet_floor_tpu", "tpu")
        or ctx.is_module("utils/trace.py")
    )
    return ctx.in_scope("FL-RACE", default)


def _walk_own(root: ast.AST):
    """Walk a function body WITHOUT descending into nested defs or
    lambdas — their bodies run on their own schedule, not inline."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _lexical_locks(project: Project, ctx: FileContext, info,
                   node: ast.AST, fn_node: ast.AST) -> Set[tuple]:
    """Statically-known locks held around ``node`` inside ``fn_node``
    (enclosing ``with`` regions, resolved through the lock registry)."""
    held: Set[tuple] = set()
    for anc in ancestors(ctx, node):
        if anc is fn_node:
            break
        if isinstance(anc, ast.With):
            for item in anc.items:
                lk = project.lock_id(info, ctx, item.context_expr)
                if lk is not None:
                    held.add(tuple(lk))
    return held


# -- thread-entry inference ---------------------------------------------------


def thread_roots(project: Project) -> Dict[str, str]:
    """Functions handed to a spawn shape anywhere in the project:
    ``qual -> spawn label`` (memoized on the project)."""
    cached = getattr(project, "_thread_roots_cache", None)
    if cached is not None:
        return cached
    roots: Dict[str, str] = {}
    for info in project.functions.values():
        partials = project.partials_of(info)
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            ref = None
            how = None
            name = last_part(node.func)
            if name == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        ref, how = kw.value, "Thread(target=)"
            elif name in _SPAWN_ARG_INDEX:
                i = _SPAWN_ARG_INDEX[name]
                if len(node.args) > i:
                    ref, how = node.args[i], f".{name}()"
            if ref is None:
                continue
            qual = project._resolve_ref(info, ref, partials)
            if qual is not None and qual in project.functions:
                roots.setdefault(qual, how)
    project._thread_roots_cache = roots
    return roots


def thread_reach(project: Project) -> Dict[str, Tuple[str, Tuple[str, ...]]]:
    """Every function reachable from a thread entry:
    ``qual -> (spawn label, chain from the entry)``."""
    cached = getattr(project, "_thread_reach_cache", None)
    if cached is not None:
        return cached
    reach: Dict[str, Tuple[str, Tuple[str, ...]]] = {}
    for qual, how in thread_roots(project).items():
        info = project.functions[qual]
        reach.setdefault(qual, (how, (short(qual),)))
        for callee, chain, _line in project.walk_calls(info, CALL_DEPTH):
            reach.setdefault(callee.qual, (how, chain))
    project._thread_reach_cache = reach
    return reach


# -- inter-procedural lock context -------------------------------------------


def _inherited_locks(project: Project) -> Dict[str, frozenset]:
    """Locks provably held on EVERY resolved call path into each
    function (intersection over call sites, two bounded rounds).  Used
    only to SUPPRESS findings — the ``_locked``-helper idiom; a single
    lock-free call site clears the context."""
    cached = getattr(project, "_inherited_locks_cache", None)
    if cached is not None:
        return cached
    sites: Dict[str, List[Tuple[str, frozenset]]] = {}
    for info in project.functions.values():
        partials = project.partials_of(info)
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            qual = project.resolve_call(info, node, partials)
            if qual is None or qual == info.qual:
                continue
            held = frozenset(_lexical_locks(
                project, info.ctx, info, node, info.node
            ))
            sites.setdefault(qual, []).append((info.qual, held))
    inherited: Dict[str, frozenset] = {
        q: frozenset() for q in project.functions
    }
    # Jacobi iteration to a fixpoint: one round propagates the context
    # one call-hop deeper, so helper chains (`put -> _insert_locked ->
    # _promote_locked -> _evict_locked`) need as many rounds as they
    # are deep.  Locked-helper chains are short; the bound is a
    # terminator for pathological (cyclic) shapes, not a budget.
    for _round in range(8):
        nxt: Dict[str, frozenset] = {}
        for qual, callers in sites.items():
            acc: Optional[frozenset] = None
            for caller_qual, held in callers:
                eff = held | inherited.get(caller_qual, frozenset())
                acc = eff if acc is None else (acc & eff)
            nxt[qual] = acc or frozenset()
        if all(inherited.get(q) == v for q, v in nxt.items()):
            break
        inherited.update(nxt)
    project._inherited_locks_cache = inherited
    return inherited


# -- per-class access model ---------------------------------------------------


class _Access:
    __slots__ = ("ctx", "line", "write", "locks", "method_qual",
                 "method_name", "in_init", "node")

    def __init__(self, ctx, line, write, locks, method_qual,
                 method_name, node):
        self.ctx = ctx
        self.line = line
        self.write = write
        self.locks = locks
        self.method_qual = method_qual
        self.method_name = method_name
        self.in_init = method_name == "__init__"
        self.node = node


#: method names that mutate their receiver in place — a
#: ``self.field.add(x)`` is a WRITE of the field's state, exactly like
#: ``self.field[k] = x`` (dicts/sets/lists are the dominant shared-state
#: shape in the serving fabric)
_MUTATORS = {
    "append", "appendleft", "add", "clear", "discard", "extend",
    "insert", "pop", "popitem", "popleft", "remove", "setdefault",
    "update", "sort",
}


def _access_kind(ctx: FileContext, node: ast.Attribute) -> Optional[bool]:
    """True = write, False = read, None = not a data access (a method
    invocation).  Writes include direct stores/deletes, container-slot
    stores (``self.f[k] = v``, ``del self.f[k]``) and in-place mutator
    calls (``self.f.add(x)``)."""
    parent = ctx.parents.get(node)
    if isinstance(parent, ast.Call) and parent.func is node:
        return None  # a method/callable invocation, not data
    if isinstance(node.ctx, (ast.Store, ast.Del)):
        return True
    if isinstance(parent, ast.Subscript) and parent.value is node and \
            isinstance(parent.ctx, (ast.Store, ast.Del)):
        return True
    if isinstance(parent, ast.Attribute) and parent.value is node and \
            parent.attr in _MUTATORS:
        gp = ctx.parents.get(parent)
        if isinstance(gp, ast.Call) and gp.func is parent:
            return True
    return False


def _class_accesses(project: Project, ctx: FileContext, cls,
                    inherited) -> Dict[str, List[_Access]]:
    """Every ``self.<field>`` data access in the class's own methods,
    with the effective lockset (lexical + inherited) at each site."""
    fields: Dict[str, List[_Access]] = {}
    for mname, info in cls.methods.items():
        inh = inherited.get(info.qual, frozenset())
        for node in _walk_own(info.node):
            if not isinstance(node, ast.Attribute):
                continue
            if not (isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                continue
            attr = node.attr
            if attr in cls.methods or attr in cls.lock_attrs:
                continue
            write = _access_kind(ctx, node)
            if write is None:
                continue
            locks = frozenset(_lexical_locks(
                project, ctx, info, node, info.node
            )) | inh
            fields.setdefault(attr, []).append(_Access(
                ctx, node.lineno, write, locks, info.qual, mname, node
            ))
    return fields


def _blessed_fields(ctx: FileContext, cls_node: ast.ClassDef
                    ) -> Dict[str, str]:
    """Fields opted out via ``# floorlint: unguarded=<why>`` on (or the
    line above) a line naming the field inside the class body."""
    blessed: Dict[str, str] = {}
    end = min(cls_node.end_lineno or cls_node.lineno, len(ctx.lines))
    for i in range(cls_node.lineno, end + 1):
        line = ctx.lines[i - 1]
        m = _UNGUARDED.search(line)
        if not m:
            continue
        code = line.split("#", 1)[0]
        if not code.strip() and i < len(ctx.lines):
            code = ctx.lines[i]  # standalone comment blesses next line
        fm = (re.search(r"self\.(\w+)", code)
              or re.match(r"\s*(\w+)\s*[:=]", code))
        if fm:
            blessed[fm.group(1)] = m.group(1).strip()
    return blessed


def _infer_guard(accesses: List[_Access], reach
                 ) -> Optional[Tuple[tuple, int, int]]:
    """The inferred guard for one field: ``(lock, locked_write_sites,
    total_write_sites)`` or None (unguarded / blessed-by-shape)."""
    writes = [a for a in accesses if a.write and not a.in_init]
    if not writes:
        return None  # never mutated after construction
    write_sites = {(a.ctx.rel, a.line) for a in writes}
    if len(write_sites) <= 1:
        return None  # assign-once-after-init: immutable-after-publish
    counts: Dict[tuple, Set[tuple]] = {}
    for a in writes:
        for lk in a.locks:
            if lk[0] == "attr":
                counts.setdefault(lk, set()).add((a.ctx.rel, a.line))
    if not counts:
        return None
    guard = max(counts, key=lambda lk: len(counts[lk]))
    n_sites = len(counts[guard])
    if n_sites >= 2:
        return guard, n_sites, len(write_sites)
    for a in writes:
        if guard in a.locks and a.method_qual in reach:
            return guard, n_sites, len(write_sites)
    return None


# -- the project-wide pass ----------------------------------------------------


def race_model(project: Project):
    """Findings per file plus the inferred-guard map (for tests):
    ``(findings: {ctx: [(line, rule, msg, chain)]},
    guards: {cls_qual: {field: LockId}})``.  Computed once per project."""
    cached = getattr(project, "_race_cache", None)
    if cached is not None:
        return cached
    findings: Dict[object, List[tuple]] = {}
    guards_out: Dict[str, Dict[str, LockId]] = {}
    reach = thread_reach(project)
    inherited = _inherited_locks(project)
    for cls in project.classes.values():
        ctx = project.by_module.get(cls.module)
        if ctx is None or not _in_scope(ctx):
            continue
        fields = _class_accesses(project, ctx, cls, inherited)
        blessed = _blessed_fields(ctx, cls.node)
        guards: Dict[str, tuple] = {}
        for field, accs in fields.items():
            if field in blessed:
                continue
            g = _infer_guard(accs, reach)
            if g is not None:
                guards[field] = g
                guards_out.setdefault(cls.qual, {})[field] = LockId(g[0])
        out = findings.setdefault(ctx, [])
        _emit_race001(project, cls, fields, guards, reach, out)
        _emit_race002(project, ctx, cls, guards, inherited, out)
        _emit_race002_writer(ctx, cls, fields, guards, blessed, out)
    project._race_cache = (findings, guards_out)
    return project._race_cache


def _emit_race001(project, cls, fields, guards, reach, out) -> None:
    cname = cls.qual.rsplit(".", 1)[-1]
    for field, (guard, n_locked, n_writes) in guards.items():
        render = LockId(guard).render()
        seen_lines: Set[int] = set()
        for a in fields[field]:
            if a.in_init or guard in a.locks or a.line in seen_lines:
                continue
            seen_lines.add(a.line)
            verb = "write to" if a.write else "read of"
            msg = (f"{verb} {cname}.{field} without its inferred guard "
                   f"{render} (the field is written under {render} at "
                   f"{n_locked} of {n_writes} sites)")
            chain: Tuple[str, ...] = ()
            hit = reach.get(a.method_qual)
            if hit is not None:
                how, chain = hit
                msg += (f" — reachable from thread entry {how} via "
                        f"{' -> '.join(chain)}")
            msg += ("; hold the guard, or bless the field with "
                    "`# floorlint: unguarded=<why>`")
            out.append((a.line, "FL-RACE001", msg, chain))


def _emit_race002(project, ctx, cls, guards, inherited, out) -> None:
    cname = cls.qual.rsplit(".", 1)[-1]
    for mname, info in cls.methods.items():
        inh = inherited.get(info.qual, frozenset())
        for node in _walk_own(info.node):
            if not isinstance(node, ast.If):
                continue
            test_reads = {
                sub.attr for sub in ast.walk(node.test)
                if isinstance(sub, ast.Attribute)
                and isinstance(sub.value, ast.Name)
                and sub.value.id == "self"
                and isinstance(sub.ctx, ast.Load)
                and sub.attr in guards
            }
            if not test_reads:
                continue
            held = frozenset(_lexical_locks(
                project, ctx, info, node, info.node
            )) | inh
            for field in sorted(test_reads):
                guard = guards[field][0]
                if guard in held:
                    continue  # the whole check-then-act is atomic
                wrote = any(
                    isinstance(sub, ast.Attribute)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "self"
                    and sub.attr == field
                    and _access_kind(ctx, sub) is True
                    for stmt in node.body + node.orelse
                    for sub in ast.walk(stmt)
                )
                if not wrote:
                    continue
                render = LockId(guard).render()
                out.append((
                    node.lineno, "FL-RACE002",
                    f"check-then-act on {cname}.{field}: the test reads "
                    f"it and the branch writes it, but {render} is not "
                    "held across the whole sequence — the window between "
                    "check and act loses updates; take the guard around "
                    "the if, not just the write", (),
                ))


def _under_test(ctx: FileContext, node: ast.AST) -> bool:
    """Is ``node`` inside the condition of an ``if``/``while``/ternary
    — i.e. does this read DECIDE something?"""
    child, p = node, ctx.parents.get(node)
    while p is not None and not isinstance(
            p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        if isinstance(p, (ast.If, ast.While, ast.IfExp)) \
                and child is p.test:
            return True
        child, p = p, ctx.parents.get(p)
    return False


def _emit_race002_writer(ctx, cls, fields, guards, blessed, out) -> None:
    """The writer-side check-then-act arm: a function that WRITES field
    F under lock L, but whose decision to write rests on a read of F
    taken OUTSIDE L — and the guarded region never re-checks.  Applies
    precisely to the fields the assign-once escape blesses (guarded
    fields' unlocked reads are FL-RACE001's domain): the snapshot
    pattern makes *readers* safe, but the writer's own monotonicity /
    existence check must still be atomic with the write.  A re-check of
    F under L (double-checked locking) makes the sequence atomic and is
    never flagged."""
    cname = cls.qual.rsplit(".", 1)[-1]
    for field, accs in fields.items():
        if field in blessed or field in guards:
            continue
        by_method: Dict[str, List[_Access]] = {}
        for a in accs:
            if not a.in_init:
                by_method.setdefault(a.method_qual, []).append(a)
        for m_accs in by_method.values():
            locks = {
                lk for a in m_accs if a.write
                for lk in a.locks if lk[0] == "attr"
            }
            for guard in sorted(locks):
                w_lines = [a.line for a in m_accs
                           if a.write and guard in a.locks]
                rechecks = [a.line for a in m_accs
                            if not a.write and guard in a.locks]
                for a in m_accs:
                    if a.write or guard in a.locks:
                        continue
                    if not _under_test(ctx, a.node):
                        continue
                    later = [w for w in w_lines if w > a.line]
                    if not later:
                        continue
                    if any(a.line < r <= max(later) for r in rechecks):
                        continue  # double-checked: re-validated under L
                    render = LockId(guard).render()
                    out.append((
                        a.line, "FL-RACE002",
                        f"check-then-act on {cname}.{field}: this read "
                        f"decides a write performed under {render} at "
                        f"line {later[0]}, but the check runs outside "
                        "the lock and the guarded region never "
                        "re-checks — two concurrent callers can both "
                        "pass and commit in either order; take the "
                        "guard around the check, or re-validate under "
                        "it", (),
                    ))


def check(ctx: FileContext, project: Project):
    if not _in_scope(ctx):
        return
    findings, _guards = race_model(project)
    yield from findings.get(ctx, [])
