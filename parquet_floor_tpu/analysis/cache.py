"""floorlint incremental cache — warm runs re-analyze only what changed.

The engine is a PROJECT-wide pass (call graph, inherited locks, thread
reachability), so per-file verdicts are only reusable when the whole
project is unchanged — a one-file edit can shift a cross-file chain.
The cache is therefore two honest tiers:

* **context tier** — each file's parsed :class:`FileContext` (AST,
  parent map, directives) pickled under ``<root>/ctx/``, keyed by
  ``(path, mtime_ns, size)`` plus the analyzer stamp.  A warm run
  re-parses ONLY changed files; rules still run project-wide, so
  graph-aware verdicts stay sound after any edit.
* **run tier** — the full :class:`RunResult` pickled under
  ``<root>/run/``, keyed by a signature over EVERY file key, the
  analyzer stamp and the baseline.  The no-change warm run (the common
  CI case) reduces to a directory stat walk plus one unpickle.

The **analyzer stamp** folds in ``analysis/*.py`` (mtime/size) and the
interpreter version, so editing any rule — or upgrading Python —
invalidates everything.

Every read is wrapped: a missing, truncated, or corrupted artifact is
treated as a miss and the engine falls back to a full pass (pinned by
``test_floorlint.py::test_cache_corruption_falls_back``).  Writes are
atomic (tmp + ``os.replace``) and best-effort — a read-only cache dir
degrades to uncached, never to an error.
"""

from __future__ import annotations

import hashlib
import os
import pathlib
import pickle
import sys
import tempfile
from typing import Optional, Sequence

#: bump to orphan every artifact written by an incompatible layout
_LAYOUT = 1


def _sha1(text: str) -> str:
    return hashlib.sha1(text.encode()).hexdigest()


class LintCache:
    """Artifact store rooted at ``.floorlint_cache/`` (or any dir)."""

    def __init__(self, root) -> None:
        self.root = pathlib.Path(root)
        self._stamp: Optional[str] = None

    # -- keys ----------------------------------------------------------------

    def stamp(self) -> str:
        """Fingerprint of the analyzer itself (lazy, computed once)."""
        if self._stamp is None:
            pkg = pathlib.Path(__file__).parent
            parts = [f"layout={_LAYOUT}", f"py={sys.version_info[:3]}"]
            for f in sorted(pkg.glob("*.py")):
                st = f.stat()
                parts.append(f"{f.name}:{st.st_mtime_ns}:{st.st_size}")
            self._stamp = _sha1("|".join(parts))
        return self._stamp

    @staticmethod
    def file_key(path: pathlib.Path) -> tuple:
        st = path.stat()
        return (str(path), st.st_mtime_ns, st.st_size)

    def run_signature(self, files: Sequence[pathlib.Path],
                      baseline=None) -> str:
        """One hash over the whole input: every file key, the analyzer
        stamp, and the baseline entries."""
        parts = [self.stamp()]
        parts.extend(repr(self.file_key(f)) for f in files)
        if baseline:
            parts.append(repr(sorted(baseline.items())))
        return _sha1("|".join(parts))

    # -- raw artifact I/O ----------------------------------------------------

    def _load(self, rel: str):
        try:
            with open(self.root / rel, "rb") as fh:
                return pickle.load(fh)
        except Exception:
            return None  # missing/corrupt/incompatible: a miss, never an error

    def _store(self, rel: str, payload) -> None:
        try:
            target = self.root / rel
            target.parent.mkdir(parents=True, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=str(target.parent), suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as fh:
                    pickle.dump(payload, fh, protocol=pickle.HIGHEST_PROTOCOL)
                os.replace(tmp, target)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except Exception:
            pass  # best-effort: a read-only cache degrades to uncached

    # -- context tier --------------------------------------------------------

    def load_context(self, path: pathlib.Path):
        """The file's cached FileContext, or None when the file (or the
        analyzer) changed since it was stored."""
        payload = self._load(f"ctx/{_sha1(str(path))}.pkl")
        if not isinstance(payload, dict):
            return None
        try:
            fresh = payload["key"] == self.file_key(path) \
                and payload["stamp"] == self.stamp()
        except Exception:
            return None
        return payload["ctx"] if fresh else None

    def store_context(self, path: pathlib.Path, ctx) -> None:
        self._store(f"ctx/{_sha1(str(path))}.pkl", {
            "key": self.file_key(path), "stamp": self.stamp(), "ctx": ctx,
        })

    # -- run tier ------------------------------------------------------------

    def load_run(self, signature: str):
        payload = self._load(f"run/{signature}.pkl")
        if not isinstance(payload, dict):
            return None
        return payload.get("result")

    def store_run(self, signature: str, result) -> None:
        self._store(f"run/{signature}.pkl", {"result": result})
