"""floorlint project pass — whole-package symbol table + call graph.

PR 2's analyzer was strictly per-file: every rule saw one ``ast`` tree
and nothing else, so a helper *called from* a jitted function (FL-TPU)
or a blocking call buried one frame below a lock (FL-LOCK) was
invisible.  This module parses the project ONCE and builds the three
indexes the cross-file rules traverse:

* a **symbol table** — module-level functions (``pkg.mod.fn``), classes
  (``pkg.mod.Cls``) and their methods (``pkg.mod.Cls.fn``), plus each
  file's import-alias map (``from ..io.source import FileSource`` makes
  the local name ``FileSource`` resolve to ``parquet_floor_tpu.io.
  source.FileSource``);
* a **call graph** — for every function body, the calls that resolve to
  a known project function, via the same shapes FL-TPU already
  recognizes: bare names (local import aliases and same-module
  functions), ``self.method()`` (self-type from the enclosing class,
  single-level base lookup in-package), ``self.attr.method()`` when the
  attribute's type was inferred from a ``self.attr = KnownClass(...)``
  assignment, ``mod.fn()`` through module aliases, ``KnownClass(...)``
  (an edge into ``__init__``), and ``functools.partial`` targets (both
  ``h = partial(fn, ...); h()`` locals and direct
  ``partial(fn, ...)()`` calls);
* a **lock registry** — every attribute or module global bound to
  ``threading.Lock/RLock/Condition/Semaphore/BoundedSemaphore`` (the
  FL-LOCK rules' notion of "statically-known lock").

Known blind spots (deliberate — documented in
``docs/static_analysis.md``): dynamic dispatch (a receiver whose type
the two inference shapes above cannot pin), callables passed as
arguments, monkey-patching, and ``getattr`` strings.  Rules built on
the graph are therefore *under*-approximate: a resolved edge is real,
an unresolved call is silently not followed.

Traversal is bounded: :meth:`Project.walk_calls` follows edges to
``depth`` hops (default :data:`CALL_DEPTH`) and yields each reached
function once with the call chain that got there — the bound keeps the
whole-project pass linear and the messages readable.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

#: default bound on cross-function traversal (hops below the root body)
CALL_DEPTH = 3

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}

_PKG = "parquet_floor_tpu"


def _module_name(rel_parts: Tuple[str, ...]) -> str:
    """Dotted module name for one analyzed file.  Files under the
    package get their real import path; everything else (tests,
    scripts, fixtures) gets a path-derived pseudo-module so same-run
    cross-file resolution still works between explicit files."""
    parts = list(rel_parts)
    if _PKG in parts:
        parts = parts[parts.index(_PKG):]
    if parts and parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or "<root>"


def _last(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _ann_name(node) -> Optional[str]:
    """The class NAME an annotation expression pins, or None: handles
    ``Foo``, ``mod.Foo``, ``"Foo"`` (string annotations, including the
    ``from __future__ import annotations`` form every package module
    uses), ``Optional[Foo]`` / ``Final[Foo]`` / ``Annotated[Foo, ...]``
    and ``Foo | None`` unions.  Anything more exotic (real unions of two
    classes, generics over type vars) stays a documented blind spot —
    a wrong pin would fabricate call edges."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            return _ann_name(ast.parse(node.value, mode="eval").body)
        except SyntaxError:
            return None
    if isinstance(node, (ast.Name, ast.Attribute)):
        return _last(node)
    if isinstance(node, ast.Subscript):
        if _last(node.value) in ("Optional", "Final", "Annotated"):
            sl = node.slice
            if isinstance(sl, ast.Tuple) and sl.elts:
                sl = sl.elts[0]
            return _ann_name(sl)
        return None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        names = [_ann_name(node.left), _ann_name(node.right)]
        real = [n for n in names if n and n != "None"]
        return real[0] if len(real) == 1 else None
    return None


class FunctionInfo:
    """One project function: its AST, home file, and resolution scope."""

    __slots__ = ("qual", "node", "ctx", "cls", "module", "_ann")

    def __init__(self, qual: str, node: ast.AST, ctx, module: str,
                 cls: Optional["ClassInfo"]):
        self.qual = qual
        self.node = node
        self.ctx = ctx          # the FileContext the function lives in
        self.module = module
        self.cls = cls
        self._ann = None

    def ann_types(self) -> Dict[str, ast.AST]:
        """Annotated locals of this function: parameter annotations plus
        ``x: Foo`` annotated assignments — name → annotation node.  This
        is how the call graph sees dynamic dispatch through annotated
        receivers (``def f(s: DatasetScanner): s.close()``)."""
        if self._ann is None:
            out: Dict[str, ast.AST] = {}
            a = getattr(self.node, "args", None)
            if a is not None:
                for arg in (
                    list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
                ):
                    if arg.annotation is not None and arg.arg != "self":
                        out[arg.arg] = arg.annotation
            for sub in ast.walk(self.node):
                if isinstance(sub, ast.AnnAssign) and \
                        isinstance(sub.target, ast.Name):
                    out.setdefault(sub.target.id, sub.annotation)
            self._ann = out
        return self._ann


class ClassInfo:
    """One project class: methods, bases, inferred attribute types, and
    the lock attributes its methods bind."""

    __slots__ = ("qual", "node", "module", "methods", "bases",
                 "attr_types", "lock_attrs")

    def __init__(self, qual: str, node: ast.ClassDef, module: str):
        self.qual = qual
        self.node = node
        self.module = module
        self.methods: Dict[str, FunctionInfo] = {}
        self.bases: List[str] = [b for b in map(_last, node.bases) if b]
        self.attr_types: Dict[str, str] = {}   # attr -> class qual
        self.lock_attrs: Dict[str, str] = {}   # attr -> ctor name


class LockId(Tuple[str, str, str]):
    """Identity of one statically-known lock: ``(kind, owner, name)``
    with kind ``attr`` (owner = class qual), ``global`` (owner =
    module), or ``attrname`` (owner = "?" — an attribute whose receiver
    could not be typed but whose NAME is bound to a lock constructor
    somewhere in the project; good enough to *detect* a lock, too weak
    to pair lock IDENTITIES for ordering)."""

    def render(self) -> str:
        kind, owner, name = self
        if kind == "attr":
            return f"{owner.rsplit('.', 1)[-1]}.{name}"
        if kind == "global":
            return f"{owner}.{name}"
        return name


class Project:
    """The shared whole-project pass (module docstring).  Built once per
    :func:`analysis.core.run`; every rule module receives it."""

    def __init__(self, contexts):
        self.contexts = list(contexts)
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.module_of: Dict[object, str] = {}     # FileContext -> module
        self.by_module: Dict[str, object] = {}     # module -> FileContext
        #: per-file import alias map: FileContext -> {local: qual}
        self.aliases: Dict[object, Dict[str, str]] = {}
        #: module globals bound to lock constructors: (module, name) -> ctor
        self.global_locks: Dict[Tuple[str, str], str] = {}
        #: every attribute NAME bound to a lock ctor anywhere: name -> ctor
        self.lock_attr_names: Dict[str, str] = {}
        #: resolved call edges: caller qual -> [(callee qual, lineno)]
        self._edges: Dict[str, List[Tuple[str, int]]] = {}
        for ctx in self.contexts:
            self._index_file(ctx)
        for ctx in self.contexts:
            self._resolve_imports(ctx)
        for ctx in self.contexts:
            self._infer_attr_types(ctx)
        for info in list(self.functions.values()):
            self._edges[info.qual] = list(self._resolve_calls(info))

    # -- pass 1: symbols -----------------------------------------------------

    def _index_file(self, ctx) -> None:
        mod = _module_name(ctx.rel_parts)
        self.module_of[ctx] = mod
        self.by_module.setdefault(mod, ctx)
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{mod}.{node.name}"
                self.functions[qual] = FunctionInfo(qual, node, ctx, mod,
                                                    None)
            elif isinstance(node, ast.ClassDef):
                cqual = f"{mod}.{node.name}"
                cls = ClassInfo(cqual, node, mod)
                self.classes[cqual] = cls
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        fqual = f"{cqual}.{item.name}"
                        fi = FunctionInfo(fqual, item, ctx, mod, cls)
                        cls.methods[item.name] = fi
                        self.functions[fqual] = fi
            elif isinstance(node, ast.Assign):
                self._index_global_assign(mod, node)

    def _index_global_assign(self, mod: str, node: ast.Assign) -> None:
        if not isinstance(node.value, ast.Call):
            return
        ctor = _last(node.value.func)
        if ctor not in _LOCK_CTORS:
            return
        for t in node.targets:
            if isinstance(t, ast.Name):
                self.global_locks[(mod, t.id)] = ctor

    # -- pass 2: imports -----------------------------------------------------

    def _resolve_imports(self, ctx) -> None:
        mod = self.module_of[ctx]
        table: Dict[str, str] = {}
        # the containing package for relative imports: a leaf module's
        # parent — but an __init__.py's module name IS its package
        # (_module_name strips the '__init__' segment), so level-1
        # imports there resolve into the package itself, not above it
        if ctx.rel_parts and ctx.rel_parts[-1] == "__init__.py":
            pkg_parts = mod.split(".")
        else:
            pkg_parts = mod.split(".")[:-1]
        for node in ctx.nodes:
            if isinstance(node, ast.Import):
                for a in node.names:
                    table[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module != \
                    "__future__":
                if node.level:
                    base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    src = ".".join(base + ([node.module]
                                           if node.module else []))
                else:
                    src = node.module or ""
                for a in node.names:
                    if a.name != "*":
                        table[a.asname or a.name] = f"{src}.{a.name}"
        self.aliases[ctx] = table

    # -- pass 3: attribute types --------------------------------------------

    def _infer_attr_types(self, ctx) -> None:
        """``self.attr = KnownClass(...)`` (or ``= threading.Lock()``)
        inside any method types the attribute for the whole class —
        flow-insensitive; a reassignment to an unknown type leaves the
        earlier inference in place (documented blind spot).  ANNOTATIONS
        type attributes too: ``self.attr: KnownClass`` in a method and
        ``attr: KnownClass`` in the class body both pin the attribute,
        covering receivers whose constructor call the two inference
        shapes above cannot see (factory returns, injected
        collaborators)."""
        mod = self.module_of[ctx]
        for node in ctx.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            cls = self.classes[f"{mod}.{node.name}"]
            for item in node.body:
                # class-body annotation: ``attr: KnownClass [= ...]``
                if isinstance(item, ast.AnnAssign) and \
                        isinstance(item.target, ast.Name):
                    self._record_attr(
                        ctx, cls, item.target.id,
                        _ann_name(item.annotation),
                    )
            for sub in ast.walk(node):
                if isinstance(sub, ast.AnnAssign):
                    t = sub.target
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        self._record_attr(
                            ctx, cls, t.attr, _ann_name(sub.annotation)
                        )
                    continue
                if not isinstance(sub, ast.Assign) or \
                        not isinstance(sub.value, ast.Call):
                    continue
                ctor = _last(sub.value.func)
                for t in sub.targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    self._record_attr(ctx, cls, t.attr, ctor)

    def _record_attr(self, ctx, cls: ClassInfo, attr: str,
                     type_name: Optional[str]) -> None:
        if not type_name:
            return
        if type_name in _LOCK_CTORS:
            cls.lock_attrs[attr] = type_name
            self.lock_attr_names.setdefault(attr, type_name)
            return
        cq = self._class_qual(ctx, type_name)
        if cq is not None:
            cls.attr_types.setdefault(attr, cq)

    # -- name resolution -----------------------------------------------------

    def _class_qual(self, ctx, name: Optional[str]) -> Optional[str]:
        if not name:
            return None
        mod = self.module_of[ctx]
        if f"{mod}.{name}" in self.classes:
            return f"{mod}.{name}"
        target = self.aliases.get(ctx, {}).get(name)
        if target in self.classes:
            return target
        return None

    def class_of(self, ctx, node: ast.AST) -> Optional[ClassInfo]:
        """The ClassInfo whose body lexically contains ``node``."""
        for anc in _ancestors(ctx, node):
            if isinstance(anc, ast.ClassDef):
                return self.classes.get(
                    f"{self.module_of[ctx]}.{anc.name}"
                )
        return None

    def function_at(self, ctx, fn_node: ast.AST) -> Optional[FunctionInfo]:
        """The FunctionInfo for a def node (module-level or method)."""
        mod = self.module_of.get(ctx)
        if mod is None:
            return None
        cls = self.class_of(ctx, fn_node)
        name = getattr(fn_node, "name", None)
        qual = (f"{cls.qual}.{name}" if cls is not None
                else f"{mod}.{name}")
        info = self.functions.get(qual)
        if info is not None and info.node is fn_node:
            return info
        return None

    def _method_in(self, cqual: str, name: str,
                   _seen: Optional[Set[str]] = None) -> Optional[str]:
        """Resolve a method by name in a class or (single-level,
        in-package) its bases."""
        seen = _seen or set()
        if cqual in seen:
            return None
        seen.add(cqual)
        cls = self.classes.get(cqual)
        if cls is None:
            return None
        if name in cls.methods:
            return cls.methods[name].qual
        for b in cls.bases:
            bq = self._class_qual(cls_ctx(self, cls), b)
            if bq is not None:
                hit = self._method_in(bq, name, seen)
                if hit is not None:
                    return hit
        return None

    def resolve_call(self, info: FunctionInfo, call: ast.Call,
                     partials: Dict[str, ast.AST]) -> Optional[str]:
        """Qualified name of the project function ``call`` invokes, or
        None when the receiver cannot be pinned (blind spot)."""
        f = call.func
        # partial(fn, ...)(...) applied directly
        if isinstance(f, ast.Call) and _last(f.func) == "partial" \
                and f.args:
            return self._resolve_ref(info, f.args[0], partials)
        return self._resolve_ref(info, f, partials, as_call=True)

    def _resolve_ref(self, info: FunctionInfo, ref: ast.AST,
                     partials: Dict[str, ast.AST],
                     as_call: bool = False) -> Optional[str]:
        ctx, mod = info.ctx, info.module
        if isinstance(ref, ast.Name):
            name = ref.id
            if name in partials:
                return self._resolve_ref(info, partials[name], partials)
            cq = self._class_qual(ctx, name)
            if cq is not None:
                return self._method_in(cq, "__init__")
            if f"{mod}.{name}" in self.functions:
                return f"{mod}.{name}"
            target = self.aliases.get(ctx, {}).get(name)
            if target in self.functions:
                return target
            if target in self.classes:
                return self._method_in(target, "__init__")
            return None
        if not isinstance(ref, ast.Attribute):
            return None
        recv, attr = ref.value, ref.attr
        # typed receiver: ``self``, an annotated local/parameter, a
        # typed ``self.attr`` — or any CHAIN of typed attribute hops
        # (``param.attr.method()``, ``self.a.b.method()``): the closed
        # PR 12 blind spot.  receiver_type walks the chain through the
        # per-class attr_types maps.
        tq = self.receiver_type(info, recv)
        if tq is not None:
            hit = self._method_in(tq, attr)
            if hit is not None:
                return hit
            if not isinstance(recv, ast.Name) or recv.id == "self":
                return None
            # an annotated NAME that resolved to a class without the
            # method still falls through to the module-alias shape
            # below (an alias shadowing would be exotic, a silently
            # dropped mod.fn edge is not)
        # mod.fn(...) through a module alias
        if isinstance(recv, ast.Name):
            target = self.aliases.get(ctx, {}).get(recv.id)
            if target is not None:
                if f"{target}.{attr}" in self.functions:
                    return f"{target}.{attr}"
                if f"{target}.{attr}" in self.classes:
                    return self._method_in(f"{target}.{attr}", "__init__")
        return None

    def receiver_type(self, info: FunctionInfo,
                      expr: ast.AST) -> Optional[str]:
        """Class qual of a receiver expression, walking attribute
        chains through every typing shape the graph knows: ``self``
        (the enclosing class), annotated parameters/locals, and typed
        attributes (``self.attr = KnownClass(...)`` assignments or
        annotations) — applied RECURSIVELY, so
        ``param.attr.sub.method()`` resolves as long as every hop is
        typed.  None on the first untyped hop (under-approximate, like
        the rest of the graph)."""
        if isinstance(expr, ast.Name):
            if expr.id == "self":
                return info.cls.qual if info.cls is not None else None
            ann = info.ann_types().get(expr.id)
            if ann is not None:
                return self._class_qual(info.ctx, _ann_name(ann))
            return None
        if isinstance(expr, ast.Attribute):
            base = self.receiver_type(info, expr.value)
            if base is None:
                return None
            return self._attr_type_in(base, expr.attr)
        return None

    def _attr_type_in(self, cqual: str, attr: str,
                      _seen: Optional[Set[str]] = None) -> Optional[str]:
        """``attr``'s inferred class on ``cqual`` or (single-level,
        in-package) its bases — the attr_types mirror of
        :meth:`_method_in`."""
        seen = _seen or set()
        if cqual in seen:
            return None
        seen.add(cqual)
        cls = self.classes.get(cqual)
        if cls is None:
            return None
        if attr in cls.attr_types:
            return cls.attr_types[attr]
        for b in cls.bases:
            bq = self._class_qual(cls_ctx(self, cls), b)
            if bq is not None:
                hit = self._attr_type_in(bq, attr, seen)
                if hit is not None:
                    return hit
        return None

    # -- call-graph construction --------------------------------------------

    @staticmethod
    def partial_locals(fn_node: ast.AST) -> Dict[str, ast.AST]:
        """``h = functools.partial(target, ...)`` locals in one body:
        name -> the target reference expression."""
        out: Dict[str, ast.AST] = {}
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    _last(node.value.func) == "partial" and \
                    node.value.args:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = node.value.args[0]
        return out

    def partials_of(self, info: FunctionInfo) -> Dict[str, ast.AST]:
        """Memoized :meth:`partial_locals` for an indexed function."""
        cache = self.__dict__.setdefault("_partials_cache", {})
        hit = cache.get(info.qual)
        if hit is None:
            hit = cache[info.qual] = self.partial_locals(info.node)
        return hit

    def _resolve_calls(self, info: FunctionInfo
                       ) -> Iterator[Tuple[str, int]]:
        partials = self.partials_of(info)
        for node in ast.walk(info.node):
            if isinstance(node, ast.Call):
                qual = self.resolve_call(info, node, partials)
                if qual is not None and qual != info.qual:
                    yield qual, node.lineno

    def callees(self, qual: str) -> List[Tuple[str, int]]:
        return self._edges.get(qual, [])

    def walk_calls(self, root: FunctionInfo, depth: int = CALL_DEPTH
                   ) -> List[Tuple[FunctionInfo, Tuple[str, ...], int]]:
        """BFS over resolved call edges from ``root``'s body, bounded to
        ``depth`` hops.  Returns ``(callee info, chain, first_line)``
        tuples where ``chain`` is the function-name path from the root
        to the callee and ``first_line`` is the line IN THE ROOT'S FILE
        of the first hop — where a violation found down the chain is
        reported.  Each function is visited once (shortest chain wins);
        results are memoized per ``(root, depth)`` — several rules
        traverse from the same roots."""
        cache = self.__dict__.setdefault("_walk_cache", {})
        key = (root.qual, depth)
        hit = cache.get(key)
        if hit is None:
            hit = cache[key] = list(self._walk_calls(root, depth))
        return hit

    def _walk_calls(self, root: FunctionInfo, depth: int
                    ) -> Iterator[Tuple[FunctionInfo, Tuple[str, ...], int]]:
        seen: Set[str] = {root.qual}
        frontier: List[Tuple[FunctionInfo, Tuple[str, ...], int]] = []
        for qual, line in self.callees(root.qual):
            if qual not in seen:
                seen.add(qual)
                frontier.append(
                    (self.functions[qual],
                     (short(root.qual), short(qual)), line)
                )
        hops = 1
        while frontier and hops <= depth:
            yield from frontier
            nxt: List[Tuple[FunctionInfo, Tuple[str, ...], int]] = []
            if hops == depth:
                break
            for info, chain, line0 in frontier:
                for qual, _line in self.callees(info.qual):
                    if qual not in seen:
                        seen.add(qual)
                        nxt.append((self.functions[qual],
                                    chain + (short(qual),), line0))
            frontier = nxt
            hops += 1

    # -- lock identity -------------------------------------------------------

    def lock_id(self, info: Optional[FunctionInfo], ctx,
                expr: ast.AST) -> Optional[LockId]:
        """Resolve an expression used as a lock (a ``with`` item or an
        ``.acquire()`` receiver) to a :class:`LockId`, or None when it
        is not a statically-known lock."""
        mod = self.module_of.get(ctx)
        if isinstance(expr, ast.Name):
            ctor = self.global_locks.get((mod, expr.id))
            if ctor is not None:
                return LockId(("global", mod, expr.id))
            return None
        if not isinstance(expr, ast.Attribute):
            return None
        recv, attr = expr.value, expr.attr
        if isinstance(recv, ast.Name) and recv.id == "self" and \
                info is not None and info.cls is not None:
            if attr in info.cls.lock_attrs or \
                    self._inherited_lock(info.cls, attr):
                return LockId(("attr", info.cls.qual, attr))
            return None
        # typed receiver: self.attr.lock / obj.lock where obj's class is
        # known through attribute inference
        if isinstance(recv, ast.Attribute) and \
                isinstance(recv.value, ast.Name) and \
                recv.value.id == "self" and info is not None and \
                info.cls is not None:
            tq = info.cls.attr_types.get(recv.attr)
            cls = self.classes.get(tq) if tq else None
            if cls is not None and attr in cls.lock_attrs:
                return LockId(("attr", tq, attr))
        # untyped receiver: fall back to the project-wide attribute NAME
        # registry (detects a lock; too weak to pair identities)
        if attr in self.lock_attr_names:
            return LockId(("attrname", "?", attr))
        return None

    def _inherited_lock(self, cls: ClassInfo, attr: str,
                        _seen: Optional[Set[str]] = None) -> bool:
        seen = _seen if _seen is not None else set()
        if cls.qual in seen:  # cyclic bases parse fine statically
            return False
        seen.add(cls.qual)
        for b in cls.bases:
            bq = self._class_qual(self.by_module.get(cls.module), b)
            bcls = self.classes.get(bq) if bq else None
            if bcls is not None and (
                attr in bcls.lock_attrs
                or self._inherited_lock(bcls, attr, seen)
            ):
                return True
        return False

    def lock_ctor(self, lock: LockId) -> Optional[str]:
        kind, owner, name = lock
        if kind == "global":
            return self.global_locks.get((owner, name))
        if kind == "attr":
            cls = self.classes.get(owner)
            return cls.lock_attrs.get(name) if cls else None
        return self.lock_attr_names.get(name)


def short(qual: str) -> str:
    """Readable chain element: drop the package prefix, keep
    ``module.Class.fn`` / ``module.fn``."""
    parts = qual.split(".")
    if parts and parts[0] == _PKG:
        parts = parts[1:]
    return ".".join(parts[-3:]) if len(parts) > 3 else ".".join(parts)


def cls_ctx(project: Project, cls: ClassInfo):
    return project.by_module.get(cls.module)


def _ancestors(ctx, node: ast.AST):
    cur = ctx.parents.get(node)
    while cur is not None:
        yield cur
        cur = ctx.parents.get(cur)
