"""FL-EXC — error-taxonomy guards.

The invariants PR 1's robustness layer depends on (docs/robustness.md):
transient ``OSError``/``MemoryError`` must never be reclassified as
corruption, wrapped raises must chain their cause, and taxonomy errors
raised at a boundary must carry location context.

Rules:

* **FL-EXC001** — an ``except Exception``/bare ``except`` handler that
  wraps-and-raises must be preceded (in the same ``try``) by a handler
  re-raising ``OSError`` and ``MemoryError``; otherwise a flaky mount or
  host memory pressure gets misclassified as file corruption.  The one
  blessed spelling of the full ladder is
  ``errors.classified_decode_errors()`` — prefer it over hand-rolling.
* **FL-EXC002** — a ``raise SomeError(...)`` inside ``except ... as e``
  must use ``from e`` (or ``from None``), or pass ``e`` into the call
  (the ``annotate(e, ...)``/re-wrap pattern), so the cause chain survives.
* **FL-EXC003** — in the boundary modules (where path/column/row-group
  are in hand) a taxonomy raise must carry at least one location-context
  kwarg.  Exempt: raises inside ``with classified_decode_errors(...)``
  (the ladder annotates) and private ``_helpers`` (their public caller
  annotates).

Scope: FL-EXC001/002 apply inside the ``parquet_floor_tpu`` package;
FL-EXC003 only to the boundary modules listed below.
"""

from __future__ import annotations

import ast

from .core import FileContext, ancestors, enclosing_function, last_part

TAXONOMY = {
    "ParquetError", "CorruptFooterError", "CorruptPageError",
    "ChecksumMismatchError", "TruncatedFileError", "UnsupportedFeatureError",
    "IoRetryExhaustedError", "ThriftDecodeError", "UnsupportedCodec",
}
CONTEXT_KWARGS = {"path", "column", "row_group", "page", "offset"}
_TRANSIENT = {"OSError", "IOError", "EnvironmentError", "MemoryError"}
BOUNDARY_MODULES = (
    "format/metadata.py", "format/file_read.py", "format/pages.py",
    "io/source.py",
)

RULES = [
    ("FL-EXC001",
     "except Exception that wraps-and-raises must re-raise "
     "OSError/MemoryError first (use errors.classified_decode_errors)"),
    ("FL-EXC002",
     "raise inside `except ... as e` must chain the cause "
     "(`from e` / `from None` / pass e into the call)"),
    ("FL-EXC003",
     "taxonomy raises at decode boundaries must carry location-context "
     "kwargs (path/column/row_group/page/offset)"),
]


def _handler_names(handler: ast.ExceptHandler):
    t = handler.type
    if t is None:
        return set()
    if isinstance(t, ast.Tuple):
        return {last_part(e) for e in t.elts}
    return {last_part(t)}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    return _handler_names(handler) & {"Exception", "BaseException"} != set()


def _own_raises(handler: ast.ExceptHandler):
    """Raise nodes belonging to this handler — not to a nested handler
    (whose bare ``raise`` re-raises the NESTED exception) and not to a
    nested ``def`` (which does not execute here).  Nested try *bodies*
    and ``finally`` blocks do belong: a bare ``raise`` there still
    re-raises this handler's exception."""
    stack = list(handler.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.ExceptHandler, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Raise):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _reraises(handler: ast.ExceptHandler) -> bool:
    """Does this handler re-raise what it caught (bare `raise`, or
    `raise e` of its own as-name)?"""
    for r in _own_raises(handler):
        if r.exc is None:
            return True
        if handler.name and isinstance(r.exc, ast.Name) and \
                r.exc.id == handler.name:
            return True
    return False


def _check_exc001(ctx: FileContext):
    for node in ctx.nodes:
        if not isinstance(node, ast.Try):
            continue
        # transient classes whose re-raise arms have been seen so far —
        # one `except (OSError, MemoryError): raise` or separate
        # per-class arms both count
        reraised: set = set()
        for handler in node.handlers:
            names = _handler_names(handler)
            if _reraises(handler):
                reraised |= names
            protected = (
                {"OSError", "IOError", "EnvironmentError"} & reraised
                and "MemoryError" in reraised
            )
            if not _is_broad(handler):
                continue
            wraps = [r for r in _own_raises(handler)
                     if isinstance(r.exc, ast.Call)]
            # a bare `raise` alongside the wrap means not every exception
            # is reclassified (guarded-rewrap shape): that is fine
            if wraps and not _reraises(handler) and not protected:
                yield (handler.lineno, "FL-EXC001",
                       "broad except wraps-and-raises without a preceding "
                       "`except (OSError, MemoryError): raise` arm — "
                       "transient I/O or host pressure would be "
                       "misclassified (use errors.classified_decode_errors)")


def _check_exc002(ctx: FileContext):
    for node in ctx.nodes:
        if not isinstance(node, ast.ExceptHandler) or not node.name:
            continue
        for r in _own_raises(node):
            if not isinstance(r.exc, ast.Call) or r.cause is not None:
                continue
            # e passed into the call (annotate/re-wrap) keeps the object
            carries = any(
                isinstance(n, ast.Name) and n.id == node.name
                for n in ast.walk(r.exc)
            )
            if not carries:
                yield (r.lineno, "FL-EXC002",
                       f"raise inside `except ... as {node.name}` loses the "
                       f"cause — add `from {node.name}` (or `from None`)")


def _in_classified_with(ctx: FileContext, node: ast.AST) -> bool:
    for anc in ancestors(ctx, node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                call = item.context_expr
                if isinstance(call, ast.Call) and \
                        last_part(call.func) == "classified_decode_errors":
                    return True
    return False


def _check_exc003(ctx: FileContext):
    for node in ctx.nodes:
        if not isinstance(node, ast.Raise) or not isinstance(node.exc, ast.Call):
            continue
        name = last_part(node.exc.func)
        if name not in TAXONOMY:
            continue
        has_ctx = any(
            kw.arg is None or kw.arg in CONTEXT_KWARGS
            for kw in node.exc.keywords
        )
        if has_ctx:
            continue
        fn = enclosing_function(ctx, node)
        if fn is not None and fn.name.startswith("_"):
            continue  # private helper: the public boundary annotates
        if _in_classified_with(ctx, node):
            continue  # the ladder annotates on the way out
        yield (node.lineno, "FL-EXC003",
               f"{name} raised at a decode boundary without location "
               "context kwargs (path/column/row_group/page/offset) and "
               "outside `with classified_decode_errors(...)`")


def check(ctx: FileContext, project=None):
    in_pkg = ctx.under("parquet_floor_tpu")
    if ctx.in_scope("FL-EXC001", in_pkg):
        yield from _check_exc001(ctx)
    if ctx.in_scope("FL-EXC002", in_pkg):
        yield from _check_exc002(ctx)
    boundary = ctx.is_module(*BOUNDARY_MODULES)
    if ctx.in_scope("FL-EXC003", boundary):
        yield from _check_exc003(ctx)
