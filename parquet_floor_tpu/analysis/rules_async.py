"""FL-ASYNC — event-loop protection for the asyncio serving fabric.

The serve daemon (PR 15) and the fleet fabric (PR 16) put an asyncio
event loop at the front of every request: one coroutine that blocks
the loop stalls EVERY connection on that host, which is why the daemon
offloads all real work through ``loop.run_in_executor(pool, fn, ...)``
— the exemplar good shape these rules enforce:

* **FL-ASYNC001** — no blocking sinks in coroutine context:
  ``time.sleep``, ``open()``/file I/O, socket verbs, ``fcntl.flock``,
  storage reads (``Source.read_at/read_many/load``, ``.get_range``),
  ``.result()`` on futures, ``.acquire()``/``.wait()`` on threading
  primitives and thread ``.join()`` — direct, or buried in a *sync*
  helper the coroutine calls (followed through the bounded-BFS call
  graph, reported at the first-hop call with the chain).  Work handed
  to ``run_in_executor``/``to_thread`` is the blessed escape: the
  callable is a reference there, not a call, so the graph naturally
  never follows it into the coroutine's execution context.
* **FL-ASYNC002** — no ``await`` while holding a *threading* lock (the
  dual of FL-LOCK002): the coroutine parks at the await with the lock
  held, and every pool worker contending on that lock now waits on the
  loop's scheduling — the loop starves its own executor.  ``async
  with`` on asyncio locks is fine and never matches (the registry only
  knows ``threading`` constructors).
* **FL-ASYNC003** — a call that resolves to an ``async def`` used as a
  bare statement never runs: a coroutine object is created and
  dropped (the silent-no-op bug class).  ``await``, ``create_task``/
  ``gather``/any wrapping call, and assignment for a later await all
  pass.

Awaited calls are never sinks (``await loop.sock_connect(...)``,
``await ev.wait()`` on an asyncio Event are the loop-friendly
spellings).  Blind spots (documented): blocking calls behind
unresolved edges (dynamic dispatch), thread ``.join()`` on receivers
whose name does not look thread-like, and coroutine objects stored
then never awaited.

Scope: package code (``parquet_floor_tpu/``) — async defs only exist
in the serving fabric today, but the rules key on ``async def``
syntax, not paths, so new loops are covered the day they land.
Fixtures opt in via ``# floorlint: scope=FL-ASYNC``.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Tuple

from .core import FileContext, dotted, last_part
from .project import CALL_DEPTH, Project, short

RULES = [
    ("FL-ASYNC001",
     "no blocking calls (sleep, file/socket I/O, flock, storage reads, "
     "future.result, threading acquire/wait/join) in coroutine context — "
     "computed over the call graph; offload through run_in_executor/"
     "to_thread like the serve daemon"),
    ("FL-ASYNC002",
     "no await while holding a threading lock — the parked coroutine "
     "keeps the lock and the loop starves every worker contending on it"),
    ("FL-ASYNC003",
     "a coroutine called as a bare statement never runs — await it or "
     "schedule it with create_task/gather"),
]

_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep",
    "subprocess": "subprocess",
    "socket": "socket I/O",
    "urllib.request.urlopen": "urlopen",
    "fcntl.flock": "fcntl.flock",
    "fcntl.lockf": "fcntl.lockf",
}
_BLOCKING_OS = {"pread", "read", "write", "fsync", "sendfile"}
_BLOCKING_ATTRS = {
    "read_at": "storage read",
    "read_many": "storage read",
    "load": "storage read",
    "get_range": "remote storage read",
    "result": "future .result()",
    "shutdown": "pool shutdown",
    "recv": "socket recv",
    "recv_into": "socket recv",
    "sendall": "socket send",
    "connect": "socket connect",
    "accept": "socket accept",
}
_THREADLIKE = re.compile(r"thread|worker|proc", re.IGNORECASE)


def _blocking_shape(project: Project, info, ctx: FileContext,
                    call: ast.Call) -> Optional[str]:
    """Label of the blocking operation ``call`` performs in coroutine
    context, or None.  ``info``/``ctx`` belong to the function whose
    body the call sits in (aliases and lock identity are per-file)."""
    f = call.func
    if isinstance(f, ast.Name):
        target = project.aliases.get(ctx, {}).get(f.id, f.id)
        if f.id == "open" or target == "io.open":
            return "open()"
        if target == "time.sleep":
            return "time.sleep"
        return None
    path = dotted(f)
    if path is not None:
        for prefix, label in _BLOCKING_DOTTED.items():
            if path == prefix or path.startswith(prefix + "."):
                return label
        root, _, rest = path.partition(".")
        if root == "os" and rest in _BLOCKING_OS:
            return f"os.{rest}"
    attr = last_part(f)
    if attr in ("acquire", "wait") and isinstance(f, ast.Attribute):
        lk = project.lock_id(info, ctx, f.value)
        if lk is not None:
            return f"threading {lk.render()}.{attr}()"
        return None
    if attr == "join" and isinstance(f, ast.Attribute):
        recv = dotted(f.value)
        if recv is not None and _THREADLIKE.search(recv):
            return f"thread {recv}.join()"
        return None
    if attr in _BLOCKING_ATTRS:
        return f"{_BLOCKING_ATTRS[attr]} .{attr}()"
    return None


def _walk_own(root: ast.AST):
    """Walk a function body without descending into nested defs or
    lambdas (they run on their own schedule — often exactly the
    executor-offload escape)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _scan_blocking(project: Project, callee) -> List[Tuple[int, str]]:
    """Blocking shapes in one SYNC callee body, for the chained pass
    (memoized — chained scans revisit hot helpers)."""
    cache = project.__dict__.setdefault("_async_blocking_cache", {})
    hit = cache.get(id(callee.node))
    if hit is not None:
        return hit
    out: List[Tuple[int, str]] = []
    for node in _walk_own(callee.node):
        if isinstance(node, ast.Call):
            label = _blocking_shape(project, callee, callee.ctx, node)
            if label is not None:
                out.append((node.lineno, label))
    cache[id(callee.node)] = out
    return out


def _async_defs(project: Project, ctx: FileContext):
    for node in ctx.nodes:
        if isinstance(node, ast.AsyncFunctionDef):
            yield node, project.function_at(ctx, node)


def _is_awaited(ctx: FileContext, call: ast.Call) -> bool:
    return isinstance(ctx.parents.get(call), ast.Await)


# -- FL-ASYNC001 --------------------------------------------------------------


def _check_async001(project: Project, ctx: FileContext):
    for fn_node, info in _async_defs(project, ctx):
        reported = set()
        for node in _walk_own(fn_node):
            if not isinstance(node, ast.Call) or _is_awaited(ctx, node):
                continue
            label = _blocking_shape(project, info, ctx, node)
            if label is not None:
                yield (node.lineno, "FL-ASYNC001",
                       f"{label} in coroutine `{fn_node.name}` blocks "
                       "the event loop — every connection on this host "
                       "stalls; offload through run_in_executor/"
                       "to_thread")
                continue
            if info is None:
                continue
            qual = project.resolve_call(
                info, node, project.partials_of(info)
            )
            if qual is None:
                continue
            root = project.functions[qual]
            if isinstance(root.node, ast.AsyncFunctionDef):
                continue  # a coroutine call is FL-ASYNC003's domain
            targets = [(root, (fn_node.name, short(qual)))]
            targets.extend(
                (fi, (fn_node.name, short(qual)) + chain[1:])
                for fi, chain, _l in project.walk_calls(
                    root, depth=CALL_DEPTH - 1
                )
                if not isinstance(fi.node, ast.AsyncFunctionDef)
            )
            for callee, chain in targets:
                for bl_line, label in _scan_blocking(project, callee):
                    key = (node.lineno, label, chain[-1])
                    if key in reported:
                        continue
                    reported.add(key)
                    yield (node.lineno, "FL-ASYNC001",
                           f"{label} reachable from coroutine "
                           f"`{fn_node.name}` via {' -> '.join(chain)} "
                           f"({callee.ctx.rel}:{bl_line}) — a sync "
                           "helper that blocks stalls the loop exactly "
                           "like inline blocking; offload the call "
                           "through run_in_executor/to_thread",
                           chain)


# -- FL-ASYNC002 --------------------------------------------------------------


def _check_async002(project: Project, ctx: FileContext):
    for fn_node, info in _async_defs(project, ctx):
        for node in _walk_own(fn_node):
            if not isinstance(node, ast.With):
                continue
            locks = [
                project.lock_id(info, ctx, item.context_expr)
                for item in node.items
            ]
            locks = [lk for lk in locks if lk is not None]
            if not locks:
                continue
            for stmt in node.body:
                for sub in _walk_stmts_own(stmt):
                    if isinstance(sub, ast.Await):
                        yield (sub.lineno, "FL-ASYNC002",
                               f"await while holding threading lock "
                               f"{locks[0].render()} — the coroutine "
                               "parks with the lock held and every "
                               "pool worker contending on it now waits "
                               "on the loop; release before awaiting, "
                               "or use an asyncio.Lock")


def _walk_stmts_own(root: ast.AST):
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


# -- FL-ASYNC003 --------------------------------------------------------------


def _check_async003(project: Project, ctx: FileContext):
    for node in ctx.nodes:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        info = project.function_at(ctx, node)
        if info is None:
            continue
        partials = project.partials_of(info)
        for sub in _walk_own(node):
            if not isinstance(sub, ast.Call):
                continue
            if not isinstance(ctx.parents.get(sub), ast.Expr):
                continue  # awaited / wrapped / assigned for later
            qual = project.resolve_call(info, sub, partials)
            if qual is None:
                continue
            callee = project.functions[qual]
            if isinstance(callee.node, ast.AsyncFunctionDef):
                yield (sub.lineno, "FL-ASYNC003",
                       f"coroutine `{short(qual)}` called as a bare "
                       "statement never runs — the coroutine object is "
                       "created and dropped; await it or schedule it "
                       "with create_task/gather")


def check(ctx: FileContext, project: Project):
    in_pkg = ctx.under("parquet_floor_tpu")
    if not ctx.in_scope("FL-ASYNC", in_pkg):
        return
    yield from _check_async001(project, ctx)
    yield from _check_async002(project, ctx)
    yield from _check_async003(project, ctx)
