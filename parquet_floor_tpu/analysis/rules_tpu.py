"""FL-TPU — tracer/host-purity guards for jitted and Pallas code.

Host side effects inside a traced function either crash at trace time
(``int(tracer)``), silently bake a value into the compiled program
(``open()`` reading a config during trace), or force a device→host sync
in the middle of a compiled region (``.item()``, ``np.asarray`` on a
device array).  None of those belong in ``tpu/`` kernels or jitted
decode steps.

Rules:

* **FL-TPU001** — host I/O inside a traced function: ``open(...)`` or
  ``zlib.crc32(...)`` (CRC verification is a HOST policy —
  ``ReaderOptions.verify_crc`` pins the host engine; see docs/robustness.md).
* **FL-TPU002** — host materialization inside a traced function:
  ``.item()``, ``.block_until_ready()``, ``jax.device_get``,
  ``int(x)``/``float(x)``/``bool(x)`` on a bare name (a traced value —
  static shapes read ``int(a.shape[0])``, which is allowed), and
  ``np.array``/``np.asarray``/``np.ascontiguousarray``/``np.copy``/
  ``np.frombuffer`` (host numpy applied to traced operands).

A function counts as traced when it is decorated with ``jit``
(``@jax.jit``, ``@partial(jax.jit, ...)``) or is passed to
``pl.pallas_call`` — directly, or through a
``kernel = functools.partial(fn, ...)`` local.  Nested ``def``s inside a
traced function are traced too.

Since the project-pass rework the check is no longer lexical: helpers
*called* from a traced function are followed through the project call
graph to :data:`~parquet_floor_tpu.analysis.project.CALL_DEPTH` hops —
module-level functions, ``self`` methods, ``functools.partial`` targets,
and cross-module imports alike.  A violation found down the chain is
reported **at the call site inside the traced function** with the full
chain in the message, so the jit boundary (where the fix belongs:
hoist the host work out of the traced region) is what the finding
points at.  Unresolvable receivers (dynamic dispatch) are the
documented blind spot.

Scope: files under ``parquet_floor_tpu/tpu/`` (the traced function's
home decides; its helpers may live anywhere in the project).
"""

from __future__ import annotations

import ast

from .core import FileContext, last_part
from .project import CALL_DEPTH, Project, short

RULES = [
    ("FL-TPU001", "host I/O (open / zlib.crc32) inside a jit/Pallas-traced "
                  "function (call-graph aware)"),
    ("FL-TPU002", "host materialization (.item(), int(tracer), np.asarray, "
                  "device_get) inside a jit/Pallas-traced function "
                  "(call-graph aware)"),
]

_NP_MATERIALIZE = {"array", "asarray", "ascontiguousarray", "copy",
                   "frombuffer"}
_NP_MODULES = {"np", "numpy", "onp"}


def _is_jit_expr(node: ast.AST) -> bool:
    return last_part(node) == "jit"


def _is_jit_decorator(dec: ast.AST) -> bool:
    if _is_jit_expr(dec):
        return True
    if isinstance(dec, ast.Call):
        if _is_jit_expr(dec.func):
            return True  # @jax.jit(static_argnums=...)
        if last_part(dec.func) == "partial" and dec.args and \
                _is_jit_expr(dec.args[0]):
            return True  # @partial(jax.jit, ...)
    return False


def _partial_target(call: ast.Call):
    if last_part(call.func) == "partial" and call.args:
        return last_part(call.args[0])
    return None


def _traced_functions(ctx: FileContext):
    """FunctionDefs that are jit-decorated or used as Pallas kernels."""
    partial_locals = {}
    kernel_names = set()
    for node in ctx.nodes:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            target_fn = _partial_target(node.value)
            if target_fn:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        partial_locals[t.id] = target_fn
        if isinstance(node, ast.Call) and last_part(node.func) == "pallas_call":
            if node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Call):
                    name = _partial_target(arg) or last_part(arg.func)
                else:
                    name = last_part(arg)
                if name:
                    kernel_names.add(partial_locals.get(name, name))
    for node in ctx.nodes:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name in kernel_names or \
                any(_is_jit_decorator(d) for d in node.decorator_list):
            yield node


def _check_traced_body(fn: ast.FunctionDef, fn_label: str):
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = last_part(f)
        if isinstance(f, ast.Name) and f.id == "open":
            yield (node.lineno, "FL-TPU001",
                   f"open() inside traced function `{fn_label}` — host file "
                   "I/O runs at trace time, not per call")
        elif isinstance(f, ast.Attribute) and name == "crc32" and \
                last_part(f.value) == "zlib":
            yield (node.lineno, "FL-TPU001",
                   f"zlib.crc32 inside traced function `{fn_label}` — CRC "
                   "verification is host-side policy (ReaderOptions."
                   "verify_crc pins the host engine)")
        elif isinstance(f, ast.Attribute) and name in ("item",
                                                       "block_until_ready"):
            yield (node.lineno, "FL-TPU002",
                   f".{name}() inside traced function `{fn_label}` forces a "
                   "device→host sync / fails under trace")
        elif name == "device_get":
            yield (node.lineno, "FL-TPU002",
                   f"jax.device_get inside traced function `{fn_label}`")
        elif isinstance(f, ast.Name) and f.id in ("int", "float", "bool") \
                and len(node.args) == 1 and isinstance(node.args[0], ast.Name):
            yield (node.lineno, "FL-TPU002",
                   f"{f.id}({node.args[0].id}) inside traced function "
                   f"`{fn_label}` — materializing a traced value crashes at "
                   "trace time (static shapes read int(x.shape[i]) instead)")
        elif isinstance(f, ast.Attribute) and name in _NP_MATERIALIZE and \
                last_part(f.value) in _NP_MODULES:
            yield (node.lineno, "FL-TPU002",
                   f"np.{name} inside traced function `{fn_label}` — host "
                   "numpy on traced operands (use jnp)")


def _check_chain(project: Project, ctx: FileContext,
                 fn: ast.FunctionDef):
    """Follow the traced function's resolvable calls through the project
    graph; a host-purity violation in any reached helper is reported at
    the first hop's call site with the chain."""
    info = project.function_at(ctx, fn)
    if info is None:
        return
    seen = set()
    for callee, chain, line0 in project.walk_calls(info,
                                                   depth=CALL_DEPTH):
        label = " -> ".join(chain)
        for _line, rule, message in _check_traced_body(
            callee.node, short(callee.qual)
        ):
            head = message.split(" inside traced function")[0]
            key = (line0, rule, callee.qual, head)
            if key in seen:
                continue
            seen.add(key)
            yield (line0, rule,
                   f"{head} in helper `{short(callee.qual)}` reached from "
                   f"traced function `{fn.name}` via {label} "
                   f"({callee.ctx.rel}:{_line}) — hoist the host work out "
                   "of the traced region", chain)


def check(ctx: FileContext, project: Project):
    in_tpu = ctx.under("parquet_floor_tpu", "tpu")
    if not ctx.in_scope("FL-TPU", in_tpu):
        return
    for fn in _traced_functions(ctx):
        yield from _check_traced_body(fn, fn.name)
        yield from _check_chain(project, ctx, fn)
