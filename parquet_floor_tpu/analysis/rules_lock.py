"""FL-LOCK — concurrency-discipline guards for the threaded runtime.

PRs 3–9 made the package deeply concurrent: the scan executor's worker
pools, the device engine's stage‖ship‖decode pipeline, the shared
buffer cache, the weighted-fair tenancy gate — 20+ ``threading.Lock``/
``Condition`` sites with exactly the hazard profile of a serving
system: a wedged buffer-cache lock stalls every tenant.  These rules
make the discipline that keeps them safe *checkable*:

* **FL-LOCK001** — a bare ``lock.acquire()`` must be ``with``-managed
  or released in a ``finally`` block of the same function.  An acquire
  whose release an exception can skip wedges the lock forever.
* **FL-LOCK002** — no blocking calls while a lock is held: host I/O
  (``open``, ``os.pread``, socket/transport verbs, ``Source.read_at/
  read_many/load``, ``.get_range``), ``time.sleep``, ``subprocess``,
  ``.result()`` on futures, ``.wait()``/``.shutdown()``, and
  user-supplied callbacks (``on_report``/``on_salvage``/``read_fn``/
  ``read_many_fn``).  Computed over the call graph to
  :data:`~parquet_floor_tpu.analysis.project.CALL_DEPTH` hops — a
  blocking call buried in a helper is reported at the lock site with
  the chain.  The **blessed escape** is the single-flight
  release-before-wait spelling ``serve/cache.py`` uses: do the blocking
  work OUTSIDE the ``with`` block (leaders read after releasing;
  followers wait on an Event they were handed under the lock).
  ``cond.wait()`` on the very condition the ``with`` block holds is
  allowed — ``Condition.wait`` releases the lock while it blocks.
* **FL-LOCK003** — ``Condition.wait()`` must sit inside a ``while``
  predicate loop, never a bare ``if``: wakeups are spurious and the
  predicate may be re-falsified between ``notify`` and wakeup (the
  ``serve/tenancy.py`` WFQ gate is the live exemplar).
* **FL-LOCK004** — two statically-known locks nested in the same
  function chain must nest in ONE consistent order project-wide;
  observing both ``A→B`` and ``B→A`` is a deadlock hazard (reported at
  every site of both orders, with the opposing site named).

"Statically known" means the lock resolves through the project pass:
``self.X`` where some method assigns ``self.X = threading.Lock()``
(Condition/RLock/Semaphore too), a module global so assigned, or an
attribute whose NAME is so assigned anywhere in the project (detection
only — identity pairing for FL-LOCK004 uses fully-resolved locks).
Blind spots (documented in docs/static_analysis.md): locks passed as
parameters, ``getattr`` strings, and ``.join()`` (str.join noise).

Scope: package code (``parquet_floor_tpu/``).  Tests and scripts spawn
threads for harness reasons and opt in via ``# floorlint:
scope=FL-LOCK`` when they want the discipline checked.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from .core import FileContext, ancestors, dotted, last_part
from .project import CALL_DEPTH, Project

RULES = [
    ("FL-LOCK001",
     "Lock/RLock/Condition.acquire() must be with-managed or released "
     "in finally"),
    ("FL-LOCK002",
     "no blocking calls (I/O, sleep, subprocess, future.result, waits, "
     "user callbacks) while a lock is held — computed over the call "
     "graph; single-flight does its blocking AFTER release"),
    ("FL-LOCK003",
     "Condition.wait() must sit inside a while-predicate loop, not an "
     "if (spurious wakeups re-falsify predicates)"),
    ("FL-LOCK004",
     "statically-known lock pairs must nest in one consistent order "
     "project-wide (A→B and B→A is a deadlock hazard)"),
]

# -- FL-LOCK002 blocking-shape tables ---------------------------------------

_BLOCKING_MODULE_CALLS = {
    # dotted-prefix → label
    "time.sleep": "time.sleep",
    "subprocess": "subprocess",
    "socket": "socket I/O",
    "urllib.request.urlopen": "urlopen",
}
_BLOCKING_OS = {"pread", "read", "write", "fsync", "sendfile"}
# attribute verbs that block regardless of receiver type: storage reads
# (the Source protocol + remote transports), futures, events, pools
_BLOCKING_ATTRS = {
    "read_at": "storage read",
    "read_many": "storage read",
    "load": "storage read",
    "get_range": "remote storage read",
    "result": "future .result()",
    "shutdown": "pool shutdown",
    "recv": "socket recv",
    "recv_into": "socket recv",
    "sendall": "socket send",
    "connect": "socket connect",
    "accept": "socket accept",
}
# zero-trust callback parameter names: calling user code under a lock
# hands the lock's critical section to the user
_CALLBACK_NAMES = {"on_report", "on_salvage", "read_fn", "read_many_fn",
                   "callback", "hydrator", "dehydrator"}


def _blocking_shape(node: ast.Call, held_exprs: List[str]
                    ) -> Optional[str]:
    """Label of the blocking operation ``node`` performs, or None.
    ``held_exprs`` are the dotted spellings of locks held around this
    call — a ``.wait()`` on one of them is the blessed Condition.wait
    (it RELEASES that lock while blocking); the caller decides whether
    some OTHER lock stays held."""
    f = node.func
    if isinstance(f, ast.Name):
        if f.id == "open":
            return "open()"
        if f.id == "sleep":
            return "time.sleep"
        if f.id in _CALLBACK_NAMES:
            return f"user callback {f.id}()"
        return None
    path = dotted(f)
    if path is not None:
        for prefix, label in _BLOCKING_MODULE_CALLS.items():
            if path == prefix or path.startswith(prefix + "."):
                return label
        root, _, rest = path.partition(".")
        if root == "os" and rest in _BLOCKING_OS:
            return f"os.{rest}"
    attr = last_part(f)
    if attr == "wait":
        recv = dotted(f.value) if isinstance(f, ast.Attribute) else None
        if recv is not None and recv in held_exprs:
            return None  # Condition.wait on a held cv: releases it
        return ".wait()"
    if attr in _CALLBACK_NAMES:
        return f"user callback .{attr}()"
    if attr in _BLOCKING_ATTRS:
        return f"{_BLOCKING_ATTRS[attr]} .{attr}()"
    return None


# -- with-region discovery ---------------------------------------------------


class _Region:
    """One ``with <lock>:`` region: the statement, the resolved lock,
    and the lock expression's dotted spelling."""

    __slots__ = ("stmt", "lock", "expr")

    def __init__(self, stmt: ast.With, lock, expr: str):
        self.stmt = stmt
        self.lock = lock
        self.expr = expr


def _lock_regions(project: Project, ctx: FileContext, info,
                  fn_node: ast.AST) -> List[_Region]:
    cache = project.__dict__.setdefault("_regions_cache", {})
    hit = cache.get(id(fn_node))
    if hit is not None:
        return hit
    out: List[_Region] = []
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.With):
            continue
        for item in node.items:
            expr = item.context_expr
            lock = project.lock_id(info, ctx, expr)
            if lock is not None:
                out.append(_Region(node, lock, dotted(expr) or ""))
    cache[id(fn_node)] = out
    return out


def _body_calls(region_stmt: ast.With):
    """Calls lexically inside the region body — nested defs/lambdas are
    skipped (they do not run under the lock at definition time)."""
    stack = list(region_stmt.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


# -- FL-LOCK001 --------------------------------------------------------------


def _check_lock001(project: Project, ctx: FileContext):
    for node in ctx.nodes:
        if not isinstance(node, ast.Call) or \
                last_part(node.func) != "acquire" or \
                not isinstance(node.func, ast.Attribute):
            continue
        recv = node.func.value
        info = _info_at(project, ctx, node)
        lock = project.lock_id(info, ctx, recv)
        if lock is None:
            continue
        recv_str = dotted(recv)
        if recv_str is not None and _released_in_finally(
            ctx, node, recv_str
        ):
            continue
        yield (node.lineno, "FL-LOCK001",
               f"{lock.render()}.acquire() without `with` or a finally "
               "release in this function — an exception between acquire "
               "and release wedges the lock (use `with "
               f"{recv_str or lock.render()}:`)")


def _released_in_finally(ctx: FileContext, call: ast.Call,
                         recv_str: str) -> bool:
    fn = _enclosing_fn(ctx, call)
    scope = fn if fn is not None else ctx.tree
    for node in ast.walk(scope):
        if not isinstance(node, ast.Try) or not node.finalbody:
            continue
        for stmt in node.finalbody:
            for c in ast.walk(stmt):
                if isinstance(c, ast.Call) and \
                        last_part(c.func) == "release" and \
                        isinstance(c.func, ast.Attribute) and \
                        dotted(c.func.value) == recv_str:
                    return True
    return False


# -- FL-LOCK002 --------------------------------------------------------------


def _scan_blocking(project: Project, fn_node: ast.AST,
                   ctx: FileContext) -> List[tuple]:
    """Blocking shapes in one CALLEE body, for the chained pass.  No
    held-cv allowance applies here: the caller's lock stays held while
    the callee blocks, and ``Condition.wait`` only releases the cv it
    waits on — so even the callee's own ``with cv: cv.wait()`` pattern
    blocks the caller's distinct lock (moving a violation into a helper
    must not silence it).  Returns ``(lineno, label)`` pairs (memoized
    per function — chained scans revisit hot helpers)."""
    cache = project.__dict__.setdefault("_blocking_cache", {})
    hit = cache.get(id(fn_node))
    if hit is not None:
        return hit
    out = []
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        label = _blocking_shape(node, [])
        if label is not None:
            out.append((node.lineno, label))
    cache[id(fn_node)] = out
    return out


def _check_lock002(project: Project, ctx: FileContext):
    for fn_node, info in _functions(project, ctx):
        regions = _lock_regions(project, ctx, info, fn_node)
        if not regions:
            continue
        for region in regions:
            # direct shapes under this region.  Blessing is PER LOCK:
            # `cv.wait()` is evaluated against each held region
            # separately, so the wait is fine for the cv it releases
            # but still flags any OTHER lock the caller keeps held.
            for call in _body_calls(region.stmt):
                label = _blocking_shape(call, [region.expr])
                if label is not None:
                    yield (call.lineno, "FL-LOCK002",
                           f"{label} while holding "
                           f"{region.lock.render()} — blocking under a "
                           "lock stalls every waiter (single-flight: "
                           "release first, block after)")
            # call-graph hops: a resolvable call under the lock whose
            # callee (to depth) blocks
            if info is None:
                continue
            yield from _chained_blocking(project, ctx, info, region)


def _chained_blocking(project: Project, ctx: FileContext, info,
                      region: _Region):
    partials = project.partials_of(info)
    reported = set()
    for call in _body_calls(region.stmt):
        qual = project.resolve_call(info, call, partials)
        if qual is None:
            continue
        root = project.functions[qual]
        targets = [(root, (region.expr or region.lock.render(),
                           _short(qual)), call.lineno)]
        targets.extend(
            (fi, (region.expr or region.lock.render(), _short(qual))
             + chain[1:], call.lineno)
            for fi, chain, _line in project.walk_calls(
                root, depth=CALL_DEPTH - 1
            )
        )
        for callee, chain, line0 in targets:
            for bl_line, label in _scan_blocking(
                project, callee.node, callee.ctx
            ):
                key = (line0, label, chain[-1])
                if key in reported:
                    continue
                reported.add(key)
                yield (line0, "FL-LOCK002",
                       f"{label} reachable while holding "
                       f"{region.lock.render()} via "
                       f"{' -> '.join(chain)} "
                       f"({callee.ctx.rel}:{bl_line}) — blocking under "
                       "a lock stalls every waiter (single-flight: "
                       "release first, block after)")


# -- FL-LOCK003 --------------------------------------------------------------


def _check_lock003(project: Project, ctx: FileContext):
    for node in ctx.nodes:
        if not isinstance(node, ast.Call) or \
                last_part(node.func) != "wait" or \
                not isinstance(node.func, ast.Attribute):
            continue
        info = _info_at(project, ctx, node)
        lock = project.lock_id(info, ctx, node.func.value)
        if lock is None or project.lock_ctor(lock) != "Condition":
            continue
        if any(isinstance(a, ast.While) for a in ancestors(ctx, node)):
            continue
        yield (node.lineno, "FL-LOCK003",
               f"{lock.render()}.wait() outside a while-predicate loop — "
               "wakeups are spurious and the predicate can re-falsify "
               "between notify and wakeup; spell it `while not pred: "
               "cv.wait()`")


# -- FL-LOCK004 --------------------------------------------------------------


def _nesting_pairs(project: Project):
    """Project-wide ordered lock pairs: ``{(A, B): [(ctx, line,
    chain)]}`` where A was held when B was acquired — lexically nested
    ``with`` blocks, and ``with A:`` bodies calling (to depth) into
    functions that take B.  Only fully-resolved identities pair (the
    ``attrname`` fallback would merge every ``_lock`` in the project
    into one)."""
    pairs: Dict[Tuple[tuple, tuple], List[tuple]] = {}

    def record(a, b, ctx, line, chain):
        if a[0] == "attrname" or b[0] == "attrname" or a == b:
            return
        pairs.setdefault((tuple(a), tuple(b)), []).append(
            (ctx, line, chain)
        )

    for ctx in project.contexts:
        for fn_node, info in _functions(project, ctx):
            regions = _lock_regions(project, ctx, info, fn_node)
            if not regions:
                continue
            region_by_stmt: Dict[ast.AST, List] = {}
            for r in regions:
                region_by_stmt.setdefault(r.stmt, []).append(r)
            # lexical nesting
            for r in regions:
                for anc in ancestors(ctx, r.stmt):
                    for outer in region_by_stmt.get(anc, ()):
                        record(outer.lock, r.lock, ctx,
                               r.stmt.lineno, ())
            # multi-item `with a, b:` IS nesting (Python defines it as
            # the nested form), but both items share one With node, so
            # the ancestor walk above never sees the pair — record the
            # items' left-to-right acquisition order here
            for stmt_regions in region_by_stmt.values():
                for i, outer in enumerate(stmt_regions):
                    for inner_r in stmt_regions[i + 1:]:
                        record(outer.lock, inner_r.lock, ctx,
                               outer.stmt.lineno, ())
            # chained nesting
            if info is None:
                continue
            partials = project.partials_of(info)
            for r in regions:
                for call in _body_calls(r.stmt):
                    qual = project.resolve_call(info, call, partials)
                    if qual is None:
                        continue
                    root = project.functions[qual]
                    for callee, chain, _l in [
                        (root, (_short(info.qual), _short(qual)), 0)
                    ] + list(project.walk_calls(root,
                                                depth=CALL_DEPTH - 1)):
                        inner = _lock_regions(project, callee.ctx,
                                              callee, callee.node)
                        for ir in inner:
                            record(r.lock, ir.lock, ctx, call.lineno,
                                   chain)
    return pairs


def check_project_lock004(project: Project):
    """Whole-project FL-LOCK004 verdicts, grouped per file: ``{ctx:
    [(line, rule, message)]}``.  Computed once per project (cached on
    the Project object) and handed out per file by :func:`check`."""
    cached = getattr(project, "_lock004_cache", None)
    if cached is not None:
        return cached
    pairs = _nesting_pairs(project)
    out: Dict[object, List[tuple]] = {}
    from .project import LockId

    for (a, b), sites in pairs.items():
        if (b, a) not in pairs or a > b:
            continue  # report each unordered pair once, from one side
        ra, rb = LockId(a).render(), LockId(b).render()
        other = pairs[(b, a)]
        for ctx, line, chain in sites:
            via = f" via {' -> '.join(chain)}" if chain else ""
            o_ctx, o_line, _ = other[0]
            out.setdefault(ctx, []).append((
                line, "FL-LOCK004",
                f"lock order {ra} -> {rb}{via} conflicts with "
                f"{rb} -> {ra} at {o_ctx.rel}:{o_line} — inconsistent "
                "nesting order is a deadlock hazard; pick one order "
                "project-wide",
            ))
        for ctx, line, chain in other:
            via = f" via {' -> '.join(chain)}" if chain else ""
            s_ctx, s_line, _ = sites[0]
            out.setdefault(ctx, []).append((
                line, "FL-LOCK004",
                f"lock order {rb} -> {ra}{via} conflicts with "
                f"{ra} -> {rb} at {s_ctx.rel}:{s_line} — inconsistent "
                "nesting order is a deadlock hazard; pick one order "
                "project-wide",
            ))
    project._lock004_cache = out
    return out


# -- shared helpers ----------------------------------------------------------


def _functions(project: Project, ctx: FileContext):
    """Every def in the file, paired with its FunctionInfo when the
    project indexed it (module-level / method), else None (nested)."""
    for node in ctx.nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, project.function_at(ctx, node)


def _enclosing_fn(ctx: FileContext, node: ast.AST):
    for anc in ancestors(ctx, node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def _info_at(project: Project, ctx: FileContext, node: ast.AST):
    fn = _enclosing_fn(ctx, node)
    return project.function_at(ctx, fn) if fn is not None else None


def _short(qual: str) -> str:
    from .project import short

    return short(qual)


def check(ctx: FileContext, project: Project):
    in_pkg = ctx.under("parquet_floor_tpu")
    if not ctx.in_scope("FL-LOCK", in_pkg):
        return
    yield from _check_lock001(project, ctx)
    yield from _check_lock002(project, ctx)
    yield from _check_lock003(project, ctx)
    yield from check_project_lock004(project).get(ctx, [])
