"""floorlint — the project-invariant static analyzer (stdlib-only).

``scripts/lint.py`` checks style; this package checks the *invariants*
the robustness layer depends on — the bug classes PR 1 fixed by fuzzing
become unrepresentable at commit time:

========== ==================================================================
FL-EXC     error-taxonomy guards: no broad except that misclassifies
           OSError/MemoryError as corruption, ``raise ... from`` discipline,
           location context on boundary taxonomy raises
FL-TPU     tracer/host-purity guards: no host I/O or host materialization
           inside ``jax.jit``/Pallas-traced functions in ``tpu/`` —
           followed through the project call graph (helpers called from
           jitted functions, ``functools.partial`` hops, cross-module)
FL-RES     resource guards: every ``open()``/Source acquisition is
           context-managed or closed on all exception paths
FL-ALLOC   allocation guards: sizes parsed off the wire flow through
           ``errors.checked_alloc_size``
FL-OBS     observability guards: trace metric/decision/span name literals
           in package code come from the ``trace.names`` registry
FL-LOCK    concurrency-discipline guards: with-managed acquires, no
           blocking under a lock (call-graph-computed), while-predicate
           Condition waits, consistent project-wide lock ordering
FL-RACE    lockset race detection: per-field guard locks inferred from
           write sites + thread-entry reachability; accesses outside the
           inferred guard and non-atomic check-then-act flagged
FL-ASYNC   event-loop protection: no blocking sinks in coroutine context
           (call-graph-computed; run_in_executor is the escape), no await
           under a threading lock, no dropped (un-awaited) coroutines
========== ==================================================================

The engine runs ONE project-wide pass (``analysis.project``): every file
parses once, a symbol table + call graph + lock registry is built over
the whole package, and each rule checks its files against the shared
indexes.

CLI: ``python -m parquet_floor_tpu.analysis [paths ...]``
(``--format=json`` for machine consumers).
Docs: ``docs/static_analysis.md``.
"""

from .core import (  # noqa: F401  (public surface)
    RunResult,
    Violation,
    analyze_file,
    build_project,
    iter_python_files,
    load_baseline,
    run,
    write_baseline,
)
from .project import CALL_DEPTH, Project  # noqa: F401
from . import (rules_alloc, rules_async, rules_exc, rules_lock, rules_obs,
               rules_race, rules_res, rules_tpu)

ALL_RULES = (
    rules_exc.RULES + rules_tpu.RULES + rules_res.RULES + rules_alloc.RULES
    + rules_obs.RULES + rules_lock.RULES + rules_race.RULES
    + rules_async.RULES
)

__all__ = [
    "ALL_RULES", "CALL_DEPTH", "Project", "RunResult", "Violation",
    "analyze_file", "build_project", "iter_python_files", "load_baseline",
    "run", "write_baseline",
]
