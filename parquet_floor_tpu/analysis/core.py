"""floorlint core — file walking, suppression directives, scoping, baseline.

The analyzer is stdlib-only (``ast`` + ``pathlib``): the lint gate must run
in hermetic images with no ruff installed, exactly like ``scripts/lint.py``.

Directives (comments, parsed without executing the file)::

    # floorlint: disable=FL-EXC001,FL-RES     same line or the line above
    # floorlint: disable-file=FL-TPU          whole file
    # floorlint: scope=FL-ALLOC               opt the file INTO rule families
                                              its path would not select
                                              (how the test fixtures under
                                              tests/analysis_fixtures/ are
                                              analyzed)

A token names either a full rule id (``FL-EXC001``) or a family prefix
(``FL-EXC``); ``all`` matches everything.

Baseline: a text file of ``path:RULE:message`` fingerprints (no line
numbers, so unrelated edits do not churn it).  Each entry cancels one
matching violation; the checked-in ``floorlint.baseline`` is empty and
must stay empty — it exists so a future emergency has a paved road.
"""

from __future__ import annotations

import ast
import pathlib
import re
from collections import Counter
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_EXCLUDED_DIRS = {"__pycache__", ".git", "data", "analysis_fixtures"}

_DIRECTIVE = re.compile(
    r"#\s*floorlint:\s*(disable-file|disable|scope)\s*=\s*([A-Za-z0-9_,\-]+)"
)


@dataclass(frozen=True)
class Violation:
    path: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def fingerprint(self) -> str:
        return f"{self.path}:{self.rule}:{self.message}"


class FileContext:
    """Everything a rule needs about one source file."""

    def __init__(self, path: pathlib.Path, rel: str, src: str, tree: ast.AST):
        self.path = path
        self.rel = rel
        self.src = src
        self.tree = tree
        self.lines = src.splitlines()
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.scoped: Set[str] = set()       # families opted in via scope=
        self.file_disables: Set[str] = set()
        self.line_disables: Dict[int, Set[str]] = {}
        self._parse_directives()

    # -- directives --------------------------------------------------------

    def _parse_directives(self) -> None:
        for i, line in enumerate(self.lines, 1):
            for kind, value in _DIRECTIVE.findall(line):
                tokens = {t for t in value.split(",") if t}
                if kind == "scope":
                    self.scoped |= tokens
                elif kind == "disable-file":
                    self.file_disables |= tokens
                else:
                    self.line_disables.setdefault(i, set()).update(tokens)
                    # a standalone comment line suppresses the next line
                    if line.lstrip().startswith("#"):
                        self.line_disables.setdefault(i + 1, set()).update(
                            tokens
                        )

    def suppressed(self, rule: str, line: int) -> bool:
        tokens = self.file_disables | self.line_disables.get(line, set())
        return any(_matches(rule, t) for t in tokens)

    # -- path scoping ------------------------------------------------------

    @property
    def rel_parts(self) -> Tuple[str, ...]:
        return tuple(pathlib.PurePosixPath(self.rel.replace("\\", "/")).parts)

    def under(self, *parts: str) -> bool:
        """True when ``parts`` appear consecutively in the file's path."""
        rp = self.rel_parts
        n = len(parts)
        return any(rp[i : i + n] == parts for i in range(len(rp) - n + 1))

    def is_module(self, *suffixes: str) -> bool:
        posix = "/".join(self.rel_parts)
        return any(posix.endswith(s) for s in suffixes)

    def in_scope(self, family: str, default: bool) -> bool:
        if any(_matches(family, t) or _matches(t, family) for t in self.scoped):
            return True
        return default


def _matches(rule: str, token: str) -> bool:
    return token == "all" or rule == token or rule.startswith(token)


# -- AST helpers shared by the rule modules ---------------------------------

def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_part(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def ancestors(ctx: FileContext, node: ast.AST):
    cur = ctx.parents.get(node)
    while cur is not None:
        yield cur
        cur = ctx.parents.get(cur)


def enclosing_function(ctx: FileContext, node: ast.AST):
    for anc in ancestors(ctx, node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def enclosing_class(ctx: FileContext, node: ast.AST):
    for anc in ancestors(ctx, node):
        if isinstance(anc, ast.ClassDef):
            return anc
    return None


# -- runner -----------------------------------------------------------------

def iter_python_files(paths: Sequence[str]) -> Iterable[pathlib.Path]:
    """Explicit files are always analyzed (that is how the deliberately
    violating fixtures get checked); directory walks skip ``_EXCLUDED_DIRS``."""
    for p in paths:
        path = pathlib.Path(p)
        if path.is_file():
            yield path
        elif path.is_dir():
            for f in sorted(path.rglob("*.py")):
                if not _EXCLUDED_DIRS.intersection(f.parts):
                    yield f


def _display_path(path: pathlib.Path) -> str:
    try:
        return path.resolve().relative_to(pathlib.Path.cwd()).as_posix()
    except ValueError:
        return path.as_posix()


def _analyze_one(path: pathlib.Path):
    """Shared per-file pass: returns ``(kept, suppressed_count)`` with
    ``# floorlint: disable`` directives already applied (baseline handling
    stays in :func:`run` — it is a cross-file budget)."""
    from . import rules_alloc, rules_exc, rules_obs, rules_res, rules_tpu

    rel = _display_path(path)
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:
        return [Violation(rel, e.lineno or 1, "FL-SYNTAX",
                          f"file does not parse: {e.msg}")], 0
    ctx = FileContext(path, rel, src, tree)
    kept: List[Violation] = []
    suppressed = 0
    for mod in (rules_exc, rules_tpu, rules_res, rules_alloc, rules_obs):
        for line, rule, message in mod.check(ctx):
            if ctx.suppressed(rule, line):
                suppressed += 1
            else:
                kept.append(Violation(rel, line, rule, message))
    return kept, suppressed


def analyze_file(path: pathlib.Path) -> List[Violation]:
    """Analyze one file, honoring its suppression directives (the same
    verdicts the CLI reports — editor/tooling consumers see no
    deliberately-suppressed lines)."""
    return _analyze_one(path)[0]


@dataclass
class RunResult:
    violations: List[Violation]
    suppressed: int
    baselined: int
    files: int
    stale_baseline: int

    @property
    def ok(self) -> bool:
        return not self.violations


def run(paths: Sequence[str],
        baseline: Optional[Counter] = None) -> RunResult:
    reported: List[Violation] = []
    suppressed = 0
    baselined = 0
    files = 0
    remaining = Counter(baseline or ())
    for path in iter_python_files(paths):
        files += 1
        kept, n_suppressed = _analyze_one(path)
        suppressed += n_suppressed
        for v in kept:
            if remaining[v.fingerprint()] > 0:
                remaining[v.fingerprint()] -= 1
                baselined += 1
                continue
            reported.append(v)
    stale = sum(remaining.values())
    reported.sort(key=lambda v: (v.path, v.line, v.rule))
    return RunResult(reported, suppressed, baselined, files, stale)


def load_baseline(path: pathlib.Path) -> Counter:
    entries: Counter = Counter()
    if not path.exists():
        return entries
    for line in path.read_text().splitlines():
        line = line.strip()
        if line and not line.startswith("#"):
            entries[line] += 1
    return entries


def write_baseline(path: pathlib.Path, violations: Iterable[Violation]) -> None:
    lines = [
        "# floorlint baseline — one `path:RULE:message` fingerprint per",
        "# accepted pre-existing violation.  Keep this empty: new code must",
        "# be clean; entries are an emergency paved road, not a policy.",
    ]
    lines += sorted(v.fingerprint() for v in violations)
    path.write_text("\n".join(lines) + "\n")
