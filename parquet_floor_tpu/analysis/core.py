"""floorlint core — project pass, suppression directives, baseline.

The analyzer is stdlib-only (``ast`` + ``pathlib``): the lint gate must
run in hermetic images with no ruff installed, exactly like
``scripts/lint.py``.

Since the FL-LOCK/call-graph rework the engine runs ONE project-wide
pass: every requested file is parsed once into a :class:`FileContext`,
a :class:`~parquet_floor_tpu.analysis.project.Project` (symbol table +
call graph + lock registry) is built over all of them together, and
each rule module's ``check(ctx, project)`` runs per file against the
shared indexes.  Per-file verdicts — including every suppression
directive — are identical to the old per-file pass for rules that never
consult the graph; graph-aware rules (FL-TPU chain mode, FL-LOCK002/004)
additionally see across file boundaries.

Directives (comments, parsed without executing the file)::

    # floorlint: disable=FL-EXC001,FL-RES     same line or the line above
    # floorlint: disable-file=FL-TPU          whole file
    # floorlint: scope=FL-ALLOC               opt the file INTO rule families
                                              its path would not select
                                              (how the test fixtures under
                                              tests/analysis_fixtures/ are
                                              analyzed)

A token names either a full rule id (``FL-EXC001``) or a family prefix
(``FL-EXC``); ``all`` matches everything.

Baseline: a text file of fingerprints, one per accepted violation.  The
CURRENT format is ``path:RULE:span`` where ``span`` is the violation's
source line with whitespace collapsed — stable under message rewording
AND under line-number drift from unrelated edits.  Legacy
``path:RULE:message`` entries (the PR 2 format) still match during the
transition; ``--update-baseline`` rewrites everything to the new
format.  The checked-in ``floorlint.baseline`` is empty and must stay
empty — it exists so a future emergency has a paved road.
"""

from __future__ import annotations

import ast
import pathlib
import re
from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_EXCLUDED_DIRS = {"__pycache__", ".git", "data", "analysis_fixtures"}

_DIRECTIVE = re.compile(
    r"#\s*floorlint:\s*(disable-file|disable|scope)\s*=\s*([A-Za-z0-9_,\-]+)"
)

_WS = re.compile(r"\s+")


@dataclass(frozen=True)
class Violation:
    path: str
    line: int
    rule: str
    message: str
    #: resolved call chain for graph-aware findings (root → sink), empty
    #: for lexical ones
    chain: Tuple[str, ...] = ()
    #: the violation's source line, whitespace-collapsed — the stable
    #: half of the fingerprint
    span: str = ""

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def fingerprint(self) -> str:
        """Stable fingerprint: ``path:rule:normalized-span``.  No line
        number (unrelated edits must not churn the baseline) and no
        message text (rewording a message must not orphan entries —
        the PR 2 scheme's bug)."""
        return f"{self.path}:{self.rule}:{self.span}"

    def legacy_fingerprint(self) -> str:
        """The PR 2 ``path:RULE:message`` shape — still honored when
        reading a baseline, never written anymore."""
        return f"{self.path}:{self.rule}:{self.message}"

    def to_dict(self) -> dict:
        """The ``--format=json`` shape (CI / editor consumers)."""
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "call_chain": list(self.chain),
        }


class FileContext:
    """Everything a rule needs about one source file."""

    def __init__(self, path: pathlib.Path, rel: str, src: str, tree: ast.AST):
        self.path = path
        self.rel = rel
        self.src = src
        self.tree = tree
        self.lines = src.splitlines()
        self.parents: Dict[ast.AST, ast.AST] = {}
        #: every node in walk order — the one tree traversal; rules
        #: iterate this instead of re-running ``ast.walk`` per rule
        self.nodes: List[ast.AST] = list(ast.walk(tree))
        for node in self.nodes:
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.calls: List[ast.Call] = [
            n for n in self.nodes if isinstance(n, ast.Call)
        ]
        self.scoped: Set[str] = set()       # families opted in via scope=
        self.file_disables: Set[str] = set()
        self.line_disables: Dict[int, Set[str]] = {}
        self._parse_directives()

    # -- directives --------------------------------------------------------

    def _parse_directives(self) -> None:
        for i, line in enumerate(self.lines, 1):
            for kind, value in _DIRECTIVE.findall(line):
                tokens = {t for t in value.split(",") if t}
                if kind == "scope":
                    self.scoped |= tokens
                elif kind == "disable-file":
                    self.file_disables |= tokens
                else:
                    self.line_disables.setdefault(i, set()).update(tokens)
                    # a standalone comment line suppresses the next line
                    if line.lstrip().startswith("#"):
                        self.line_disables.setdefault(i + 1, set()).update(
                            tokens
                        )

    def suppressed(self, rule: str, line: int) -> bool:
        tokens = self.file_disables | self.line_disables.get(line, set())
        return any(_matches(rule, t) for t in tokens)

    def span_at(self, line: int) -> str:
        """The whitespace-collapsed source line — the violation's
        stable fingerprint span."""
        if 1 <= line <= len(self.lines):
            return _WS.sub(" ", self.lines[line - 1].strip())
        return ""

    # -- path scoping ------------------------------------------------------

    @property
    def rel_parts(self) -> Tuple[str, ...]:
        return tuple(pathlib.PurePosixPath(self.rel.replace("\\", "/")).parts)

    def under(self, *parts: str) -> bool:
        """True when ``parts`` appear consecutively in the file's path."""
        rp = self.rel_parts
        n = len(parts)
        return any(rp[i : i + n] == parts for i in range(len(rp) - n + 1))

    def is_module(self, *suffixes: str) -> bool:
        posix = "/".join(self.rel_parts)
        return any(posix.endswith(s) for s in suffixes)

    def in_scope(self, family: str, default: bool) -> bool:
        if any(_matches(family, t) or _matches(t, family) for t in self.scoped):
            return True
        return default


def _matches(rule: str, token: str) -> bool:
    return token == "all" or rule == token or rule.startswith(token)


# -- AST helpers shared by the rule modules ---------------------------------

def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_part(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def ancestors(ctx: FileContext, node: ast.AST):
    cur = ctx.parents.get(node)
    while cur is not None:
        yield cur
        cur = ctx.parents.get(cur)


def enclosing_function(ctx: FileContext, node: ast.AST):
    for anc in ancestors(ctx, node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def enclosing_class(ctx: FileContext, node: ast.AST):
    for anc in ancestors(ctx, node):
        if isinstance(anc, ast.ClassDef):
            return anc
    return None


# -- runner -----------------------------------------------------------------

def iter_python_files(paths: Sequence[str]) -> Iterable[pathlib.Path]:
    """Explicit files are always analyzed (that is how the deliberately
    violating fixtures get checked); directory walks skip ``_EXCLUDED_DIRS``."""
    for p in paths:
        path = pathlib.Path(p)
        if path.is_file():
            yield path
        elif path.is_dir():
            for f in sorted(path.rglob("*.py")):
                if not _EXCLUDED_DIRS.intersection(f.parts):
                    yield f


def _display_path(path: pathlib.Path) -> str:
    try:
        return path.resolve().relative_to(pathlib.Path.cwd()).as_posix()
    except ValueError:
        return path.as_posix()


def _rule_modules():
    from . import (rules_alloc, rules_async, rules_exc, rules_lock,
                   rules_obs, rules_race, rules_res, rules_tpu)

    return (rules_exc, rules_tpu, rules_res, rules_alloc, rules_obs,
            rules_lock, rules_race, rules_async)


def _parse_contexts(paths: Sequence[str], cache=None):
    """Parse every requested file ONCE (the project AST cache).  Returns
    ``(contexts, syntax_violations)`` — unparsable files are reported as
    FL-SYNTAX and excluded from the project pass.  With a
    :class:`~parquet_floor_tpu.analysis.cache.LintCache`, unchanged
    files load their pickled FileContext instead of re-parsing (the
    incremental context tier — rules still run project-wide)."""
    contexts: List[FileContext] = []
    broken: List[Violation] = []
    for path in iter_python_files(paths):
        if cache is not None:
            hit = cache.load_context(path)
            if hit is not None:
                contexts.append(hit)
                continue
        rel = _display_path(path)
        src = path.read_text()
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError as e:
            broken.append(Violation(rel, e.lineno or 1, "FL-SYNTAX",
                                    f"file does not parse: {e.msg}"))
            continue
        ctx = FileContext(path, rel, src, tree)
        contexts.append(ctx)
        if cache is not None:
            cache.store_context(path, ctx)
    return contexts, broken


def _check_context(ctx: FileContext, project):
    """All rules over one file against the shared project; returns
    ``(kept, suppressed_rule_ids)`` with directives applied."""
    kept: List[Violation] = []
    suppressed: List[str] = []
    seen = set()
    for mod in _rule_modules():
        for found in mod.check(ctx, project):
            line, rule, message = found[0], found[1], found[2]
            chain = tuple(found[3]) if len(found) > 3 and found[3] else ()
            key = (line, rule, message)
            if key in seen:
                continue
            seen.add(key)
            if ctx.suppressed(rule, line):
                suppressed.append(rule)
            else:
                kept.append(Violation(ctx.rel, line, rule, message,
                                      chain=chain,
                                      span=ctx.span_at(line)))
    return kept, suppressed


def build_project(contexts):
    from .project import Project

    return Project(contexts)


def analyze_file(path: pathlib.Path) -> List[Violation]:
    """Analyze one file, honoring its suppression directives (the same
    verdicts the CLI reports — editor/tooling consumers see no
    deliberately-suppressed lines).  Cross-file edges obviously cannot
    resolve from a single file; use :func:`run` over several paths for
    project-wide verdicts."""
    contexts, broken = _parse_contexts([str(path)])
    if broken:
        return broken
    project = build_project(contexts)
    return _check_context(contexts[0], project)[0]


@dataclass
class RunResult:
    violations: List[Violation]
    suppressed: int
    baselined: int
    files: int
    stale_baseline: int
    #: every pre-suppression/pre-baseline violation — what
    #: ``--update-baseline`` snapshots (suppressed lines excluded: they
    #: are already accepted in-code)
    all_kept: List[Violation] = field(default_factory=list)
    #: True when this verdict came whole from the incremental cache's
    #: run tier (no file changed since it was stored)
    from_cache: bool = False
    #: rule ids of directive-suppressed findings (len == ``suppressed``)
    #: — per-family accounting for ``scripts/lint.py``
    suppressed_rules: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations


def run(paths: Sequence[str],
        baseline: Optional[Counter] = None,
        cache=None) -> RunResult:
    files = list(iter_python_files(paths))
    signature = None
    if cache is not None:
        signature = cache.run_signature(files, baseline)
        hit = cache.load_run(signature)
        if isinstance(hit, RunResult):
            hit.from_cache = True
            return hit
    contexts, broken = _parse_contexts(files, cache)
    project = build_project(contexts)
    reported: List[Violation] = []
    all_kept: List[Violation] = list(broken)
    suppressed_rules: List[str] = []
    baselined = 0
    remaining = Counter(baseline or ())
    for ctx in contexts:
        kept, ctx_suppressed = _check_context(ctx, project)
        suppressed_rules.extend(ctx_suppressed)
        all_kept.extend(kept)
    for v in broken + sorted(
        all_kept[len(broken):], key=lambda v: (v.path, v.line, v.rule)
    ):
        fp = v.fingerprint()
        legacy = v.legacy_fingerprint()
        if remaining[fp] > 0:
            remaining[fp] -= 1
            baselined += 1
        elif remaining[legacy] > 0:
            remaining[legacy] -= 1
            baselined += 1
        else:
            reported.append(v)
    stale = sum(remaining.values())
    reported.sort(key=lambda v: (v.path, v.line, v.rule))
    result = RunResult(reported, len(suppressed_rules), baselined,
                       len(contexts) + len(broken), stale, all_kept,
                       suppressed_rules=suppressed_rules)
    if cache is not None and signature is not None:
        cache.store_run(signature, result)
    return result


def load_baseline(path: pathlib.Path) -> Counter:
    entries: Counter = Counter()
    if not path.exists():
        return entries
    for line in path.read_text().splitlines():
        line = line.strip()
        if line and not line.startswith("#"):
            entries[line] += 1
    return entries


def write_baseline(path: pathlib.Path, violations: Iterable[Violation]) -> None:
    lines = [
        "# floorlint baseline — one `path:RULE:normalized-span` fingerprint",
        "# per accepted pre-existing violation.  Keep this empty: new code",
        "# must be clean; entries are an emergency paved road, not a policy.",
    ]
    lines += sorted(v.fingerprint() for v in violations)
    path.write_text("\n".join(lines) + "\n")
