"""Device encode engine + pipelined file writer (docs/write.md).

The decode side stages compressed bytes to the device and fuses a whole
row group's decode into one launch; this module is its mirror image.
Per row group:

1. **analyze launch** (``tpu.encode_kernels``): dictionary build for
   every dict-candidate numeric column, DELTA offset preparation, and
   BYTE_STREAM_SPLIT transposition — one fused executable through the
   persistent exec cache.
2. The host reads the launch's tiny scalars (distinct counts, max
   offsets), applies the SAME dictionary acceptance rule as the host
   encoder (``dictionary_max_fraction`` / ``dictionary_max_bytes``),
   and picks static pack widths.
3. **pack launch**: every accepted index/offset stream bit-packs in a
   second fused executable.
4. Host page assembly: hybrid run headers, delta block headers, page
   statistics, levels, page headers, CRCs — all through the ONE
   pagination path in ``format/file_write.py``
   (:class:`~parquet_floor_tpu.format.file_write.PrecomputedPages`), so
   a device-encoded chunk is metadata-identical in kind to a
   host-encoded one.
5. Compression runs on a thread pool BEHIND the device encode of the
   next group (the inverse of the measured decode boundary in
   docs/DESIGN_DECOMPRESSION.md), and :class:`DeviceFileWriter` emits
   finished groups to the sink strictly in order.

Routing is per COLUMN: flat INT32/INT64/FLOAT/DOUBLE columns ride the
device; strings, booleans, fixed-width, repeated columns, empty chunks,
and data-dependent fallbacks (dictionary rejected, delta offsets wider
than 32 bits) encode on host inside the same pool — one writer, mixed
chunks, identical file shape either way.

Like the decode engine, the device path requires ``jax_enable_x64``
(INT64/DOUBLE encode exactness).
"""

from __future__ import annotations

import os
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..errors import checked_alloc_size
from ..format.encodings.delta import _write_varint, _write_zigzag
from ..format.encodings.dictionary import encode_dict_indices
from ..format.file_write import (
    ColumnData,
    ParquetFileWriter,
    PrecomputedPages,
    WriterOptions,
    _ColumnChunkWriter,
    _NUMPY_DTYPE,
)
from ..format.parquet_thrift import Encoding, Type
from ..utils import trace

#: device page boundaries align to the DELTA block geometry (128) so
#: every page's packed payload is a byte-aligned slice of the fused
#: contiguous stream (module docstring of tpu/encode_kernels.py)
_PAGE_ALIGN = 128

_VIEW_DTYPE = {
    Type.INT32: np.dtype("<u4"),
    Type.INT64: np.dtype("<u8"),
    Type.FLOAT: np.dtype("<u4"),
    Type.DOUBLE: np.dtype("<u8"),
}


def _varint_bytes(n: int) -> bytes:
    out = bytearray()
    _write_varint(out, n)
    return bytes(out)


def _zigzag_bytes(n: int) -> bytes:
    out = bytearray()
    _write_zigzag(out, int(n))
    return bytes(out)


class _ColRoute:
    """Per-column device-encode plan for one row group."""

    __slots__ = ("kind", "positions", "per_page", "present", "vlo",
                 "spec", "view", "width", "dictionary", "encoding",
                 "min_delta", "packed", "full", "tail")

    def __init__(self, kind: str):
        self.kind = kind          # dict | delta | bss | host
        self.positions = None     # page boundaries (level positions)
        self.per_page = 0
        self.present = None       # per-page non-null counts
        self.vlo = None           # per-page starting value index
        self.spec = None          # EncSpec of the analyze launch
        self.view = None          # unsigned bit view of the values
        self.width = 0            # chosen pack width
        self.dictionary = None    # host dictionary values (dict path)
        self.encoding = Encoding.PLAIN
        self.min_delta = 0        # delta: signed global min
        self.packed = b""         # fused pack launch output bytes
        self.full = b""           # bss: full-page transposed bytes
        self.tail = b""           # bss: partial tail page bytes


class EncodeEngine:
    """Fused device encode of row groups for one schema/options pair.

    :meth:`device_precompute` returns one
    :class:`~parquet_floor_tpu.format.file_write.PrecomputedPages` (or
    None = host fallback) per column; callers hand them to
    ``_ColumnChunkWriter.prepare`` — typically on a worker pool, which
    is exactly what :class:`DeviceFileWriter` does."""

    def __init__(self, schema, options: WriterOptions, device=None):
        from ..tpu.engine import _require_x64

        _require_x64()
        self.schema = schema
        self.options = options
        self.device = device

    # -- routing -------------------------------------------------------------

    def _dict_enabled(self, desc) -> bool:
        opt = self.options
        enable = opt.enable_dictionary
        if opt.column_dictionary is not None:
            enable = opt.column_dictionary.get(desc.path[0], enable)
        if opt.column_encodings and desc.path[0] in opt.column_encodings:
            enable = False
        return enable

    def _page_positions(self, cd: ColumnData) -> Tuple[int, list]:
        """Aligned page boundaries for a flat device column: the host
        per-page target rounded DOWN to the 128-value grid (never below
        128) so dict/delta payload slices stay byte-aligned."""
        per = max(1, self.options.data_page_values)
        if self.options.data_page_bytes:
            # byte-bound composition, numeric flat columns only: the
            # host estimate simplifies to itemsize per slot
            isz = _NUMPY_DTYPE[cd.descriptor.physical_type].itemsize
            per = max(
                1, min(per, int(self.options.data_page_bytes / isz))
            )
        per = max(_PAGE_ALIGN, per - (per % _PAGE_ALIGN))
        n = cd.num_values
        positions = [
            (i, min(i + per, n)) for i in range(0, n, per)
        ] or [(0, 0)]
        return per, positions

    def _route(self, cd: ColumnData) -> _ColRoute:
        from ..tpu.encode_kernels import EncSpec

        desc = cd.descriptor
        opt = self.options
        pt = desc.physical_type
        values = cd.values
        if (
            desc.max_repetition_level > 0
            or pt not in _VIEW_DTYPE
            or len(values) == 0
        ):
            return _ColRoute("host")
        optional = cd.def_levels is not None
        view = np.ascontiguousarray(
            np.asarray(values, dtype=_NUMPY_DTYPE[pt])
        ).view(_VIEW_DTYPE[pt])
        n = len(view)
        dtype = str(view.dtype)
        route = None
        if self._dict_enabled(desc):
            route = _ColRoute("dict")
            route.spec = EncSpec("dict", dtype, n)
            route.encoding = Encoding.RLE_DICTIONARY
        else:
            enc = _ColumnChunkWriter(opt, desc)._choose_value_encoding(
                values
            )
            if enc == Encoding.DELTA_BINARY_PACKED and not optional:
                route = _ColRoute("delta")
                route.spec = EncSpec("delta", dtype, n)
                route.encoding = enc
            elif enc == Encoding.BYTE_STREAM_SPLIT and not optional:
                route = _ColRoute("bss")
                route.encoding = enc
            else:
                # PLAIN is an identity copy (no device leverage) and
                # optional delta/bss pages have data-dependent value
                # counts — the host pagination handles both
                return _ColRoute("host")
        route.view = view
        per, positions = self._page_positions(cd)
        route.per_page, route.positions = per, positions
        if cd.def_levels is not None:
            dl = np.asarray(cd.def_levels)
            md = desc.max_definition_level
            route.present = [
                int(np.count_nonzero(dl[lo:hi] == md))
                for lo, hi in positions
            ]
        else:
            route.present = [hi - lo for lo, hi in positions]
        route.vlo = np.concatenate(
            [[0], np.cumsum(route.present[:-1])]
        ).astype(np.int64) if len(route.present) > 1 else np.zeros(
            1, np.int64
        )
        if route.kind == "bss":
            route.spec = EncSpec("bss", dtype, n, page_rows=per)
        return route

    # -- the fused launches --------------------------------------------------

    def device_precompute(
        self, columns: Sequence[ColumnData]
    ) -> List[Optional[PrecomputedPages]]:
        from ..tpu import encode_kernels as ek

        routes = [self._route(cd) for cd in columns]
        dev = [
            (r, cd) for r, cd in zip(routes, columns) if r.kind != "host"
        ]
        if not dev:
            trace.count("write.host_columns", len(routes))
            return [None] * len(routes)
        program = tuple(r.spec for r, _ in dev)
        arrays = [r.view for r, _ in dev]
        outs = ek.run_analyze(program, arrays, device=self.device)

        # walk the flat outputs; fetch scalars (blocks on the launch)
        oi = 0
        pack_specs: list = []
        pack_arrays: list = []
        pack_routes: list = []
        bss_fetch: list = []  # (route, full, tail) device arrays
        for r, cd in dev:
            if r.kind == "dict":
                indices, count, uniq_pos = outs[oi : oi + 3]
                oi += 3
                n_leaf = len(r.view)
                cnt = int(count)
                isz = r.view.dtype.itemsize
                opt = self.options
                if cnt > max(
                    1, int(n_leaf * opt.dictionary_max_fraction)
                ) or cnt * isz > opt.dictionary_max_bytes:
                    trace.decision("write.engine", {
                        "action": "dict_reject",
                        "column": cd.descriptor.path[0],
                        "distinct": cnt,
                    })
                    r.kind = "host"
                    continue
                upos = np.asarray(uniq_pos)[:cnt]
                r.dictionary = np.asarray(
                    cd.values, dtype=_NUMPY_DTYPE[
                        cd.descriptor.physical_type
                    ]
                )[upos]
                r.width = ek.pack_width_for(
                    max((cnt - 1).bit_length(), 1)
                )
                pack_specs.append(ek.EncSpec(
                    "pack", "uint32", n_leaf, width=r.width
                ))
                pack_arrays.append(indices)
                pack_routes.append(r)
            elif r.kind == "delta":
                offs, min_d, max_off = outs[oi : oi + 3]
                oi += 3
                w_min = int(max_off).bit_length()
                if w_min > 32:
                    trace.decision("write.engine", {
                        "action": "delta_wide",
                        "column": cd.descriptor.path[0],
                        "width": w_min,
                    })
                    r.kind = "host"
                    continue
                r.width = ek.pack_width_for(w_min)
                r.min_delta = int(min_d)
                if r.width:
                    pack_specs.append(ek.EncSpec(
                        "pack", "uint32", max(len(r.view) - 1, 0),
                        width=r.width,
                    ))
                    pack_arrays.append(offs)
                    pack_routes.append(r)
            else:  # bss
                bss_fetch.append((r,) + tuple(outs[oi : oi + 2]))
                oi += 2

        if pack_specs:
            packed = ek.run_pack(
                tuple(pack_specs), pack_arrays, device=self.device
            )
            for r, arr in zip(pack_routes, packed):
                r.packed = np.asarray(arr).tobytes()
        for r, full, tail in bss_fetch:
            r.full = np.asarray(full).tobytes()
            r.tail = np.asarray(tail).tobytes()

        out: List[Optional[PrecomputedPages]] = []
        n_dev = 0
        for r, cd in zip(routes, columns):
            if r.kind == "host":
                out.append(None)
                continue
            n_dev += 1
            out.append(self._assemble(r, cd))
        trace.count("write.device_columns", n_dev)
        trace.count("write.host_columns", len(routes) - n_dev)
        return out

    # -- host page assembly --------------------------------------------------

    def _assemble(self, r: _ColRoute, cd: ColumnData) -> PrecomputedPages:
        if r.kind == "dict":
            payloads = self._dict_payloads(r)
        elif r.kind == "delta":
            payloads = self._delta_payloads(r, cd)
        else:
            payloads = self._bss_payloads(r)
        return PrecomputedPages(
            value_encoding=r.encoding,
            positions=r.positions,
            page_payloads=payloads,
            dictionary=r.dictionary,
        )

    def _dict_payloads(self, r: _ColRoute) -> List[bytes]:
        """Per-page RLE_DICTIONARY streams: width byte + one bit-packed
        run sliced out of the fused contiguous pack.  Aligned (required
        columns) pages slice bytes zero-copy; ragged (optional) pages
        realign through one C-level unpack/pack."""
        w = r.width
        payloads = []
        aligned = all(v * w % 8 == 0 for v in r.vlo)
        bits = None
        for pi in range(len(r.positions)):
            present = r.present[pi]
            if present == 0:
                payloads.append(
                    encode_dict_indices(
                        np.zeros(0, np.uint32), max(1 << w, 2)
                    )
                )
                continue
            vlo = int(r.vlo[pi])
            groups8 = -(-present // 8)
            head = bytes([w]) + _varint_bytes((groups8 << 1) | 1)
            nbytes = groups8 * w
            if aligned:
                start = vlo * w // 8
                body = r.packed[start : start + nbytes]
                if len(body) < nbytes:
                    body = body + b"\x00" * (nbytes - len(body))
            else:
                if bits is None:
                    bits = np.unpackbits(
                        np.frombuffer(r.packed, np.uint8),
                        bitorder="little",
                    )
                sel = bits[vlo * w : (vlo + present) * w]
                pad = nbytes * 8 - len(sel)
                if pad:
                    sel = np.concatenate([
                        sel,
                        np.zeros(
                            checked_alloc_size(pad, "dict page pad"),
                            np.uint8,
                        ),
                    ])
                body = np.packbits(sel, bitorder="little").tobytes()
            payloads.append(head + body)
        return payloads

    def _delta_payloads(self, r: _ColRoute, cd: ColumnData) -> List[bytes]:
        """Per-page DELTA_BINARY_PACKED streams: standard 128/4
        geometry, one global ``min_delta`` re-declared per block, all
        four miniblock widths equal to the fused pack width — each
        block's payload is a byte-aligned 16*w-byte slice of the
        contiguous device pack (page starts sit on the 128 grid)."""
        w = r.width
        values = np.asarray(cd.values)
        mind = _zigzag_bytes(getattr(r, "min_delta", 0))
        widths = bytes([w, w, w, w])
        payloads = []
        for pi, (lo, hi) in enumerate(r.positions):
            page_n = hi - lo
            out = bytearray()
            _write_varint(out, 128)
            _write_varint(out, 4)
            _write_varint(out, page_n)
            _write_zigzag(out, int(values[lo]) if page_n else 0)
            n_deltas = max(page_n - 1, 0)
            for b in range(-(-n_deltas // 128) if n_deltas else 0):
                out += mind
                out += widths
                if w:
                    start = (lo + b * 128) * w // 8
                    blk = r.packed[start : start + 16 * w]
                    if len(blk) < 16 * w:
                        blk = blk + b"\x00" * (16 * w - len(blk))
                    out += blk
            payloads.append(bytes(out))
        return payloads

    def _bss_payloads(self, r: _ColRoute) -> List[bytes]:
        isz = r.view.dtype.itemsize
        per = r.per_page
        payloads = []
        k_full = len(r.view) // per
        for pi, (lo, hi) in enumerate(r.positions):
            if pi < k_full:
                payloads.append(
                    r.full[pi * per * isz : (pi + 1) * per * isz]
                )
            else:
                payloads.append(r.tail)
        return payloads


class DeviceFileWriter(ParquetFileWriter):
    """:class:`ParquetFileWriter` with the fused device encode engine
    and the encode ‖ compress ‖ write pipeline (module docstring).

    ``write_row_group`` runs the group's device launches synchronously
    (they are the cheap part and keep the device busy), hands every
    column's pagination + compression to the pool, and emits FINISHED
    groups to the sink strictly in submission order — at most
    ``WriterOptions.write_pipeline_depth`` groups ride in flight, so
    memory stays bounded while group *k*'s compression overlaps group
    *k+1*'s encode."""

    def __init__(self, dest, schema, options: Optional[WriterOptions] = None,
                 key_value_metadata: Optional[Dict[str, str]] = None,
                 device=None, use_device: bool = True):
        """``use_device=False`` keeps the full pipeline (pooled
        per-column prepare + ordered emit) but skips the fused launches
        — every column host-encodes on the pool.  That is the
        ``engine="pipelined"`` writer: the parallel host encoder for
        environments without a usable jax backend (and the fair host
        comparator for the write bench)."""
        if options is None:
            options = WriterOptions(engine="tpu")
        super().__init__(dest, schema, options, key_value_metadata)
        try:
            # the engine check can raise (no jax backend / x64 off) —
            # the sink the base ctor just opened must not leak (the
            # same ctor-guard contract ParquetFileWriter itself holds)
            self._engine = (
                EncodeEngine(schema, self.options, device=device)
                if use_device else None
            )
            self._tracer = trace.current()
            self._pool = ThreadPoolExecutor(
                max_workers=self.options.compress_threads
                or min(4, os.cpu_count() or 1),
                thread_name_prefix="pftpu-write",
            )
        except BaseException:
            self.sink.close()
            raise
        self._inflight: deque = deque()  # (futures, num_rows)
        self._depth = max(1, self.options.write_pipeline_depth)

    def write_row_group(self, columns: Sequence[ColumnData]) -> None:
        if self._closed:
            raise ValueError("writer is closed")
        expected = self.schema.columns
        if len(columns) != len(expected):
            raise ValueError(
                f"row group has {len(columns)} columns, schema has "
                f"{len(expected)}"
            )
        num_rows = None
        for cd, desc in zip(columns, expected):
            if cd.descriptor.path != desc.path:
                raise ValueError(
                    f"column order mismatch: got {cd.descriptor.path}, "
                    f"want {desc.path}"
                )
            rows = (
                int(np.count_nonzero(np.asarray(cd.rep_levels) == 0))
                if cd.rep_levels is not None
                else cd.num_values
            )
            if num_rows is None:
                num_rows = rows
            elif rows != num_rows:
                raise ValueError(
                    f"column {desc.path}: {rows} rows != {num_rows}"
                )
        if self._engine is not None:
            with trace.span("write.encode", attrs={
                "row_group": len(self._row_groups) + len(self._inflight),
                "rows": num_rows or 0,
            }):
                pres = self._engine.device_precompute(columns)
        else:
            pres = [None] * len(columns)
            trace.count("write.host_columns", len(columns))
        futs = [
            self._pool.submit(
                self._tracer.run,
                _ColumnChunkWriter(self.options, desc).prepare, cd, pre,
            )
            for cd, desc, pre in zip(columns, expected, pres)
        ]
        self._inflight.append((futs, num_rows or 0))
        trace.count("write.groups")
        trace.count("write.rows", num_rows or 0)
        trace.gauge_max("write.inflight_groups_max", len(self._inflight))
        # opportunistic in-order drain, then enforce the depth bound
        while self._inflight and all(
            f.done() for f in self._inflight[0][0]
        ):
            self._emit_head()
        while len(self._inflight) > self._depth:
            self._emit_head()

    def _emit_head(self) -> None:
        futs, num_rows = self._inflight.popleft()
        try:
            prepared = [f.result() for f in futs]
        except BaseException:
            for f in futs:
                f.cancel()
            raise
        with trace.span("write.emit", attrs={"rows": num_rows},
                        observe="write.emit_seconds"):
            pos0 = self.sink.pos
            self.write_prepared_group(prepared, num_rows)
            trace.count("write.bytes_written", self.sink.pos - pos0)

    def close(self):
        if self._closed:
            return self._file_meta
        try:
            while self._inflight:
                self._emit_head()
        except BaseException:
            self.abort()
            raise
        self._pool.shutdown(wait=True)
        return super().close()

    def abort(self) -> None:
        for futs, _ in self._inflight:
            for f in futs:
                f.cancel()
        self._inflight.clear()
        self._pool.shutdown(wait=False)
        super().abort()


def resolve_writer(dest, schema, options: Optional[WriterOptions] = None,
                   key_value_metadata: Optional[Dict[str, str]] = None,
                   device=None) -> ParquetFileWriter:
    """The ``WriterOptions.engine`` switch: "host" → the numpy
    :class:`ParquetFileWriter`, "tpu" → :class:`DeviceFileWriter`
    (raises without a usable x64 jax backend, mirroring
    ``TpuRowGroupReader``), "pipelined" → the same pipeline with every
    column host-encoded on the pool (no jax needed), "auto" → tpu when
    the backend is up, host otherwise (``write.engine`` decision
    records the pick)."""
    opts = options or WriterOptions()
    engine = opts.engine
    if engine not in ("host", "tpu", "auto", "pipelined"):
        raise ValueError(f"bad WriterOptions.engine {engine!r}")
    if engine == "auto":
        # the cost-model shape of the decode side's engine.auto: the
        # fused encode launches win on a real accelerator, but on the
        # CPU backend their per-launch fixed cost loses to the pooled
        # host encoders — auto picks the faster pipeline either way
        try:
            import jax

            dev = jax.devices()[0]
            if not jax.config.jax_enable_x64:
                raise RuntimeError("x64 disabled")
            engine = "tpu" if dev.platform != "cpu" else "pipelined"
            trace.decision("write.engine", {
                "action": f"auto_{engine}", "platform": dev.platform,
            })
        except Exception as e:
            trace.decision("write.engine", {
                "action": "auto_host", "reason": str(e)[:120],
            })
            engine = "host"
    if engine == "tpu":
        return DeviceFileWriter(
            dest, schema, opts, key_value_metadata, device=device
        )
    if engine == "pipelined":
        return DeviceFileWriter(
            dest, schema, opts, key_value_metadata, use_device=False
        )
    return ParquetFileWriter(dest, schema, opts, key_value_metadata)
