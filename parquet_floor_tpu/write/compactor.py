"""Dataset compaction / re-writing service (docs/write.md).

Production stores churn data: small-file sprawl from incremental
ingestion, row groups sized for the writer's memory instead of the
scanner's schedule, encodings chosen before the data's shape was known,
and — after an incident — corpora that only read under ``salvage=True``.
:class:`DatasetCompactor` streams a corpus through the scan scheduler
(:class:`~parquet_floor_tpu.scan.executor.DatasetScanner`) and re-writes
it at scan speed through the device write engine:

* **re-shard** — output row groups cut at ``target_row_group_rows``
  (every group exact except each file's last), files rotated at
  ``target_file_rows``; boundaries are PLANNED up front from the
  corpus's unit-row prefix sums (the order plan's arithmetic —
  ``data.order.EpochPlan``), so output geometry is deterministic before
  a row is read.
* **re-sort** — ``unit_order`` replays units in an explicit order
  (the scanner's permuted-delivery face), and ``sort_by`` sorts rows
  WITHIN each output row group (recorded as ``sorting_columns`` in the
  output metadata).
* **re-encode / re-compress** — output codec/encodings come from the
  ``WriterOptions`` handed in; the writer is resolved through
  ``write.resolve_writer``, so the fused device encode path carries the
  compaction by default.
* **salvage retirement** — with ``salvage=True`` the read leg decodes
  through the salvage engine: page-null quarantines flow through as
  ordinary nulls (legal data now), and any unit with GEOMETRY damage
  (row-mask or chunk tier — its surviving columns no longer agree on a
  row set the output schema could represent) is dropped WHOLE and
  counted.  The output corpus needs no salvage to read and a fresh
  :class:`~parquet_floor_tpu.quarantine.QuarantineMap` over it stays
  empty — the map retires with the corrupt bytes (pinned by test).

Flat schemas only (the row-slicing carry buffer does not re-shard
repeated columns; compact those with the host writer per file).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np

from ..data.order import EpochPlan, Unit
from ..errors import UnsupportedFeatureError, checked_alloc_size
from ..format.encodings.plain import ByteArrayColumn
from ..format.file_read import ParquetFileReader, SalvageReport
from ..format.file_write import ColumnData, WriterOptions
from ..format.schema import MessageType
from ..io.source import FileSource
from ..scan.executor import DatasetScanner
from ..scan.plan import ScanOptions
from ..utils import trace
from .encode import resolve_writer


@dataclass
class CompactOptions:
    """Knobs of one compaction run (module docstring)."""

    target_row_group_rows: int = 1 << 20
    target_file_rows: Optional[int] = None   # None = one output file
    writer: Optional[WriterOptions] = None   # output codec/encodings/engine
    columns: Optional[Sequence[str]] = None  # top-level projection
    sort_by: Optional[Sequence[str]] = None  # within-group row sort
    unit_order: Optional[Sequence] = None    # explicit (file, group) order
    # secondary-index sidecars (query/index.py): one key → row-span
    # index emitted per named column, fingerprinted against the output
    # files — the point-probe rung for NON-sort columns
    index_columns: Optional[Sequence[str]] = None
    salvage: bool = False
    reader: Optional[object] = None          # ReaderOptions overrides
    scan: Optional[ScanOptions] = None
    # Read leg: "tpu" streams the corpus through scan_device_groups
    # (decode at device-scan speed, the compact_leg bench shape),
    # "host" through DatasetScanner, "auto" picks tpu whenever it can —
    # salvage and unit_order pin host (per-unit salvage reports and
    # explicit unit order are host-scanner faces).
    read_leg: str = "auto"

    def __post_init__(self):
        if self.target_row_group_rows < 1:
            raise ValueError(
                f"target_row_group_rows must be >= 1, got "
                f"{self.target_row_group_rows}"
            )
        if self.target_file_rows is not None and \
                self.target_file_rows < self.target_row_group_rows:
            raise ValueError(
                "target_file_rows must be >= target_row_group_rows"
            )
        if self.read_leg not in ("auto", "host", "tpu"):
            raise ValueError(f"bad read_leg {self.read_leg!r}")
        if self.read_leg == "tpu" and (
            self.salvage or self.unit_order is not None
        ):
            raise ValueError(
                "read_leg='tpu' does not compose with salvage or "
                "unit_order (both are host-scanner faces); use "
                "read_leg='auto' or 'host'"
            )


@dataclass
class CompactReport:
    """What one compaction run read, dropped, and wrote."""

    paths: List[str] = field(default_factory=list)
    rows_in: int = 0
    rows_out: int = 0
    rows_dropped: int = 0           # geometry-damaged units (salvage)
    units_in: int = 0
    units_dropped: int = 0
    groups_out: int = 0
    group_rows: List[int] = field(default_factory=list)
    index_paths: List[str] = field(default_factory=list)
    wall_seconds: float = 0.0
    salvage: Optional[SalvageReport] = None

    @property
    def rows_per_sec(self) -> float:
        return self.rows_in / self.wall_seconds if self.wall_seconds else 0.0

    def as_dict(self) -> dict:
        return {
            "paths": list(self.paths),
            "rows_in": self.rows_in,
            "rows_out": self.rows_out,
            "rows_dropped": self.rows_dropped,
            "units_in": self.units_in,
            "units_dropped": self.units_dropped,
            "groups_out": self.groups_out,
            "group_rows": list(self.group_rows),
            "index_paths": list(self.index_paths),
            "wall_seconds": round(self.wall_seconds, 6),
            "rows_per_sec": round(self.rows_per_sec, 1),
        }


class _ColumnBuffer:
    """Carry buffer of one flat column across unit boundaries: decoded
    chunks append; :meth:`cut` slices exactly ``k`` rows off the front
    (re-slicing across chunk boundaries, the batcher's carry shape)."""

    __slots__ = ("desc", "values", "defs", "rows")

    def __init__(self, desc):
        self.desc = desc
        self.values: list = []   # per-chunk values (non-null only)
        self.defs: list = []     # per-chunk def_levels (or None)
        self.rows = 0

    def append(self, values, def_levels) -> None:
        n = (
            len(def_levels) if def_levels is not None else len(values)
        )
        self.values.append(values)
        self.defs.append(def_levels)
        self.rows += n

    def _merged(self):
        """Collapse the chunk lists into one (values, defs) pair."""
        if len(self.values) > 1:
            if isinstance(self.values[0], ByteArrayColumn):
                values = ByteArrayColumn.concat(self.values)
            else:
                values = np.concatenate(self.values)
            if self.desc.max_definition_level > 0:
                defs = np.concatenate([
                    d if d is not None else np.full(
                        checked_alloc_size(len(v), "compactor carry"),
                        self.desc.max_definition_level,
                        dtype=np.uint32,
                    )
                    for d, v in zip(self.defs, self.values)
                ])
            else:
                defs = None
            self.values = [values]
            self.defs = [defs]
        return (
            (self.values[0], self.defs[0]) if self.values else (None, None)
        )

    def cut(self, k: int) -> ColumnData:
        """Remove and return the first ``k`` rows as ColumnData."""
        values, defs = self._merged()
        md = self.desc.max_definition_level
        if defs is not None:
            head_defs, tail_defs = defs[:k], defs[k:]
            vk = int(np.count_nonzero(head_defs == md))
            head_vals = self._slice_values(values, 0, vk)
            self.values = [self._slice_values(values, vk, None)]
            self.defs = [tail_defs]
            self.rows -= k
            return ColumnData(self.desc, head_vals, def_levels=head_defs)
        head = self._slice_values(values, 0, k)
        self.values = [self._slice_values(values, k, None)]
        self.defs = [None]
        self.rows -= k
        return ColumnData(
            self.desc, head,
            def_levels=(
                np.full(
                    checked_alloc_size(k, "compactor group rows"),
                    md, dtype=np.uint32,
                ) if md > 0 else None
            ),
        )

    @staticmethod
    def _slice_values(values, lo, hi):
        if isinstance(values, ByteArrayColumn):
            n = len(values)
            hi = n if hi is None else min(hi, n)
            off = values.offsets
            return ByteArrayColumn(
                off[lo : hi + 1] - off[lo],
                values.data[off[lo] : off[hi]],
            )
        return values[lo:hi]


def _host_column(bc):
    """Device ``BatchColumn`` → host ``ColumnBatch`` (non-null values +
    def levels — the carry buffer's input shape).  Strings re-pool from
    the device's padded-row layout with one vectorized ragged gather;
    bit-form DOUBLE views back to float64."""
    from ..batch.columns import ColumnBatch

    desc = bc.descriptor
    md = desc.max_definition_level
    mask = np.asarray(bc.mask) if bc.mask is not None else None
    if bc.is_strings:
        rows = np.asarray(bc.values)
        lens = np.asarray(bc.lengths).astype(np.int64)
        n = len(lens)
        ml = rows.shape[1] if rows.ndim == 2 else 0
        keep = np.flatnonzero(~mask) if mask is not None else np.arange(n)
        lens_k = lens[keep]
        offsets = np.zeros(len(keep) + 1, np.int64)
        np.cumsum(lens_k, out=offsets[1:])
        total = int(offsets[-1])
        if total:
            flat = rows.reshape(-1)
            src = np.repeat(keep * ml - offsets[:-1], lens_k) + \
                np.arange(total)
            pool = flat[src]
        else:
            pool = np.zeros(0, np.uint8)
        values = ByteArrayColumn(offsets, pool)
    else:
        vals = np.asarray(bc.values)
        if bc.f64_bits and vals.dtype == np.int64:
            vals = vals.view(np.float64)
        n = len(vals)
        values = vals if mask is None else vals[~mask]
    def_levels = None
    if mask is not None:
        def_levels = np.where(mask, md - 1, md).astype(np.uint32)
    return ColumnBatch(desc, n, values, def_levels=def_levels)


def _sort_group(columns: List[ColumnData], sort_by: Sequence[str]):
    """Stable multi-key within-group row sort, nulls last per key."""
    by_name = {cd.descriptor.path[0]: cd for cd in columns}
    n = columns[0].num_values
    order = np.arange(n)
    for name in reversed(list(sort_by)):
        cd = by_name.get(name)
        if cd is None:
            raise ValueError(f"sort_by: no column named {name!r}")
        md = cd.descriptor.max_definition_level
        nn = checked_alloc_size(n, "sort group rows")
        if cd.def_levels is not None:
            null = cd.def_levels != md
            vidx = np.cumsum(~null) - 1
        else:
            null = np.zeros(nn, dtype=bool)
            vidx = np.arange(n)
        values = cd.values
        if isinstance(values, ByteArrayColumn):
            dense = np.empty(nn, dtype=object)
            data, off = values.data.tobytes(), values.offsets
            for i in np.flatnonzero(~null):
                j = vidx[i]
                dense[i] = data[off[j] : off[j + 1]]
            for i in np.flatnonzero(null):
                dense[i] = b""
        else:
            dense = np.zeros(nn, dtype=np.asarray(values).dtype)
            dense[~null] = np.asarray(values)[vidx[~null]]
        order = order[np.argsort(dense[order], kind="stable")]
        order = order[np.argsort(null[order], kind="stable")]
    return _apply_order(columns, order)


def _index_runs(columns: List[ColumnData], names: Sequence[str]) -> dict:
    """Equal-key row runs of one OUTPUT row group, per indexed column:
    ``{name: [(api_key, row_start, row_end), ...]}`` in row order,
    null rows skipped (nulls are not keys).  Keys are API-typed the
    way a probe supplies them (BINARY stringified via the descriptor,
    exactly like the lookup face's cell conversion), so index probes
    and predicate probes agree on key identity."""
    from ..format.parquet_thrift import Type as _T

    by_name = {cd.descriptor.path[0]: cd for cd in columns}
    out: dict = {}
    for name in names:
        cd = by_name[name]
        desc = cd.descriptor
        md = desc.max_definition_level
        n = int(cd.num_values)
        if cd.def_levels is not None:
            null = cd.def_levels != md
            vidx = np.cumsum(~null) - 1
        else:
            null = np.zeros(
                checked_alloc_size(n, "index runs"), dtype=bool
            )
            vidx = np.arange(n)
        stringify = desc.physical_type in (
            _T.BYTE_ARRAY, _T.FIXED_LEN_BYTE_ARRAY, _T.INT96
        )
        if isinstance(cd.values, ByteArrayColumn):
            data, off = cd.values.data.tobytes(), cd.values.offsets
            dense = np.empty(
                checked_alloc_size(n, "index runs"), dtype=object
            )
            for i in np.flatnonzero(~null):
                j = int(vidx[i])
                dense[i] = data[off[j]:off[j + 1]]
            for i in np.flatnonzero(null):
                dense[i] = b""

            def conv(v, desc=desc):
                return desc.primitive.stringify(v)
        else:
            vals = np.asarray(cd.values)
            dense = np.zeros(
                checked_alloc_size(n, "index runs"), dtype=vals.dtype
            )
            dense[~null] = vals[vidx[~null]]

            def conv(v, stringify=stringify, desc=desc):
                if stringify:
                    v = v.tobytes() if isinstance(v, np.ndarray) else v
                    return desc.primitive.stringify(v)
                return v.item() if hasattr(v, "item") else v
        if n == 0:
            out[name] = []
            continue
        change = np.flatnonzero(
            (dense[1:] != dense[:-1]) | (null[1:] != null[:-1])
        ) + 1
        bounds = [0, *change.tolist(), n]
        runs = []
        for a, b in zip(bounds[:-1], bounds[1:]):
            if null[a]:
                continue
            runs.append((conv(dense[a]), int(a), int(b)))
        out[name] = runs
    return out


def _apply_order(columns: List[ColumnData], order: np.ndarray):
    from ..batch.columns import take_rows

    out = []
    for cd in columns:
        values, new_defs = take_rows(
            cd.values, cd.def_levels,
            cd.descriptor.max_definition_level, order,
        )
        out.append(ColumnData(cd.descriptor, values, def_levels=new_defs))
    return out


class DatasetCompactor:
    """Stream ``sources`` through the scan scheduler and re-write them
    into ``dest`` (a directory — output files are
    ``part-{i:05d}.parquet`` — or a callable ``index -> dest``).  See
    the module docstring for the full contract; :meth:`run` executes
    one compaction and returns a :class:`CompactReport`."""

    def __init__(self, sources: Sequence, dest,
                 options: Optional[CompactOptions] = None):
        self.sources = list(sources)
        self.dest = dest
        self.options = options or CompactOptions()

    # -- planning ------------------------------------------------------------

    def _plan(self):
        """Open every footer once: (metadata list, units, EpochPlan,
        first file's schema).  The plan's row prefix sums fix the
        output boundaries before any data byte is read.  Sources must
        be paths or zero-arg factories (the planning pass and the scan
        each need their own open — a shared live Source object cannot
        be closed twice)."""
        metas = []
        units: List[Unit] = []
        schema = None
        for fi, src in enumerate(self.sources):
            if hasattr(src, "read_at"):
                raise ValueError(
                    "DatasetCompactor sources must be paths or zero-arg "
                    "source factories (an open Source cannot serve both "
                    "the planning pass and the scan)"
                )
            if callable(src):
                reader = ParquetFileReader(src())
            else:
                reader = ParquetFileReader(FileSource(src))
            try:
                metas.append(reader.metadata)
                if schema is None:
                    schema = reader.schema
                for gi, rg in enumerate(reader.row_groups):
                    units.append(Unit(fi, gi, int(rg.num_rows or 0)))
            finally:
                reader.close()
        if self.options.unit_order is not None:
            by_key = {(u.file_index, u.group_index): u for u in units}
            ordered = []
            for fi, gi in self.options.unit_order:
                u = by_key.pop((int(fi), int(gi)), None)
                if u is None:
                    raise ValueError(
                        f"unit_order names unknown or duplicate unit "
                        f"({fi}, {gi})"
                    )
                ordered.append(u)
            units = ordered
        plan = EpochPlan(units, seed=None, epoch=0)
        return metas, units, plan, schema

    def _dest_path(self, index: int) -> str:
        if callable(self.dest):
            return self.dest(index)
        os.makedirs(self.dest, exist_ok=True)
        return os.path.join(self.dest, f"part-{index:05d}.parquet")

    # -- the run -------------------------------------------------------------

    def run(self) -> CompactReport:
        opt = self.options
        t0 = time.perf_counter()
        metas, units, plan, schema = self._plan()
        report = CompactReport()
        if not units:
            report.wall_seconds = time.perf_counter() - t0
            return report

        reader_opts = self._reader_options()
        sel = set(opt.columns) if opt.columns else None
        out_schema = MessageType(schema.name, [
            f for f in schema.fields if sel is None or f.name in sel
        ])
        for desc in out_schema.columns:
            if desc.max_repetition_level > 0:
                raise UnsupportedFeatureError(
                    "DatasetCompactor re-shards flat columns only "
                    f"(repeated column {'.'.join(desc.path)})"
                )
        idx_names = list(opt.index_columns or [])
        if idx_names and opt.salvage:
            # a quarantined chunk of the indexed column has no values —
            # an index built over it would silently prove rows absent
            raise UnsupportedFeatureError(
                "index_columns does not compose with salvage: a "
                "quarantined chunk of an indexed column has no keys to "
                "record — compact without salvage, or drop index_columns"
            )
        out_names = {d.path[0] for d in out_schema.columns}
        for name in idx_names:
            if name not in out_names:
                raise ValueError(
                    f"index_columns names {name!r}, which is not in the "
                    "output schema"
                )
        leg = self._resolve_leg(opt, out_schema)
        scanner = None
        if leg == "host":
            scanner = DatasetScanner(
                self.sources,
                columns=list(opt.columns) if opt.columns else None,
                options=reader_opts,
                scan=opt.scan,
                order=[(u.file_index, u.group_index) for u in units],
                metadata=metas,
            )
            stream = iter(scanner)
        else:
            stream = self._device_units(opt, reader_opts)
        wopts = opt.writer or WriterOptions(engine="auto")
        if opt.sort_by:
            from dataclasses import replace as _rep

            wopts = _rep(
                wopts,
                sorting_columns=[
                    (name, False, False) for name in opt.sort_by
                ],
            )
        G = opt.target_row_group_rows
        F = opt.target_file_rows
        buffers = [_ColumnBuffer(d) for d in out_schema.columns]
        trace.decision("compact.plan", {
            "units": len(units),
            "rows": plan.total_rows,
            "target_group_rows": G,
            "target_file_rows": F,
            "sort_by": list(opt.sort_by) if opt.sort_by else None,
            "read_leg": leg,
        })

        # The write leg runs on its OWN thread behind a bounded queue,
        # so the read leg's decode overlaps the re-encode — compaction
        # wall approaches max(read, write) instead of their sum.  One
        # writer thread keeps emission strictly ordered; the queue bound
        # is the carry-memory backpressure.
        import queue as _queue

        work_q: _queue.Queue = _queue.Queue(maxsize=4)
        werr: list = []  # writer-thread error, raised after join
        # (file_ordinal, group_in_file, {col: [(key, r0, r1), ...]}) per
        # written group — writer-thread-only until join, then the
        # sidecar build reads it
        index_acc: list = []
        tracer = trace.current()

        def writer_loop():
            # the loop consumes until the SENTINEL no matter what: an
            # error is recorded and later items drain, so the producer's
            # bounded put() can never block against a dead consumer (a
            # write failure must surface as a raise, not a hang)
            writer = None
            file_idx = 0
            file_rows = 0
            file_groups = 0
            while True:
                item = work_q.get()
                if item is None:
                    break
                if werr:
                    continue  # drain: the error already recorded
                k, columns = item
                try:
                    if writer is None or (
                        F is not None and file_rows >= F
                    ):
                        if writer is not None:
                            writer.close()
                            writer = None
                        path = self._dest_path(file_idx)
                        report.paths.append(path)
                        writer = resolve_writer(path, out_schema, wopts)
                        file_idx += 1
                        file_rows = 0
                        file_groups = 0
                    if opt.sort_by:
                        columns = _sort_group(columns, opt.sort_by)
                    if idx_names:
                        # runs are cut AFTER the sort: the sidecar's
                        # spans must be the written rows' truth
                        index_acc.append((
                            file_idx - 1, file_groups,
                            _index_runs(columns, idx_names),
                        ))
                    writer.write_row_group(columns)
                    file_groups += 1
                except BaseException as e:  # noqa: BLE001 - raised after join
                    werr.append(e)
                    if writer is not None:
                        writer.abort()
                        writer = None
                    continue
                file_rows += k
                report.rows_out += k
                report.groups_out += 1
                report.group_rows.append(k)
                trace.count("compact.groups_out")
            try:
                if not werr and writer is not None:
                    writer.close()
                    writer = None
            except BaseException as e:  # noqa: BLE001 - raised after join
                werr.append(e)
            finally:
                if writer is not None:
                    writer.abort()

        import threading

        wthread = threading.Thread(
            target=tracer.run, args=(writer_loop,),
            name="pftpu-compact-write",
        )
        wthread.start()

        def flush_group(k: int):
            columns = [b.cut(k) for b in buffers]
            work_q.put((k, columns))
            if werr:
                # raise WITHOUT clearing the flag: writer_loop must keep
                # seeing the error so already-queued groups drain instead
                # of being written into a fresh, wrong-looking part file
                raise werr[0]

        try:
            for unit in stream:
                report.units_in += 1
                trace.count("compact.units_in")
                batch = unit.batch
                n = batch.num_rows
                report.rows_in += n
                trace.count("compact.rows_in", n)
                if opt.salvage and self._unit_damaged(unit, out_schema):
                    report.units_dropped += 1
                    report.rows_dropped += n
                    trace.count("compact.rows_dropped", n)
                    trace.decision("compact.unit_dropped", {
                        "file": unit.file_index,
                        "row_group": unit.group_index,
                        "rows": n,
                    })
                    continue
                by_name = {
                    cb.descriptor.path: cb for cb in batch.columns
                }
                for buf in buffers:
                    cb = by_name.get(buf.desc.path)
                    if cb is None:
                        raise ValueError(
                            f"unit (file {unit.file_index}, group "
                            f"{unit.group_index}) missing column "
                            f"{'.'.join(buf.desc.path)}"
                        )
                    buf.append(cb.values, cb.def_levels)
                while buffers[0].rows >= G:
                    flush_group(G)
            if buffers[0].rows:
                flush_group(buffers[0].rows)
        except BaseException:
            werr.insert(0, None)  # poison: writer drains + aborts
            raise
        finally:
            work_q.put(None)
            wthread.join()
            # quiesce whichever read leg drove the run: closing the
            # device generator joins the engine pipeline; closing the
            # scanner drains its worker pool and file handles
            if scanner is not None:
                scanner.close()
            else:
                stream.close()
        if werr and werr[0] is not None:
            raise werr[0]
        report.salvage = (
            scanner.salvage_report if scanner is not None else None
        )
        if idx_names and report.paths:
            self._emit_indexes(report, idx_names, index_acc)
        report.wall_seconds = time.perf_counter() - t0
        return report

    def _emit_indexes(self, report: CompactReport, idx_names,
                      index_acc) -> None:
        """Build + save one ``SecondaryIndex`` sidecar per indexed
        column (``<column>.index.json`` beside the output files),
        fingerprinting the just-written parts — the install-time
        soundness gate ``serve.Dataset.install_index`` checks."""
        from ..quarantine import fingerprint as file_fingerprint
        from ..query.index import SecondaryIndex

        fps = []
        for path in report.paths:
            src = FileSource(path)
            try:
                fps.append(file_fingerprint(src))
            finally:
                src.close()
        for name in idx_names:
            idx = SecondaryIndex(name)
            for path, fp in zip(report.paths, fps):
                idx.add_file(os.path.basename(path), fp)
            for fi, gi, runs in index_acc:
                for key, r0, r1 in runs.get(name, []):
                    idx.add_span(key, fi, gi, r0, r1)
            side = os.path.join(
                os.path.dirname(report.paths[0]), f"{name}.index.json"
            )
            report.index_paths.append(idx.save(side))
            trace.count("compact.index_keys", len(idx))

    def _resolve_leg(self, opt: CompactOptions, out_schema) -> str:
        if opt.read_leg != "auto" and any(
            c.max_definition_level > 1 for c in out_schema.columns
        ) and opt.read_leg == "tpu":
            raise UnsupportedFeatureError(
                "read_leg='tpu' cannot compact multi-level optional "
                "columns (the device face ships a row null-mask, not "
                "the full definition levels); use read_leg='host'"
            )
        if opt.read_leg != "auto":
            return opt.read_leg
        if opt.salvage or opt.unit_order is not None:
            return "host"
        if any(
            c.max_definition_level > 1 for c in out_schema.columns
        ):
            # nested-optional structure (outer null vs inner null) only
            # survives through real definition levels — the host leg's
            # shape; the device face ships a single row null-mask
            return "host"
        try:
            import jax

            jax.devices()
            if not jax.config.jax_enable_x64:
                raise RuntimeError("x64 disabled")
            return "tpu"
        except Exception:
            return "host"

    def _device_units(self, opt: CompactOptions, reader_opts):
        """The device read leg: stream the corpus through
        ``scan_device_groups`` (decode at device-scan speed) and convert
        each delivered group to the carry buffer's host shape."""
        from ..api.reader import _device_batch_columns
        from ..batch.columns import RowGroupBatch
        from ..scan.executor import ScanUnit, scan_device_groups

        for fi, gi, cols in scan_device_groups(
            self.sources,
            columns=list(opt.columns) if opt.columns else None,
            options=reader_opts,
            scan=opt.scan,
            float64_policy="float64",
        ):
            columns = [
                _host_column(bc)
                for bc in _device_batch_columns(list(cols.values()))
            ]
            n = columns[0].num_values if columns else 0
            yield ScanUnit(fi, gi, RowGroupBatch(
                columns=columns, num_rows=n,
            ))

    # -- helpers -------------------------------------------------------------

    def _reader_options(self):
        from dataclasses import replace as _rep

        from ..api.reader import ReaderOptions

        base = self.options.reader
        if base is None:
            return ReaderOptions(salvage=True) if self.options.salvage \
                else None
        return _rep(base, salvage=base.salvage or self.options.salvage)

    @staticmethod
    def _unit_damaged(unit, out_schema) -> bool:
        """True when this unit's salvage report shows GEOMETRY damage —
        row-mask/chunk tiers, whose surviving columns cannot be
        re-written under the output schema (page-null tiers flow
        through as ordinary nulls)."""
        rep = unit.salvage
        if rep is None:
            return False
        if rep.geometry_damaged(unit.group_index):
            return True
        return any(
            rep.chunk_quarantined(unit.group_index, d.path[0])
            for d in out_schema.columns
        )
