"""Device-speed write path (docs/write.md): the encode mirror of the
decode engine plus the dataset compaction service.

* :class:`~parquet_floor_tpu.write.encode.EncodeEngine` /
  :class:`~parquet_floor_tpu.write.encode.DeviceFileWriter` — fused
  per-row-group device encode (dictionary build, index/delta
  bit-packing, byte-stream-split) with host page
  assembly + compression pipelined behind the launches.
* :func:`~parquet_floor_tpu.write.encode.resolve_writer` — the
  ``WriterOptions.engine`` switch ("host" | "tpu" | "auto").
* :class:`~parquet_floor_tpu.write.compactor.DatasetCompactor` — stream
  a corpus through the scan scheduler and re-shard / re-sort /
  re-encode / re-compress it at scan speed (salvage honored on the
  read leg, so a quarantined corpus compacts into a clean one).
"""

from .encode import DeviceFileWriter, EncodeEngine, resolve_writer
from .compactor import CompactOptions, CompactReport, DatasetCompactor

__all__ = [
    "DeviceFileWriter",
    "EncodeEngine",
    "resolve_writer",
    "CompactOptions",
    "CompactReport",
    "DatasetCompactor",
]
