"""Persistent quarantine map — the sidecar that makes corruption a
*remembered* fact instead of a rediscovered one.

Salvage mode (``docs/robustness.md``) quarantines damaged units as it
trips over them; on a large corpus every re-scan pays the same decode
failures again (a corrupt page can cost a full decompress + decode
attempt before it raises).  A :class:`QuarantineMap` records each file's
quarantined units in a small JSON sidecar keyed by a **file
fingerprint**, so a later scan with the same map short-circuits the
known-bad units: chunk-level quarantines skip the chunk's bytes
entirely, page-level quarantines substitute the recorded outcome
(all-null page or row-mask placeholder) without re-attempting the
decode.  The replayed quarantine records are byte-identical to the ones
a fresh scan would produce, so the map never changes *what* is lost —
only how cheaply the loss is re-established.

Usage::

    from parquet_floor_tpu import ReaderOptions
    from parquet_floor_tpu.quarantine import QuarantineMap

    qmap = QuarantineMap.open("corpus.quarantine.json")
    opts = ReaderOptions(salvage=True, quarantine_map=qmap)
    ... scan the corpus through any salvage-capable face ...
    qmap.save()          # persist what this scan learned

Two fingerprint modes, chosen per map (``QuarantineMap(...,
fingerprint=...)``, persisted in the sidecar so every scan of one map
keys consistently; select the map itself via
``ReaderOptions(quarantine_map=...)``):

* ``"tail"`` (default): ``"<size>:<crc32 of the last 4 KiB>"`` — cheap
  (one tail read, no full-file hash), stable for immutable Parquet
  files (the footer lives in the tail, so a rewritten file
  re-fingerprints).  The deliberate blind spot: an **in-place repair
  that preserves size and tail bytes** (restoring a mid-file region
  from a replica) keeps the old fingerprint, so stale quarantines
  replay onto the now-healthy file.  The loss is never silent — every
  replay lands in the
  :class:`~parquet_floor_tpu.format.file_read.SalvageReport` and as a
  ``salvage.map_skip`` trace decision — but the remedy after an
  in-place repair is to delete (or rebuild) the sidecar.
* ``"content"``: ``"<size>:c:<crc32 of the whole file>"`` — closes that
  blind spot exactly: any byte changing anywhere re-fingerprints, so an
  in-place mid-file repair misses the map and the clean decode
  re-establishes the truth.  The price is one full sequential read per
  file open — right for repair-prone local corpora, wrong for remote
  stores (a full-object GET per open).

Either way the fingerprint is computed through whatever source wrapper
the scan reads through, so a fault-injected test source fingerprints
its *injected* view consistently.  Files repaired the normal way —
rewritten through a writer — re-fingerprint under both modes, because
the footer bytes move.

Thread-safety: ``record``/``lookup``/``save`` may be called from any
thread (scan workers record concurrently); ``save`` writes atomically
(temp file + rename) so a crashed scan never leaves a truncated map.
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from typing import Dict, List, Optional

_VERSION = 1
_TAIL_BYTES = 4096
_CONTENT_CHUNK = 1 << 20
_FINGERPRINT_MODES = ("tail", "content")


def fingerprint(source, mode: str = "tail") -> str:
    """The map key for one positional source (module docstring):
    ``"tail"`` → ``"<size>:<crc32(tail)>"``, ``"content"`` →
    ``"<size>:c:<crc32(whole file)>"``.

    Reads through the source itself (so wrappers — retries, fault
    injection, prefetch caches — fingerprint the bytes the scan
    actually sees); content mode streams in 1 MiB chunks, never
    materializing the file."""
    if mode not in _FINGERPRINT_MODES:
        raise ValueError(
            f"unknown fingerprint mode {mode!r} "
            f"(choose from {_FINGERPRINT_MODES})"
        )
    size = int(source.size)
    if mode == "content":
        crc = 0
        for off in range(0, size, _CONTENT_CHUNK):
            n = min(_CONTENT_CHUNK, size - off)
            # crc32 takes any buffer: no bytes() copy on top of the read
            crc = zlib.crc32(source.read_at(off, n), crc)
        return f"{size}:c:{crc & 0xFFFFFFFF:08x}"
    n = min(_TAIL_BYTES, size)
    tail = bytes(source.read_at(size - n, n)) if n else b""
    return f"{size}:{zlib.crc32(tail) & 0xFFFFFFFF:08x}"


class QuarantineMap:
    """In-memory view of a quarantine sidecar (see module docstring).

    ``entries(fp)`` returns the recorded unit list for one file
    fingerprint; ``record(fp, skips)`` folds new
    :class:`~parquet_floor_tpu.format.file_read.SalvageSkip` records in
    (deduplicated on ``(row_group, column, page, kind)``).
    """

    def __init__(self, path: Optional[str] = None,
                 fingerprint: str = "tail"):
        if fingerprint not in _FINGERPRINT_MODES:
            raise ValueError(
                f"unknown fingerprint mode {fingerprint!r} "
                f"(choose from {_FINGERPRINT_MODES})"
            )
        self.path = os.fspath(path) if path is not None else None
        self.fingerprint = fingerprint
        self._lock = threading.Lock()
        self._files: Dict[str, dict] = {}

    # -- persistence --------------------------------------------------------

    @classmethod
    def open(cls, path, fingerprint: Optional[str] = None) -> "QuarantineMap":
        """Load the sidecar at ``path``, or start an empty map bound to
        it when the file does not exist yet (``fingerprint`` then picks
        the new map's mode, default ``"tail"``).  An existing sidecar's
        PERSISTED mode always applies — its keys were computed under it
        — and an explicit conflicting ``fingerprint`` raises rather
        than silently mis-keying every lookup.  A sidecar that does not
        parse raises ``ValueError`` — a corrupt *map* must never
        silently discard the quarantine history it was supposed to
        carry."""
        p = os.fspath(path)
        if os.path.exists(p):
            try:
                with open(p, "rb") as fh:
                    data = json.loads(fh.read().decode("utf-8"))
            except (OSError, MemoryError):
                raise
            except Exception as e:
                raise ValueError(
                    f"quarantine map {p!r} does not parse: {e}"
                ) from e
            if not isinstance(data, dict) or data.get("version") != _VERSION:
                raise ValueError(
                    f"quarantine map {p!r} has unknown version "
                    f"{data.get('version') if isinstance(data, dict) else data!r}"
                )
            stored = data.get("fingerprint") or "tail"
            if fingerprint is not None and fingerprint != stored:
                raise ValueError(
                    f"quarantine map {p!r} was keyed with "
                    f"fingerprint={stored!r}; reopening it as "
                    f"{fingerprint!r} would mis-key every lookup"
                )
            m = cls(path, fingerprint=stored)
            m._files = data.get("files") or {}
            return m
        return cls(path, fingerprint=fingerprint or "tail")

    def save(self, path: Optional[str] = None) -> str:
        """Write the map atomically (temp file + rename).  Returns the
        path written."""
        p = os.fspath(path) if path is not None else self.path
        if p is None:
            raise ValueError("QuarantineMap has no path; pass one to save()")
        with self._lock:
            payload = json.dumps(
                {"version": _VERSION, "fingerprint": self.fingerprint,
                 "files": self._files},
                sort_keys=True, indent=1,
            )
        tmp = f"{p}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(payload)
        os.replace(tmp, p)
        return p

    # -- queries ------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._files)

    def entries(self, fp: str) -> List[dict]:
        """The recorded quarantine entries for one fingerprint (copies;
        empty list when the file is unknown)."""
        with self._lock:
            rec = self._files.get(fp)
            return [dict(u) for u in rec["units"]] if rec else []

    def known_bad(self, fp: str) -> dict:
        """Replay index for one file:
        ``{(row_group, column): {"chunk": entry|None, "pages": {ordinal: entry}}}``
        — the shape ``ParquetFileReader`` consults per chunk.  Entries
        with ``kind == "dict"`` are informational only (dictionary
        recovery re-runs; see module docstring)."""
        out: dict = {}
        for u in self.entries(fp):
            key = (u.get("row_group"), u.get("column"))
            slot = out.setdefault(key, {"chunk": None, "pages": {}})
            if u.get("kind") == "chunk":
                slot["chunk"] = u
            elif u.get("kind") in ("page_null", "row_mask"):
                slot["pages"][int(u["page"])] = u
        return out

    # -- recording ----------------------------------------------------------

    def record(self, fp: str, report, path: Optional[str] = None) -> int:
        """Fold one salvage report's skips into the map under ``fp``.
        Returns how many NEW entries were added (re-recording a known
        quarantine is a no-op, so repeated scans keep the map stable)."""
        skips = getattr(report, "skips", report)
        added = 0
        with self._lock:
            rec = self._files.setdefault(fp, {"path": path, "units": []})
            if path and not rec.get("path"):
                rec["path"] = path
            seen = {
                (u.get("row_group"), u.get("column"), u.get("page"),
                 u.get("kind"))
                for u in rec["units"]
            }
            for s in skips:
                key = (s.row_group, s.column, s.page, s.kind)
                if key in seen:
                    continue
                seen.add(key)
                rec["units"].append({
                    "row_group": s.row_group,
                    "column": s.column,
                    "page": s.page,
                    "kind": s.kind,
                    "rows": s.rows,
                    "row_span": list(s.row_span) if s.row_span else None,
                    # page-tier entries carry their byte span so a replay
                    # can skip the page's BYTES, not just its decode
                    "byte_span": (
                        list(s.byte_span)
                        if getattr(s, "byte_span", None) else None
                    ),
                    "error": s.error,
                })
                added += 1
        return added
