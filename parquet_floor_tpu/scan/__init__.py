"""Dataset scan scheduler: coalesced vectored I/O + bounded cross-file
prefetch.

Two layers (see ``docs/scan.md``):

* :mod:`~parquet_floor_tpu.scan.plan` — the pure I/O planner: per file,
  each row group's column-chunk byte ranges (plus footer-adjacent page
  indexes) merge into coalesced read extents under gap/size thresholds.
* :mod:`~parquet_floor_tpu.scan.executor` — the scheduler: a small
  thread pool reads planned extents (``Source.read_many``) and
  host-stages row groups *across files* ahead of the consumer, bounded
  by an explicit in-flight byte budget.

Front doors: :class:`DatasetScanner` / :func:`scan_batches` (host
decode), :func:`scan_device_groups` (feeds ``TpuRowGroupReader`` across
file boundaries), :func:`scan_aggregate` (aggregate queries via device
pushdown — docs/pushdown.md), and the ``scan_options=`` parameter of
``ParquetReader.stream_content`` / ``stream_batches``.
"""

from .executor import (
    DatasetScanner,
    DatasetSchemaError,
    PrefetchedSource,
    ScanUnit,
    scan_aggregate,
    scan_batches,
    scan_device_groups,
)
from .plan import (
    Extent,
    FilePlan,
    GroupPlan,
    ScanOptions,
    coalesce,
    plan_file,
)

__all__ = [
    "DatasetScanner",
    "DatasetSchemaError",
    "Extent",
    "FilePlan",
    "GroupPlan",
    "PrefetchedSource",
    "ScanOptions",
    "ScanUnit",
    "coalesce",
    "plan_file",
    "scan_aggregate",
    "scan_batches",
    "scan_device_groups",
]
