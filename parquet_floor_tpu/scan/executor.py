"""Scan executor — bounded cross-file prefetch over planned extents.

The sequential dataset iterators open one file, decode its groups one by
one, then open the next: every file boundary drains the pipeline, and
every column chunk costs one positional read.  The executor here turns a
list of sources into ONE scheduled stream of decoded row groups:

* a small thread pool (``ScanOptions.threads``) reads each group's
  coalesced extents (``Source.read_many``) and host-decodes the group;
* work runs **across files** ahead of the consumer — while the consumer
  iterates file k, workers are already reading and decoding file k+1;
* in-flight memory is bounded by ``ScanOptions.prefetch_bytes``: each
  group charges ``max(extent bytes, footer uncompressed estimate)``
  against the budget from the moment its read is admitted until the
  consumer takes the decoded batch.  Budget is admitted strictly in
  scan order (no out-of-order unit can starve the head of the stream),
  and one group bigger than the whole budget is admitted only when it
  is alone in flight.

Concurrency contract: ``DatasetScanner`` is a single-consumer iterator —
``__next__``/``close`` must come from one thread; all internal I/O and
decode parallelism stays inside the scanner.  ``close()`` (or abandoning
via the ``with`` form / generator close in the stream faces) drains the
pool and closes every file; it is idempotent.

The same planner + budget also feed the device engine:
:func:`scan_device_groups` prefetches extents under the budget while
``tpu.engine.iter_dataset_row_groups`` runs its stage‖ship‖decode
pipeline across file boundaries.

Salvage mode (``ReaderOptions(salvage=True)``) IS honored on both scan
faces: each unit decodes on its worker thread into a fresh per-unit
``SalvageReport`` and the consumer thread folds them — in delivery
order, so the folded report is deterministic no matter how the pool
scheduled the decodes — into ``DatasetScanner.salvage_report`` via the
``SalvageReport.merge`` protocol (``docs/robustness.md``).  Each
delivered :class:`ScanUnit` also carries its own unit report, which is
how the ``DataLoader`` decides unit-level quarantine.  ``verify_crc``
and ``io_retries`` pass straight through (CRC checks ride the normal
decode path; retries wrap the *real* I/O below the prefetch cache, so
cache hits never consume retry budget).
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, replace
from typing import List, NamedTuple, Optional, Sequence, Set

from ..format.file_read import (
    ParquetFileReader,
    ReaderOptions,
    SalvageReport,
)
from ..io.source import FileSource
from ..utils import trace
from .plan import (
    DEFAULT_MAX_GAP_BYTES,
    Extent,
    FilePlan,
    GroupPlan,
    ScanOptions,
    plan_file,
)


class DatasetSchemaError(ValueError):
    """A dataset file disagrees with the first file's schema.  Still a
    ``ValueError`` — the sequential dataset stream's exact contract —
    but typed, so the scan row face can re-raise it UNWRAPPED (the
    sequential path raises it at the file boundary, outside the
    per-row RuntimeError wrap)."""


class PrefetchedSource:
    """Positional source serving reads from prefetched extent buffers.

    Sits between the real source (below: mmap / pread / retries) and the
    reader (above: footer parse, page decode).  ``load()`` installs the
    bytes of planned extents; ``read_at`` serves any sub-range of a
    loaded extent zero-copy and falls back to the inner source on a miss
    (counted as ``scan.cache_miss_bytes`` — a miss is a correctness
    non-event, only a lost prefetch).  Thread-safe: loads, drops, and
    reads may come from any executor thread.
    """

    def __init__(self, inner):
        self._inner = inner
        self._lock = threading.Lock()
        self._starts: List[int] = []          # sorted extent starts
        self._entries: List[tuple] = []       # (start, end, buffer)

    @property
    def name(self) -> str:
        return getattr(self._inner, "name", "<source>")

    @property
    def size(self) -> int:
        return self._inner.size

    def load(self, extents: Sequence[Extent]) -> int:
        """Read ``extents`` through the inner source (vectored when it
        supports ``read_many``) and install them; returns bytes loaded.
        Already-loaded extents are not re-read."""
        with self._lock:
            want = [
                e for e in extents
                if self._locate(e.offset, e.length) is None
            ]
        if not want:
            return 0
        read_many = getattr(self._inner, "read_many", None)
        ranges = [(e.offset, e.length) for e in want]
        if read_many is not None:
            bufs = read_many(ranges)
        else:
            bufs = [self._inner.read_at(o, n) for o, n in ranges]
        with self._lock:
            for e, buf in zip(want, bufs):
                i = bisect.bisect_left(self._starts, e.offset)
                self._starts.insert(i, e.offset)
                self._entries.insert(i, (e.offset, e.offset + e.length, buf))
        return sum(e.length for e in want)

    def drop(self, extents: Sequence[Extent]) -> None:
        """Forget the given extents (frees their buffers once no decoded
        view aliases them)."""
        with self._lock:
            for e in extents:
                i = bisect.bisect_left(self._starts, e.offset)
                while i < len(self._starts) and self._starts[i] == e.offset:
                    if self._entries[i][1] == e.offset + e.length:
                        del self._starts[i]
                        del self._entries[i]
                        break
                    i += 1

    def _locate(self, offset: int, length: int):
        """The cached entry covering ``[offset, offset+length)``, or None.
        Caller holds the lock."""
        i = bisect.bisect_right(self._starts, offset) - 1
        if i >= 0:
            start, end, buf = self._entries[i]
            if offset + length <= end:
                return start, buf
        return None

    def read_at(self, offset: int, length: int):
        with self._lock:
            hit = self._locate(offset, length)
        if hit is not None:
            start, buf = hit
            return memoryview(buf)[offset - start : offset - start + length]
        trace.count("scan.cache_miss_bytes", length)
        return self._inner.read_at(offset, length)

    def read_many(self, ranges) -> list:
        return [self.read_at(o, n) for o, n in ranges]

    def close(self) -> None:
        with self._lock:
            self._starts.clear()
            self._entries.clear()
        self._inner.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _ByteBudget:
    """The in-flight byte ceiling.  Admission happens only from the
    consumer thread, strictly in scan order, and is enforced by REFUSAL,
    never by waiting: ``try_acquire`` declines a unit that does not fit
    (the consumer simply retries it after delivering something), and
    ``admit`` force-admits when nothing is in flight — which is how one
    group bigger than the whole budget runs alone.  In-order admission
    is also the no-starvation argument: no later group can hold budget
    the head of the stream is waiting for.

    ``tracer`` pins the gauge to the scan's own tracer scope (the scan
    may be consumed from a context other than the one that created it —
    metrics must not migrate with the consumer)."""

    def __init__(self, cap: int, tracer: Optional[trace.Tracer] = None):
        self._cap = int(cap)
        self._used = 0
        self._lock = threading.Lock()
        self._tracer = tracer
        self.high_water = 0

    def set_cap(self, cap: int) -> None:
        """Retune the ceiling (the latency-adaptive controller's knob).
        Already-admitted bytes are never evicted — a cap cut only
        gates FUTURE admissions, so the bound stays an admission-time
        invariant."""
        with self._lock:
            self._cap = int(cap)

    def _admit_locked(self, n: int) -> None:
        self._used += n
        if self._used > self.high_water:
            self.high_water = self._used
            (self._tracer or trace.current()).gauge_max(
                "scan.inflight_bytes_max", self._used
            )

    def try_acquire(self, n: int) -> bool:
        with self._lock:
            if self._used and self._used + n > self._cap:
                return False
            self._admit_locked(n)
            return True

    def admit(self, n: int) -> None:
        """Unconditional admission — callers use this only when nothing
        is in flight (``_used == 0``), so the bound stays exact for every
        unit except a single oversized one running alone."""
        with self._lock:
            self._admit_locked(n)

    def release(self, n: int) -> None:
        with self._lock:
            self._used -= n


class _AdaptiveController:
    """Latency-adaptive prefetch (``ScanOptions.adaptive_prefetch``,
    docs/remote.md): sizes the in-flight byte budget — and the device
    pipeline's depth — from the MEASURED per-extent RTT instead of a
    static knob.

    Model: keep roughly ``threads * clamp(rtt / 2ms, 2, 16)`` units in
    flight — enough concurrent rounds to cover the RTT at a ~2 ms/unit
    consumption pace — so the byte cap is that unit count times the
    EWMA unit cost, clamped to ``[min_cap, base_cap]`` (the configured
    ``prefetch_bytes`` is the ceiling).  A warm local SSD (RTT « 2 ms)
    bottoms out at factor 2 and stays shallow; a 20–50 ms object store
    saturates toward the ceiling.  Every retune is observable: the
    chosen cap rides the ``scan.adaptive_budget_bytes`` gauge, and a
    >1.5x move records a ``scan.adaptive_budget`` decision.

    ``observe`` is called from worker threads (lock-protected EWMAs);
    ``cap()``/``depth_hint()`` from the consumer thread."""

    RTT_UNIT_S = 0.002           # the "service pace" an RTT is scored against
    MIN_FACTOR, MAX_FACTOR = 2, 16

    def __init__(self, base_cap: int, threads: int,
                 tracer: Optional[trace.Tracer] = None,
                 min_cap: int = 1 << 20):
        self._base = int(base_cap)
        self._threads = int(threads)
        self._tracer = tracer
        self._min = min(int(min_cap), self._base)
        self._lock = threading.Lock()
        self._rtt: Optional[float] = None    # EWMA per-load wall seconds
        self._cost: Optional[float] = None   # EWMA admitted unit cost
        self._bw: Optional[float] = None     # EWMA load bytes/second
        self._last_logged: Optional[int] = None

    def observe_load(self, nbytes: int, seconds: float) -> None:
        """One extent-load measurement (worker thread): the load's wall
        time is the RTT sample (transfer included — a conservative
        overestimate that only ever deepens the pipeline), and
        bytes/wall is the bandwidth sample (RTT included — an
        UNDER-estimate of the raw link, which only ever narrows the
        auto-tuned coalescing gap)."""
        if seconds <= 0:
            return
        with self._lock:
            self._rtt = (
                seconds if self._rtt is None
                else 0.7 * self._rtt + 0.3 * seconds
            )
            if nbytes > 0:
                bw = nbytes / seconds
                self._bw = (
                    bw if self._bw is None
                    else 0.7 * self._bw + 0.3 * bw
                )

    def observe_cost(self, cost: int) -> None:
        """One admitted unit's budget charge (consumer thread)."""
        with self._lock:
            self._cost = (
                float(cost) if self._cost is None
                else 0.7 * self._cost + 0.3 * float(cost)
            )

    def rtt_s(self) -> Optional[float]:
        with self._lock:
            return self._rtt

    def bandwidth_Bps(self) -> Optional[float]:
        """EWMA load bandwidth (bytes/second), None before the first
        sized load.  Pairs with :meth:`rtt_s` to price a request:
        ``rtt * bandwidth`` is the bytes one round trip is worth — the
        ``max_gap_bytes`` auto-tune's input."""
        with self._lock:
            return self._bw

    def cap(self) -> int:
        """The current effective budget cap."""
        with self._lock:
            rtt, cost = self._rtt, self._cost
        if rtt is None or cost is None:
            # no measurements yet: start shallow — the first loads are
            # the probe, and ramping up costs one scheduling round
            cap = max(self._min, self._base // 8)
        else:
            factor = min(self.MAX_FACTOR,
                         max(self.MIN_FACTOR, rtt / self.RTT_UNIT_S))
            cap = int(min(self._base,
                          max(self._min, cost * self._threads * factor)))
        tr = self._tracer or trace.current()
        tr.gauge_max("scan.adaptive_budget_bytes", cap)
        last = self._last_logged
        if last is None or cap > last * 1.5 or cap * 1.5 < last:
            self._last_logged = cap
            tr.decision("scan.adaptive_budget", {
                "cap_bytes": cap,
                "rtt_ms": None if rtt is None else round(rtt * 1e3, 3),
                "unit_cost": None if cost is None else int(cost),
                "threads": self._threads,
            })
        return cap

    def depth_hint(self, default: int = 3, floor_s: float = 0.002,
                   cap: int = 8) -> Optional[int]:
        """The device pipeline's adaptive depth: one extra stage per
        ~10 ms of measured RTT over ``default``, capped at ``cap``
        (each level pins a host arena).  None (= keep the default)
        until an RTT is measured, or when the store is effectively
        local."""
        rtt = self.rtt_s()
        if rtt is None or rtt < floor_s:
            return None
        hint = min(cap, default + int(rtt // 0.01))
        (self._tracer or trace.current()).decision("scan.adaptive_depth", {
            "depth": hint, "rtt_ms": round(rtt * 1e3, 3),
        })
        return hint


class ScanUnit(NamedTuple):
    """One delivered row group: the file's position in the dataset, the
    group's REAL index within that file, the decoded batch, and (salvage
    mode only) the unit's own :class:`SalvageReport` — what THIS group's
    decode had to give up, before any merging."""

    file_index: int
    group_index: int
    batch: object  # RowGroupBatch
    salvage: Optional[SalvageReport] = None


@dataclass
class _FileState:
    reader: ParquetFileReader
    cache: PrefetchedSource
    plan: FilePlan
    remaining: int  # groups not yet delivered; 0 → file closes
    plan_map: Optional[dict] = None    # group_index -> GroupPlan (order mode)
    keep: Optional[Set[int]] = None    # predicate survivors (order mode)
    num_groups: int = 0                # footer group count (order mode)


class _Work(NamedTuple):
    file_index: int
    plan: GroupPlan
    cost: int


def _source_chain(source, options: Optional[ReaderOptions]) -> PrefetchedSource:
    """FileSource → RetryingSource → PrefetchedSource.  Retries wrap the
    REAL I/O, below the prefetch cache: a cache hit must never consume
    retry budget, and the reader above gets ``io_retries=0`` so the
    double-wrap guard keeps meaning one bounded retry loop per physical
    read.  A zero-arg callable source is a FACTORY (resolved here, at
    open time — how multi-epoch loaders re-open custom source objects
    lazily).

    Remote sources (``io.remote``, marked ``parallel_read_many``) keep
    their vectored fan-out ABOVE the retry layer: ``RetryingSource``
    retries one range at a time, so wrapping a remote source directly
    would serialize a vectored extent read — the ``ParallelRangeReader``
    adapter re-parallelizes it while every range keeps its own full
    retry/deadline budget (docs/remote.md's chain)."""
    if callable(source) and not hasattr(source, "read_at"):
        source = source()
    src = source if hasattr(source, "read_at") else FileSource(source)
    try:
        if options is not None and options.io_retries > 0:
            from ..io.remote import compose_retrying

            src = compose_retrying(
                src, options.io_retries, options.io_retry_backoff_s,
                deadline_s=options.io_retry_deadline_s,
            )
        return PrefetchedSource(src)
    except BaseException:
        src.close()
        raise


def compute_page_covers(reader, predicate, keep: Optional[Set[int]],
                        filter_set: Optional[Set[str]], sc: ScanOptions):
    """``ScanOptions.page_prune``'s cover pass, shared by BOTH scan
    faces: narrow each surviving group to the page-aligned cover of the
    predicate's ``row_ranges`` (docs/scan.md).  Mutates ``keep`` — a
    group whose every page the ColumnIndex ruled out is dropped
    entirely (no bytes read).  Returns the ``covered_by_group`` map for
    :func:`plan_file`."""
    # prefetch EVERY kept group's page-index ranges in one vectored
    # load before the cover walk below reads them one by one — on a
    # remote source the per-chunk ColumnIndex/OffsetIndex reads
    # would otherwise each pay an RTT, serially, at file open (the
    # reader parses each index once, so the later plan_file load of
    # the same extents is a no-op hit)
    from .plan import coalesce, index_ranges

    idx: list = []
    for gi in sorted(keep):
        # ALL columns, not just the projection: the predicate's own
        # column need not be selected, and row_ranges reads it
        idx.extend(index_ranges(reader.row_groups[gi]))
    load = getattr(reader.source, "load", None)
    if idx and load is not None:
        gap = (
            sc.max_gap_bytes if sc.max_gap_bytes is not None
            else DEFAULT_MAX_GAP_BYTES
        )
        load(coalesce(idx, gap, sc.max_extent_bytes))
    covered_by_group: dict = {}
    for gi in sorted(keep):
        rg = reader.row_groups[gi]
        n = int(rg.num_rows or 0)
        chunks = [
            c for c in rg.columns or []
            if not filter_set or (
                c.meta_data is not None
                and c.meta_data.path_in_schema
                and c.meta_data.path_in_schema[0] in filter_set
            )
        ]
        if not chunks:
            continue
        rr = predicate.row_ranges(reader, gi)
        cov = reader.page_cover(gi, rr, chunks)
        if cov == []:
            # the ColumnIndex proved no page can match: the group
            # drops like a stats-pruned one (its pages all count)
            keep.discard(gi)
            trace.count("scan.pages_pruned", sum(
                len(oi.page_locations)
                for oi in (reader.read_offset_index(c) for c in chunks)
                if oi is not None and oi.page_locations
            ))
        elif cov is not None and cov != [(0, n)]:
            covered_by_group[gi] = cov
    return covered_by_group


class DatasetScanner:
    """Scheduled scan over a list of sources, yielding :class:`ScanUnit`
    in (file order, row-group order) — decoded bytes are bit-identical
    to the sequential per-file loop, delivery order included.

    ``columns`` projects by top-level field name (the reference's
    projection rule); ``predicate`` prunes row groups per file before
    any of their bytes are read; ``options`` is the usual
    :class:`ReaderOptions` (``salvage`` honored via per-unit reports —
    see module docstring and ``self.salvage_report``).  An empty
    ``sources`` list yields nothing (an empty dataset directory is a
    valid no-op scan).

    ``order`` generalizes delivery beyond the default (file order, then
    row-group order): an explicit sequence of ``(file_index,
    group_index)`` units, each at most once, delivered exactly in that
    sequence — the shape a seeded-shuffled training epoch wants
    (``data.DataLoader``, docs/data.md).  Only ordered units are read; a
    file opens at its FIRST ordered unit and closes right after its last
    one delivers, so fd usage follows the order's file locality rather
    than the dataset size.  ``predicate`` composes by intersection:
    ordered units whose group the predicate pruned are skipped (never
    read).  An out-of-range or duplicate unit raises ``ValueError``.

    Use as an iterator, ideally under ``with`` (or call :meth:`close`):
    abandoning mid-scan drains the worker pool and closes every file.
    """

    def __init__(self, sources: Sequence, columns: Optional[Sequence[str]] = None,
                 options: Optional[ReaderOptions] = None,
                 scan: Optional[ScanOptions] = None,
                 predicate=None,
                 order: Optional[Sequence] = None,
                 metadata: Optional[Sequence] = None):
        self._sources = list(sources)
        if metadata is not None and len(metadata) != len(self._sources):
            raise ValueError(
                f"metadata has {len(metadata)} entries for "
                f"{len(self._sources)} source(s)"
            )
        # pre-parsed footers, one per source (None entries re-parse):
        # multi-epoch loaders re-open files every epoch, and the thrift
        # footer parse dominates a warm re-open
        self._metadata = list(metadata) if metadata is not None else None
        self._order = None
        self._occurrences: Optional[dict] = None
        if order is not None:
            self._order = [(int(fi), int(gi)) for fi, gi in order]
            occurrences: dict = {}
            seen = set()
            for fi, gi in self._order:
                if not 0 <= fi < len(self._sources):
                    raise ValueError(
                        f"order unit (file {fi}, group {gi}) outside "
                        f"dataset of {len(self._sources)} file(s)"
                    )
                if (fi, gi) in seen:
                    raise ValueError(
                        f"order lists unit (file {fi}, group {gi}) twice"
                    )
                seen.add((fi, gi))
                occurrences[fi] = occurrences.get(fi, 0) + 1
            self._occurrences = occurrences
        self._filter: Optional[Set[str]] = set(columns) if columns else None
        self._options = options
        self._scan = scan or ScanOptions()
        self._predicate = predicate
        # host-leg pushdown (docs/pushdown.md): with
        # ``ScanOptions(pushdown=True)`` each decoded batch mask-compacts
        # to the predicate's surviving rows, so both scan legs deliver
        # the SAME row sets (the device leg compacts inside the fused
        # launch).  Salvage keeps whole groups (quarantine decisions are
        # group-wide); aggregate stays a device-leg shape.
        self._mask_compact = bool(
            self._scan.pushdown
            and predicate is not None
            and not (options is not None and options.salvage)
            and self._scan.aggregate is None
        )
        # the device-leg contract: predicate columns OUTSIDE the
        # projection decode (they must — the mask needs their values)
        # but are dropped from delivered batches; the decode filter
        # widens, the delivery filter stays the caller's projection
        self._decode_filter = self._filter
        if self._mask_compact and self._filter is not None:
            from ..batch.predicate import tree, tree_columns

            self._decode_filter = self._filter | {
                c.split(".")[0] for c in tree_columns(tree(predicate))
            }
        # salvage: per-unit reports fold here, in DELIVERY order (the
        # merge protocol); None in strict mode
        self._salvage = options is not None and options.salvage
        self.salvage_report: Optional[SalvageReport] = (
            SalvageReport() if self._salvage else None
        )
        # the scan is ATTRIBUTED to the tracer scope active at
        # construction: worker tasks bind to it (Tracer.run) and the
        # consumer-side paths re-activate it, so two scanners built
        # under different trace.scope()s never mix metrics even when
        # one thread interleaves their iteration
        self._tracer = trace.current()
        self._t0: Optional[float] = None     # first __next__ → close
        self._wall: Optional[float] = None
        self._budget = _ByteBudget(self._scan.prefetch_bytes, self._tracer)
        self._adaptive = (
            _AdaptiveController(
                self._scan.prefetch_bytes, self._scan.threads, self._tracer
            )
            if self._scan.adaptive_prefetch else None
        )
        if self._adaptive is not None:
            self._budget.set_cap(self._adaptive.cap())
        self._gap_logged: Optional[int] = None  # last auto-tuned gap
        self._pool = ThreadPoolExecutor(
            max_workers=self._scan.threads, thread_name_prefix="pftpu-scan"
        )
        self._files: dict = {}                 # file_index -> _FileState
        self._pending: deque = deque()         # (work, future)
        self._work_iter = self._gen_work()
        self._lookahead: Optional[_Work] = None
        self._schema_key = None
        self._deferred: Optional[BaseException] = None
        self._closed = False
        self._columns = None  # selected descriptors (set at first file open)
        self._meta_by_file: dict = {}  # footer metadata, kept past file close
        self._delivered_fi = 0

    @property
    def columns(self):
        """Selected descriptors of the first file.  Mirrors the
        sequential dataset iterator: accessing it before iteration opens
        the first file on demand (which also starts the prefetch), a
        first-file open failure propagates, and a closed empty scan
        raises rather than returning None.  An empty DATASET (no
        sources) is the one None case — there is no schema to report."""
        if self._columns is None and not self._closed:
            with trace.using(self._tracer):
                self._top_up()
        if self._columns is None:
            if self._deferred is not None:
                raise self._deferred  # the first file failed to open/plan
            if self._closed:
                raise ValueError("dataset scan is closed")
        return self._columns

    @property
    def metadata(self):
        """Footer of the most recently DELIVERED file (the first file
        before any delivery) — the sequential dataset iterator's
        surface.  Raises on a closed or empty scan."""
        if not self._meta_by_file and not self._closed:
            with trace.using(self._tracer):
                self._top_up()
        meta = self._meta_by_file.get(self._delivered_fi)
        if meta is None:
            if self._deferred is not None:
                raise self._deferred  # the first file failed to open/plan
            raise ValueError("dataset scan is closed (or empty)")
        return meta

    # -- file planning (consumer thread) -----------------------------------

    def _effective_scan(self) -> ScanOptions:
        """The ScanOptions this file open plans under.  With
        ``max_gap_bytes=None`` the coalescing gap auto-tunes to the
        measured RTT x bandwidth — the bytes one round trip is worth,
        so merging across any cheaper gap always wins — clamped to
        ``[DEFAULT_MAX_GAP_BYTES, max_extent_bytes]``.  Before the
        adaptive controller has measurements (first file of a scan, or
        ``adaptive_prefetch`` off) the default applies; a local chain's
        tiny RTT x bandwidth clamps to the same floor, so only a
        genuinely slow store widens the gap.  Each NEW resolved value
        records a ``scan.max_gap_autotuned`` decision."""
        sc = self._scan
        if sc.max_gap_bytes is not None:
            return sc
        gap = DEFAULT_MAX_GAP_BYTES
        rtt = bw = None
        if self._adaptive is not None:
            rtt = self._adaptive.rtt_s()
            bw = self._adaptive.bandwidth_Bps()
            if rtt is not None and bw is not None:
                gap = int(min(sc.max_extent_bytes,
                              max(DEFAULT_MAX_GAP_BYTES, rtt * bw)))
        if gap != self._gap_logged:
            self._gap_logged = gap
            trace.decision("scan.max_gap_autotuned", {
                "gap_bytes": gap,
                "rtt_ms": None if rtt is None else round(rtt * 1e3, 3),
                "bandwidth_MBps": None if bw is None
                else round(bw / 1e6, 2),
            })
        return replace(sc, max_gap_bytes=gap)

    def _open_file(self, fi: int) -> _FileState:
        opts = self._options
        cache = _source_chain(self._sources[fi], opts)
        reader_opts = replace(opts, io_retries=0) if opts is not None else None
        meta = self._metadata[fi] if self._metadata is not None else None
        try:
            reader = ParquetFileReader(cache, options=reader_opts,
                                       metadata=meta)
        except BaseException:
            cache.close()
            raise
        try:
            from ..format.schema import dataset_schema_key

            key = dataset_schema_key(reader.schema.columns)
            if self._schema_key is None:
                self._schema_key = key
                self._columns = [
                    c for c in reader.schema.columns
                    if self._filter is None or c.path[0] in self._filter
                ]
                if self._mask_compact and any(
                    c.max_repetition_level > 0
                    for c in reader.schema.columns
                    if self._decode_filter is None
                    or c.path[0] in self._decode_filter
                ):
                    from ..errors import UnsupportedFeatureError

                    raise UnsupportedFeatureError(
                        "pushdown row compaction supports flat columns "
                        "only (the device leg rejects repeated leaves "
                        "too); scan without pushdown and filter rows "
                        "downstream"
                    )
            elif key != self._schema_key:
                raise DatasetSchemaError(
                    f"dataset file {fi} disagrees with the first file's "
                    "schema"
                )
            keep = (
                set(self._predicate.row_groups(reader))
                if self._predicate is not None
                else None
            )
            sc = self._effective_scan()
            covered_by_group = self._page_covers(reader, keep, sc)
            plan = plan_file(
                reader,
                self._decode_filter if self._mask_compact
                else self._filter,
                keep, sc, covered_by_group,
            )
            # page-index extents: tiny, footer-adjacent, shared by every
            # group (page_cover/predicates) — prefetch once per file
            if plan.index_extents:
                cache.load(plan.index_extents)
        except BaseException:
            reader.close()
            raise
        self._meta_by_file[fi] = reader.metadata
        if self._occurrences is not None:
            # order mode: the file stays open until every one of its
            # ORDERED units has delivered (or been skipped as pruned) —
            # the count of order entries, not of planned groups
            remaining = self._occurrences[fi]
        else:
            remaining = len(plan.groups)
        state = _FileState(
            reader, cache, plan, remaining=remaining,
            plan_map={gp.group_index: gp for gp in plan.groups},
            keep=keep, num_groups=len(reader.row_groups),
        )
        self._files[fi] = state
        if state.remaining == 0:
            self._close_file(fi)
        return state

    def _page_covers(self, reader, keep: Optional[Set[int]],
                     sc: Optional[ScanOptions] = None):
        if self._predicate is None or not self._scan.page_prune:
            return None
        try:
            return compute_page_covers(
                reader, self._predicate, keep, self._filter,
                sc if sc is not None else self._scan,
            )
        except (OSError, MemoryError):
            raise
        except Exception:
            if not self._salvage:
                raise
            # salvage scans prune too (ranged salvage widens only the
            # damaged chunks) — but a damaged page INDEX must not fail
            # the plan; the cover just falls away for this file
            return None

    def _close_file(self, fi: int) -> None:
        state = self._files.pop(fi, None)
        if state is not None:
            state.reader.close()

    def _gen_work(self):
        if self._order is None:
            for fi in range(len(self._sources)):
                state = self._open_file(fi)
                for gp in state.plan.groups:
                    cost = max(gp.read_bytes, gp.uncompressed_bytes, 1)
                    yield _Work(fi, gp, cost)
            return
        for fi, gi in self._order:
            state = self._files.get(fi)
            if state is None:
                # not-yet-opened (a closed file never reappears: its
                # remaining counts every order entry, so it closes only
                # after its last one)
                state = self._open_file(fi)
            gp = state.plan_map.get(gi)
            if gp is None:
                if not 0 <= gi < state.num_groups:
                    raise ValueError(
                        f"order unit (file {fi}, group {gi}) outside file "
                        f"with {state.num_groups} row group(s)"
                    )
                # the unit exists but the predicate pruned it: skip
                # without reading — and retire its order slot so the
                # file still closes after its last ordered unit
                state.remaining -= 1
                if state.remaining == 0:
                    self._close_file(fi)
                continue
            cost = max(gp.read_bytes, gp.uncompressed_bytes, 1)
            yield _Work(fi, gp, cost)

    # -- worker task --------------------------------------------------------

    def _run_unit(self, work: _Work):
        state = self._files[work.file_index]
        attrs = {
            "file": work.file_index,
            "row_group": work.plan.group_index,
            "path": state.cache.name,
        }
        try:
            t0 = time.perf_counter()
            with trace.span("read", attrs=attrs) as sp:
                loaded = state.cache.load(work.plan.extents)
                sp.add_bytes(loaded)
            if self._adaptive is not None and loaded:
                self._adaptive.observe_load(
                    loaded, time.perf_counter() - t0
                )
            trace.count("scan.bytes_prefetched", loaded)
            return self._decode_unit(work, state, attrs)
        finally:
            state.cache.drop(work.plan.extents)

    def _decode_unit(self, work: _Work, state, attrs):
        with trace.span(
            "decode", work.plan.uncompressed_bytes, attrs=attrs,
            observe="scan.unit_decode_seconds",
        ):
            if not self._salvage:
                read_filter = (
                    self._decode_filter if self._mask_compact
                    else self._filter
                )
                if work.plan.covered is not None:
                    # page-pruned group (ScanOptions.page_prune):
                    # decode exactly the covered pages — the cover is
                    # already page-aligned, so read_row_group_ranges
                    # reproduces it as a fixpoint
                    batch, _cov = state.reader.read_row_group_ranges(
                        work.plan.group_index, work.plan.covered,
                        read_filter,
                    )
                else:
                    batch = state.reader.read_row_group(
                        work.plan.group_index, read_filter
                    )
                if self._mask_compact:
                    batch = _pushdown_compact(
                        batch, self._predicate, self._filter
                    )
                return batch, None
            # per-unit report: worker threads never touch a shared
            # report; the consumer folds them in delivery order
            unit_rep = SalvageReport()
            if work.plan.covered is not None:
                # ranged salvage: clean chunks keep the I/O pruning,
                # a damaged one widens to the whole-chunk ladder
                # (file_read._read_row_group_ranges_salvage)
                batch, _cov = state.reader.read_row_group_ranges(
                    work.plan.group_index, work.plan.covered,
                    self._filter, report=unit_rep,
                )
            else:
                batch = state.reader.read_row_group(
                    work.plan.group_index, self._filter, report=unit_rep
                )
            return batch, unit_rep

    # -- scheduling (consumer thread) ---------------------------------------

    def _next_work(self) -> Optional[_Work]:
        if self._lookahead is not None:
            w, self._lookahead = self._lookahead, None
            return w
        return next(self._work_iter, None)

    def _top_up(self) -> None:
        if self._deferred is not None:
            return  # planning already failed: deliver what we have, then raise
        if self._adaptive is not None:
            # consumer-thread retune: admissions below see the cap the
            # latest RTT/cost measurements justify
            self._budget.set_cap(self._adaptive.cap())
        max_units = max(2, self._scan.threads * 2)
        while len(self._pending) < max_units:
            try:
                work = self._next_work()
            except BaseException as e:
                # a planning/open failure (schema mismatch, exhausted
                # retries on a footer) keeps SEQUENTIAL error order: the
                # groups already in flight deliver first, the error
                # surfaces exactly where the one-file-at-a-time loop
                # would have raised it
                self._deferred = e
                return
            if work is None:
                return
            if self._pending:
                if not self._budget.try_acquire(work.cost):
                    self._lookahead = work  # budget full: retry later
                    return
            else:
                # nothing in flight: every cost is released, so the
                # budget is empty — force-admit (oversized groups run
                # alone; the bound stays exact for everything else)
                self._budget.admit(work.cost)
            if self._adaptive is not None:
                # admitted exactly once — a budget refusal above must
                # not double-count this unit's cost in the EWMA
                self._adaptive.observe_cost(work.cost)
            # bind the task to the scan's tracer scope: contextvars do
            # not cross thread-pool submission on their own
            self._pending.append((
                work, self._pool.submit(self._tracer.run, self._run_unit, work)
            ))
            trace.gauge_max("scan.queue_depth_max", len(self._pending))

    def __iter__(self):
        return self

    def __next__(self) -> ScanUnit:
        with trace.using(self._tracer):
            return self._next_unit()

    def _next_unit(self) -> ScanUnit:
        if self._closed:
            raise StopIteration
        if self._t0 is None:
            self._t0 = time.perf_counter()
        self._top_up()
        if not self._pending:
            err, self._deferred = self._deferred, None
            self.close()
            if err is not None:
                # planning/open errors are FILE-BOUNDARY errors: the
                # sequential dataset stream raises them bare (outside its
                # per-row wrap), so consumers can re-raise them unwrapped
                err.pftpu_scan_planning = True
                raise err
            raise StopIteration
        work, fut = self._pending.popleft()
        t0 = time.perf_counter()
        try:
            batch, unit_rep = fut.result()
        except BaseException:
            self._budget.release(work.cost)
            self.close()
            raise
        trace.add("scan.consumer_stall", time.perf_counter() - t0)
        self._budget.release(work.cost)
        self._delivered_fi = work.file_index
        state = self._files.get(work.file_index)
        if unit_rep is not None:
            # delivery-order merge (the deterministic fold), plus a copy
            # into the per-file reader's report so close() records it
            # into the quarantine map exactly like a sequential read
            self.salvage_report.merge_in(unit_rep)
            if state is not None and state.reader.salvage_report is not None:
                state.reader.salvage_report.merge_in(unit_rep)
        if state is not None:
            state.remaining -= 1
            if state.remaining == 0:
                self._close_file(work.file_index)
        self._top_up()  # refill while the consumer processes the batch
        return ScanUnit(
            work.file_index, work.plan.group_index, batch, unit_rep
        )

    def report(self) -> trace.ScanReport:
        """The scan's :class:`~parquet_floor_tpu.utils.trace.ScanReport`,
        built from the tracer scope the scanner was constructed under
        (wall time runs first ``__next__`` → ``close``; mid-scan calls
        report the elapsed time so far).  Empty when that tracer is
        disabled — wrap the scan in ``trace.scope()`` (or enable the
        global tracer) to collect one."""
        wall = self._wall
        if wall is None and self._t0 is not None:
            wall = time.perf_counter() - self._t0
        return self._tracer.scan_report(
            wall_seconds=wall, budget_bytes=self._scan.prefetch_bytes
        )

    def close(self) -> None:
        """Drain workers and close every open file; idempotent, safe after
        errors or mid-scan abandonment."""
        if self._closed:
            return
        self._closed = True
        if self._t0 is not None and self._wall is None:
            self._wall = time.perf_counter() - self._t0
        for work, fut in self._pending:
            if not fut.cancel():
                try:
                    fut.result()
                except Exception:
                    pass  # discarded lookahead must not mask the abandon
            self._budget.release(work.cost)
        self._pending.clear()
        self._pool.shutdown(wait=True)
        for fi in list(self._files):
            self._close_file(fi)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def scan_batches(sources: Sequence, columns: Optional[Sequence[str]] = None,
                 options: Optional[ReaderOptions] = None,
                 scan: Optional[ScanOptions] = None,
                 predicate=None, order: Optional[Sequence] = None):
    """Generator of :class:`ScanUnit` over a dataset — the functional face
    of :class:`DatasetScanner` (closes the scanner when the generator is
    exhausted, closed, or abandoned).  ``order`` is the scanner's explicit
    unit order (permuted delivery — see :class:`DatasetScanner`)."""
    scanner = DatasetScanner(
        sources, columns=columns, options=options, scan=scan,
        predicate=predicate, order=order,
    )
    try:
        yield from scanner
    finally:
        scanner.close()


def scan_device_groups(sources: Sequence,
                       columns: Optional[Sequence[str]] = None,
                       options: Optional[ReaderOptions] = None,
                       scan: Optional[ScanOptions] = None,
                       predicate=None,
                       float64_policy: str = "bits",
                       dict_form: str = "gather",
                       on_report=None,
                       on_salvage=None):
    """Scan-scheduled DEVICE decode of a dataset: yields
    ``(file_index, group_index, {name: DeviceColumn})`` in order.

    Two schedulers compose here: the byte prefetcher loads each group's
    coalesced extents under the ``prefetch_bytes`` budget ahead of the
    engine, and ``tpu.engine.iter_dataset_row_groups`` runs its
    stage‖ship‖decode pipeline ACROSS file boundaries — the group-i /
    group-i+1 overlap no longer drains at each file's end.  Files open
    lazily through the engine's WINDOWED task iterator and close right
    after their last planned group delivers, so fd usage follows the
    prefetch window (budget + pipeline depth), not the dataset size —
    the same fd-bounded lifetime contract as the host
    :class:`DatasetScanner`.  File-boundary errors (a later file's
    corrupt footer, schema mismatch) DEFER: groups already planned
    deliver first, preserving sequential error order.

    ``options.salvage`` is honored: each damaged unit decodes through
    the host salvage engine (the quarantine decision is face-identical
    by construction — see ``TpuRowGroupReader``), chunk-quarantined
    columns arrive as ``BatchColumn(quarantined=True)`` placeholders IN
    POSITION, and ``on_salvage`` (a callable taking one merged
    :class:`~parquet_floor_tpu.format.file_read.SalvageReport`) receives
    the dataset-level fold when the scan ends.  ``verify_crc`` without
    salvage is rejected exactly as ``TpuRowGroupReader`` rejects it.

    ``on_report`` (a callable taking one
    :class:`~parquet_floor_tpu.utils.trace.ScanReport`) is invoked once
    when the scan finishes or is abandoned, with the health summary
    built from the tracer scope active when the scan started.

    **Pushdown** (docs/pushdown.md): ``ScanOptions(page_prune=True)``
    narrows each surviving group to the predicate's page cover before a
    data byte is read (the storage rung — delivered groups then carry
    only the covered rows, exactly like the host leg);
    ``ScanOptions(pushdown=True)`` additionally evaluates the predicate
    INSIDE each group's fused decode executable and delivers only the
    surviving rows, device-compacted (``scan.rows_filtered_device``).
    ``ScanOptions(aggregate=...)`` switches the yield to ``(file_index,
    group_index, AggPartial)`` — tiny per-group partial aggregate
    states; fold them with :func:`scan_aggregate`.  Neither composes
    with salvage (quarantine decisions are group-wide).
    """
    from ..batch.columns import BatchColumn
    from ..format.schema import dataset_schema_key
    from ..tpu.compute import ComputeRequest, PushdownResult
    from ..tpu.engine import TpuRowGroupReader, iter_dataset_row_groups

    sc = scan or ScanOptions()
    compute_req = None
    use_pred = predicate is not None and (
        sc.pushdown or sc.aggregate is not None
    )
    if sc.aggregate is not None or use_pred or sc.project_exprs:
        from ..errors import UnsupportedFeatureError

        if options is not None and options.salvage:
            raise UnsupportedFeatureError(
                "pushdown/aggregate/project_exprs do not compose with "
                "salvage (quarantine decisions are group-wide); scan "
                "with salvage and filter on host"
            )
        scope = None
        if sources:
            s0 = sources[0]
            scope = (
                os.fspath(s0) if isinstance(s0, (str, os.PathLike))
                else getattr(s0, "name", None)
            )
        compute_req = ComputeRequest(
            predicate=predicate if use_pred else None,
            aggregate=sc.aggregate,
            # an expr-only request ships full columns plus the computed
            # outputs — mask mode, nothing filtered
            mode="compact" if use_pred else "mask",
            # dataset identity for the persisted capacity HWM —
            # selectivity is a property of (predicate, data)
            cache_scope=scope,
            exprs=sc.project_exprs or None,
        )
    # attribute the whole scan to the tracer active at generator start
    # (worker tasks bind to it explicitly; a bare contextvar would not
    # cross the pool's thread spawns, and the consumer may drive the
    # generator from a different scope than the one that created it)
    tracer = trace.current()
    t_start = time.perf_counter()
    budget = _ByteBudget(sc.prefetch_bytes, tracer)
    adaptive = (
        _AdaptiveController(sc.prefetch_bytes, sc.threads, tracer)
        if sc.adaptive_prefetch else None
    )
    if adaptive is not None:
        budget.set_cap(adaptive.cap())
    salvage = options is not None and options.salvage
    readers: List[TpuRowGroupReader] = []   # open order == file order
    units: List[tuple] = []          # (file_index, GroupPlan, cache, cost)
    files: dict = {}                 # fi -> (tpu, cache, fplan)
    state = {"schema_key": None, "deferred": None, "opened": -1}
    pool = ThreadPoolExecutor(max_workers=sc.threads,
                              thread_name_prefix="pftpu-scanio")

    def open_file(fi):
        """Footer open + plan for file ``fi`` (consumer thread, lazily,
        strictly in file order — the windowed lifetime contract)."""
        cache = _source_chain(sources[fi], options)
        reader_opts = (
            replace(options, io_retries=0) if options is not None else None
        )
        try:
            fr = ParquetFileReader(cache, options=reader_opts)
        except BaseException:
            cache.close()
            raise
        try:
            tpu = TpuRowGroupReader(
                fr, float64_policy=float64_policy, dict_form=dict_form
            )  # takes ownership of fr (closes it, and the chain with it)
        except BaseException:
            # the engine closes only readers it OPENED; a rejection here
            # (e.g. verify_crc pinned to host) must not leak ours
            fr.close()
            raise
        readers.append(tpu)
        key = dataset_schema_key(fr.schema.columns)
        if state["schema_key"] is None:
            state["schema_key"] = key
        elif key != state["schema_key"]:
            raise DatasetSchemaError(
                f"dataset file {fi} disagrees with the first file's schema"
            )
        keep = (
            set(predicate.row_groups(fr)) if predicate is not None else None
        )
        covered_by_group = None
        if predicate is not None and sc.page_prune:
            # the device leg's page-prune rung (docs/scan.md): same
            # cover pass as the host DatasetScanner, bit-parity pinned.
            # Salvage scans keep the pruning (the engine's ranged
            # salvage widens only damaged chunks), but a damaged page
            # INDEX must not fail the plan there — the cover falls away
            try:
                covered_by_group = compute_page_covers(
                    fr, predicate, keep, set(columns) if columns else None,
                    sc
                )
            except (OSError, MemoryError):
                raise
            except Exception:
                if not salvage:
                    raise
                covered_by_group = None
        fplan = plan_file(fr, set(columns) if columns else None, keep, sc,
                          covered_by_group)
        if fplan.index_extents:
            t0 = time.perf_counter()
            loaded = cache.load(fplan.index_extents)
            if adaptive is not None and loaded:
                adaptive.observe_load(loaded, time.perf_counter() - t0)
        elif adaptive is not None and adaptive.rtt_s() is None:
            # no index extents to time: probe the store once with a
            # tail read (~pure RTT) so the depth hint below has a
            # measurement to work from
            t0 = time.perf_counter()
            cache.read_at(max(0, cache.size - 8), min(8, cache.size))
            adaptive.observe_load(8, time.perf_counter() - t0)
        files[fi] = (tpu, cache, fplan)
        for gp in fplan.groups:
            units.append((fi, gp, cache, max(gp.read_bytes, 1)))

    def ensure_next_file() -> bool:
        """Open the next not-yet-opened file; False when exhausted or a
        planning error deferred (sequential error order: groups already
        planned deliver first, then the error surfaces)."""
        if state["deferred"] is not None:
            return False
        nxt = state["opened"] + 1
        if nxt >= len(sources):
            return False
        try:
            open_file(nxt)
        except BaseException as e:
            state["deferred"] = e
            return False
        state["opened"] = nxt
        return True

    def load_unit(cache_, gp, fi_):
        """Prefetch one group's extents (worker thread, scope-bound):
        the read span carries the (file, row group) attribution the
        timeline needs to show prefetch hiding the I/O."""
        t0 = time.perf_counter()
        with trace.span("read", attrs={
            "file": fi_, "row_group": gp.group_index, "path": cache_.name,
            "extents": len(gp.extents),
        }) as sp:
            n = cache_.load(gp.extents)
            sp.add_bytes(n)
        if adaptive is not None and n:
            adaptive.observe_load(n, time.perf_counter() - t0)
        trace.count("scan.bytes_prefetched", n)
        return n

    loads: deque = deque()  # (unit_idx, cost, future) admitted to budget
    next_load = 0
    floor = 0  # first unit the engine has not consumed yet
    WINDOW = max(2, sc.threads * 2)

    def pump():
        nonlocal next_load
        if next_load < floor:
            # budget lag left these behind and the engine already
            # read them directly — never prefetch a consumed group
            next_load = floor
        if adaptive is not None:
            budget.set_cap(adaptive.cap())
        while len(loads) < WINDOW:
            if next_load >= len(units):
                # discover more units only while the load window has
                # room: this is what bounds how far ahead files open
                if not ensure_next_file():
                    return
                continue
            fi_, gp, cache_, cost = units[next_load]
            if loads and not budget.try_acquire(cost):
                return
            if not loads:
                budget.admit(cost)  # queue empty ⇒ budget empty
            if adaptive is not None:
                # admitted exactly once — a refusal must not
                # double-count this unit's cost in the EWMA
                adaptive.observe_cost(cost)
            loads.append((next_load, cost, pool.submit(
                tracer.run, load_unit, cache_, gp, fi_
            )))
            tracer.gauge_max("scan.queue_depth_max", len(loads))
            next_load += 1

    def tasks():
        """The engine's windowed task feed: (lazy reader, group,
        close_after) per planned unit, pulling file opens DEPTH-ahead.
        Runs on the consumer thread (the engine's submission loop lives
        in the generator we drive)."""
        i = 0
        while True:
            while i >= len(units):
                if not ensure_next_file():
                    return
            fi_, gp, _cache, _cost = units[i]
            tpu = files[fi_][0]
            # a file's units all append at its open, so the next unit's
            # file index changing (or the list ending) marks its last one
            last_of_file = i + 1 >= len(units) or units[i + 1][0] != fi_
            yield (
                (lambda t=tpu: t), gp.group_index, last_of_file, None,
                compute_req, gp.covered,
            )
            i += 1

    groups = None
    try:
        # the first file opens up front: its schema defines the
        # positional contract below (and an empty dataset is a no-op)
        ensure_next_file()
        sel_names: List[str] = []
        desc_by: dict = {}
        if files:
            want = set(columns) if columns else None
            first = files[0][0].reader
            for c in first.schema.columns:
                if want is None or c.path[0] in want:
                    n = c.path[0] if len(c.path) == 1 else ".".join(c.path)
                    sel_names.append(n)
                    desc_by[n] = c
        pump()
        depth_hint = (
            adaptive.depth_hint() if adaptive is not None else None
        )
        groups = iter_dataset_row_groups(
            tasks(), columns=columns, depth_hint=depth_hint
        )
        i = 0
        while True:
            t0 = time.perf_counter()
            try:
                cols = next(groups)
            except StopIteration:
                break
            tracer.add("scan.consumer_stall", time.perf_counter() - t0)
            fi_, gp, cache_, cost = units[i]
            res_exprs = None
            if isinstance(cols, PushdownResult):
                res = cols
                if sc.aggregate is not None:
                    yield fi_, gp.group_index, res.agg
                    cols = None
                else:
                    tracer.count(
                        "scan.rows_filtered_device",
                        res.num_rows - res.num_selected,
                    )
                    cols = res.columns
                    res_exprs = res.exprs
            if cols is not None:
                # the POSITIONAL contract: every yielded group carries
                # the FIRST file's selected columns, in schema order —
                # exactly the sequential TPU batch path's ordering rule.
                # A chunk missing from a group raises — UNLESS salvage
                # recorded its quarantine, in which case it stays IN
                # POSITION as a fail-loudly placeholder (the host batch
                # face's contract).
                rep = files[fi_][0].reader.salvage_report
                ordered = {}
                for n in sel_names:
                    if n not in cols:
                        if salvage and rep is not None and \
                                rep.chunk_quarantined(gp.group_index, n):
                            ordered[n] = BatchColumn(
                                desc_by[n], None, quarantined=True
                            )
                            continue
                        raise ValueError(
                            f"row group {gp.group_index} missing column {n}"
                        )
                    ordered[n] = cols[n]
                if res_exprs:
                    # computed outputs ride AFTER the schema columns, in
                    # plan order (docs/query.md's delivery contract)
                    from ..query.expr import ComputedColumn

                    for en, (vals, emask) in res_exprs.items():
                        ordered[en] = ComputedColumn(en, vals, emask)
                    tracer.count(
                        "query.expr_rows",
                        len(res_exprs) * int(res.num_selected),
                    )
                yield fi_, gp.group_index, ordered
            floor = i + 1
            # the engine staged this group before yielding it: its
            # raw extents are dead weight now — drop and refill
            if loads and loads[0][0] == i:
                _, cost0, fut = loads.popleft()
                try:
                    fut.result()
                except Exception:
                    pass  # failed prefetch already fell back to direct reads
                budget.release(cost0)
            cache_.drop(gp.extents)
            pump()
            i += 1
        if state["deferred"] is not None:
            # file-boundary error, deferred until every already-planned
            # group delivered (sequential error order); tagged so row
            # faces can re-raise it UNWRAPPED at the file boundary
            err, state["deferred"] = state["deferred"], None
            err.pftpu_scan_planning = True
            raise err
    finally:
        # quiesce the engine pipeline FIRST: closing the generator
        # joins its stage/ship pools, so no in-flight stage read can
        # race the reader closes below (the io.source close contract)
        if groups is not None:
            groups.close()
        pool.shutdown(wait=True)
        for r in readers:
            r.close()
        import sys as _sys

        # a raising callback must never REPLACE a scan error that is
        # already unwinding through this finally — the report is
        # diagnostics, the in-flight error is the diagnosis
        unwinding = _sys.exc_info()[0] is not None
        if on_salvage is not None and salvage:
            merged = SalvageReport.merge([
                r.reader.salvage_report for r in readers
                if r.reader.salvage_report is not None
            ])
            try:
                on_salvage(merged)
            except Exception:
                if not unwinding:
                    raise
        if on_report is not None:
            try:
                on_report(tracer.scan_report(
                    wall_seconds=time.perf_counter() - t_start,
                    budget_bytes=sc.prefetch_bytes,
                ))
            except Exception:
                if not unwinding:
                    raise


def _pushdown_compact(batch, predicate, projection=None):
    """Host-leg pushdown row compaction (docs/pushdown.md): evaluate the
    predicate over one decoded ``RowGroupBatch`` and keep only the
    surviving rows — the host twin of the device leg's fused compact
    output, so both legs deliver the same row sets under
    ``ScanOptions(pushdown=True)``.  Null cells never match
    (``eval_mask`` semantics, identical on both legs).  ``projection``
    (a top-level name set, or None = all) trims predicate-only columns
    the widened decode filter pulled in — they shaped the mask, they do
    not ship, exactly like the device leg.  Runs on the decode worker
    thread; ``scan.rows_filtered_host`` counts what was dropped."""
    import numpy as np

    from ..batch.columns import ColumnBatch, RowGroupBatch, take_rows
    from ..batch.predicate import eval_mask

    n = batch.num_rows
    mask = eval_mask(predicate, _batch_resolver(batch), n)
    k = int(np.count_nonzero(mask))
    trace.count("scan.rows_filtered_host", n - k)
    deliver = [
        cb for cb in batch.columns
        if projection is None or cb.descriptor.path[0] in projection
    ]
    if k == n:
        if len(deliver) == len(batch.columns):
            return batch
        return RowGroupBatch(columns=deliver, num_rows=n)
    keep = np.flatnonzero(mask)
    cols = []
    for cb in deliver:
        values, new_dl = take_rows(
            cb.values, cb.def_levels,
            cb.descriptor.max_definition_level, keep,
        )
        cols.append(ColumnBatch(
            cb.descriptor, k, values, def_levels=new_dl,
        ))
    return RowGroupBatch(columns=cols, num_rows=k)


def _batch_resolver(batch):
    """``(values, null_mask)`` resolver over a decoded host
    ``RowGroupBatch`` — the shape ``batch.predicate.eval_mask`` and
    ``batch.aggregate.host_partial`` consume.  String columns resolve
    to object arrays of ``bytes`` (distinct-value comparisons happen on
    host anyway)."""
    import numpy as np

    from ..format.encodings.plain import ByteArrayColumn

    by_name = {}
    for cb in batch.columns:
        by_name[".".join(cb.descriptor.path)] = cb
    cache: dict = {}

    def resolve(name: str):
        if name in cache:
            return cache[name]
        cb = by_name.get(name)
        if cb is None:
            raise ValueError(f"column {name!r} missing from the batch")
        dense, mask = cb.dense()
        if isinstance(dense, ByteArrayColumn):
            data = dense.data.tobytes()
            offs = dense.offsets
            vals = np.empty(len(dense), dtype=object)
            for i in range(len(dense)):
                vals[i] = data[offs[i] : offs[i + 1]]
        else:
            vals = np.asarray(dense)
        cache[name] = (vals, mask)
        return cache[name]

    return resolve


def scan_aggregate(sources: Sequence, aggregate,
                   predicate=None,
                   options: Optional[ReaderOptions] = None,
                   scan: Optional[ScanOptions] = None,
                   engine: str = "tpu",
                   float64_policy: str = "float64",
                   dict_form: str = "gather"):
    """Answer an aggregate query over a dataset: returns the combined
    :class:`~parquet_floor_tpu.batch.aggregate.AggPartial` (call
    ``.finalize()`` for plain values).

    ``engine="tpu"`` ships tiny per-group partial states off the device
    (O(groups) bytes of D2H — docs/pushdown.md); shapes the device tail
    cannot evaluate (repeated columns, non-dictionary group keys,
    DOUBLE under a lossy float policy) fall back to the host leg —
    results identical by construction, recorded as an
    ``engine.pushdown`` decision.  ``engine="host"`` decodes on host
    and computes the same partials with NumPy.  ``predicate`` filters
    rows (and prunes groups/pages exactly like any other scan —
    statistics first, ``ScanOptions.page_prune`` optionally)."""
    from dataclasses import replace as _replace

    from ..batch.aggregate import Aggregate, AggPartial, host_partial
    from ..batch.predicate import eval_mask
    from ..errors import UnsupportedFeatureError

    if not isinstance(aggregate, Aggregate):
        raise ValueError("aggregate must be a batch.aggregate.Aggregate")
    sc = scan or ScanOptions()
    if engine not in ("tpu", "host"):
        raise ValueError(f"bad engine {engine!r}")
    if options is not None and options.salvage:
        # rejected HERE, before the device attempt: the device leg's own
        # salvage rejection must not be swallowed by the host fallback
        # below into an aggregate that silently drops quarantined rows
        raise UnsupportedFeatureError(
            "aggregate queries do not compose with salvage (quarantine "
            "decisions are group-wide); scan with salvage and aggregate "
            "the surviving batches yourself"
        )
    need_dev = set(aggregate.columns())
    if predicate is not None:
        from ..batch.predicate import tree as _tree
        from ..batch.predicate import tree_columns as _tree_columns

        need_dev |= _tree_columns(_tree(predicate))
    proj = sorted({c.split(".")[0] for c in need_dev})
    if engine == "tpu":
        dev_sc = _replace(sc, aggregate=aggregate)
        try:
            out = AggPartial(aggregate)
            for _fi, _gi, part in scan_device_groups(
                sources, columns=proj, options=options, scan=dev_sc,
                predicate=predicate, float64_policy=float64_policy,
                dict_form=dict_form,
            ):
                out.combine(part)
            return out
        except UnsupportedFeatureError as e:
            trace.decision("engine.pushdown", {
                "action": "host_fallback",
                "why": str(e)[:200],
            })
    # host leg: decode the needed columns, evaluate the same predicate
    # mask, compute the same partials — bit-identical combine protocol
    out = AggPartial(aggregate)
    scanner = DatasetScanner(
        sources, columns=proj, options=options, scan=_replace(
            sc, pushdown=False, aggregate=None
        ), predicate=predicate,
    )
    try:
        for unit in scanner:
            resolve = _batch_resolver(unit.batch)
            n = int(unit.batch.num_rows)
            sel = (
                eval_mask(predicate, resolve, n)
                if predicate is not None else None
            )
            out.combine(host_partial(aggregate, resolve, n, sel))
    finally:
        scanner.close()
    return out
