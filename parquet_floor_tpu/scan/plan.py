"""Scan I/O planner — coalesced read extents for row-group scans.

The dataset iterators used to issue one ``read_at`` per column chunk (and
the page-index reads one more each), paying one seek/syscall per range
even when ranges sit a few KB apart on disk.  This module turns the byte
ranges a row group needs into **coalesced extents**: ranges separated by
at most ``ScanOptions.max_gap_bytes`` merge into one read (the gap bytes
are over-read and discarded — the same trade Arrow Datasets makes with
its read-range coalescing), and extents are capped at
``ScanOptions.max_extent_bytes`` so one read never monopolizes the
in-flight byte budget.

Everything here is pure planning over footer metadata — no I/O happens in
this module.  The executor (:mod:`parquet_floor_tpu.scan.executor`) reads
the planned extents through ``Source.read_many`` and serves the decode
path from the prefetched bytes.

Observability: every plan emits ``trace.count`` counters —
``scan.ranges_planned`` (pre-merge), ``scan.extents_planned``
(post-merge), ``scan.bytes_used`` (the bytes decode actually wants),
``scan.bytes_read`` (what the coalesced extents fetch) and
``scan.overread_bytes`` (their difference).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Set, Tuple

from ..utils import trace

#: What ``max_gap_bytes=None`` resolves to wherever no RTT/bandwidth
#: measurement is available (local chains, the planner called directly,
#: the first loads of a remote scan before the controller warms up).
DEFAULT_MAX_GAP_BYTES = 64 << 10


@dataclass(frozen=True)
class ScanOptions:
    """Knobs of the scan scheduler (planner + executor).

    * ``max_gap_bytes`` — ranges separated by at most this many bytes
      merge into one read extent.  0 still merges *touching* ranges.
      ``None`` = auto-tune: under ``adaptive_prefetch`` the executor
      derives the gap from the measured RTT x bandwidth (the bytes one
      round trip is worth — reading them as filler is free compared to
      paying another request), recorded as a
      ``scan.max_gap_autotuned`` decision; until measurements exist
      (and anywhere the executor is not involved) ``None`` behaves as
      :data:`DEFAULT_MAX_GAP_BYTES`.
    * ``max_extent_bytes`` — soft cap on one extent; a single range
      bigger than the cap stays one extent (it cannot be split without
      re-splitting the read), but no merge grows past it.
    * ``prefetch_bytes`` — the executor's in-flight byte budget: the sum
      of all prefetched-but-unconsumed bytes (raw extents or decoded
      batches, whichever is larger per group) never exceeds it.  One
      group larger than the whole budget is admitted only when it is
      alone in flight.
    * ``threads`` — worker threads reading extents and decoding groups.
    * ``adaptive_prefetch`` — latency-adaptive budget/depth
      (docs/remote.md): ``prefetch_bytes`` becomes a CEILING, and the
      effective in-flight budget is sized from the measured per-extent
      RTT — a 50 ms object store earns deep pipelining, a warm local
      SSD stays shallow instead of pinning tens of MB it cannot use.
      The device scan face additionally derives its pipeline depth
      (``PFTPU_PREFETCH_DEPTH``'s default) from the same measurements;
      an explicit env override still wins.
    * ``page_prune`` — with a ``predicate``, prune each surviving row
      group to the OffsetIndex page boundaries of the predicate's
      ``row_ranges``: only the candidate pages' bytes are planned, read,
      and decoded (``scan.pages_pruned`` counts the skipped data
      pages), and delivered units carry only the covered rows.  OPT-IN
      because it changes the delivered row set from "whole surviving
      groups" to "covered page spans" — the lookup face's granularity
      on the scan face (docs/serving.md's pruning ladder, rung 3).
      Honored on BOTH scan faces (host ``DatasetScanner`` and the
      device leg); ignored without a predicate and under salvage
      (quarantine decisions are group-wide).
    * ``pushdown`` — row filtering below the delivery surface
      (docs/pushdown.md): the device leg evaluates the scan's
      ``predicate`` INSIDE each group's fused decode executable and
      delivers only the surviving rows, device-compacted
      (``scan.rows_filtered_device`` counts what never crossed D2H);
      the host ``DatasetScanner`` mask-compacts each decoded batch to
      the same surviving rows (``scan.rows_filtered_host``), so BOTH
      legs deliver identical row sets.  Composes with ``page_prune``
      (the storage-side rung narrows what decodes; the pushdown rung
      filters what ships).  Ignored without a predicate and under
      salvage; flat columns only (repeated leaves reject, both legs).
    * ``aggregate`` — a :class:`~parquet_floor_tpu.batch.aggregate.Aggregate`:
      the device leg ships per-group PARTIAL aggregate states
      (O(groups) bytes of D2H) instead of columns; fold them with
      ``scan.scan_aggregate`` (docs/pushdown.md).
    * ``project_exprs`` — ``((name, Expr-or-tree), ...)`` computed
      output columns (``docs/query.md``): the device leg evaluates each
      expression INSIDE the fused decode executable and delivers the
      results alongside the projected columns (the expression is part
      of the executable's persistent exec-cache key); the host leg
      computes the bit-equal twin with
      :func:`~parquet_floor_tpu.query.expr.eval_expr_host`.  Does not
      compose with ``aggregate`` or salvage.
    """

    max_gap_bytes: Optional[int] = DEFAULT_MAX_GAP_BYTES
    max_extent_bytes: int = 8 << 20
    prefetch_bytes: int = 64 << 20
    threads: int = 4
    adaptive_prefetch: bool = False
    page_prune: bool = False
    pushdown: bool = False
    aggregate: Optional[object] = None
    project_exprs: tuple = ()

    def __post_init__(self):
        if self.aggregate is not None:
            from ..batch.aggregate import Aggregate

            if not isinstance(self.aggregate, Aggregate):
                raise ValueError(
                    "ScanOptions.aggregate must be a "
                    "batch.aggregate.Aggregate"
                )
        if self.project_exprs:
            from ..query.expr import exprs_signature

            if self.aggregate is not None:
                raise ValueError(
                    "ScanOptions.project_exprs does not compose with "
                    "aggregate (an aggregate scan ships states, not "
                    "columns)"
                )
            # normalize eagerly: a malformed tree fails HERE, loudly,
            # not inside a jit trace (frozen dataclass — go around)
            object.__setattr__(
                self, "project_exprs", exprs_signature(self.project_exprs)
            )
        if self.max_gap_bytes is not None and self.max_gap_bytes < 0:
            raise ValueError(f"max_gap_bytes must be >= 0, got {self.max_gap_bytes}")
        if self.max_extent_bytes <= 0:
            raise ValueError(
                f"max_extent_bytes must be > 0, got {self.max_extent_bytes}"
            )
        if self.prefetch_bytes <= 0:
            raise ValueError(
                f"prefetch_bytes must be > 0, got {self.prefetch_bytes}"
            )
        if self.threads < 1:
            raise ValueError(f"threads must be >= 1, got {self.threads}")


@dataclass(frozen=True)
class Extent:
    """One coalesced read: ``[offset, offset + length)`` covering
    ``used`` bytes of actually-wanted ranges (``length - used`` is the
    over-read the merge decided to pay)."""

    offset: int
    length: int
    used: int

    @property
    def end(self) -> int:
        return self.offset + self.length


@dataclass
class GroupPlan:
    """The I/O plan of one row group: its chunks' byte ranges coalesced
    into extents, plus footer-derived size facts the executor budgets
    with.  ``covered`` (page-pruned plans only) is the page-aligned row
    cover the group was narrowed to — the executor decodes it through
    ``read_row_group_ranges`` instead of the whole group."""

    group_index: int
    extents: List[Extent]
    read_bytes: int          # sum of extent lengths (what hits the disk)
    used_bytes: int          # sum of the wanted ranges
    uncompressed_bytes: int  # footer estimate of the decoded size
    num_rows: int
    covered: Optional[List[Tuple[int, int]]] = None


@dataclass
class FilePlan:
    """Per-file plan: one :class:`GroupPlan` per (kept) row group plus
    the shared index extents (page indexes — read once, cached by the
    reader for every group)."""

    index_extents: List[Extent] = field(default_factory=list)
    groups: List[GroupPlan] = field(default_factory=list)


def coalesce(ranges: Sequence[Tuple[int, int]], max_gap: int,
             max_extent: int) -> List[Extent]:
    """Merge ``(offset, length)`` ranges into ascending coalesced extents.

    Overlapping or duplicate ranges are unioned (``used`` counts each
    byte once).  Zero-length ranges are dropped.
    """
    spans = sorted((int(o), int(o) + int(n)) for o, n in ranges if n > 0)
    if not spans:
        return []
    out: List[Extent] = []
    cur_s, cur_e = spans[0]
    used = cur_e - cur_s
    for s, e in spans[1:]:
        gap = s - cur_e
        new_e = max(cur_e, e)
        if gap <= max_gap and new_e - cur_s <= max_extent:
            used += max(0, e - max(s, cur_e))  # overlap counts once
            cur_e = new_e
            continue
        out.append(Extent(cur_s, cur_e - cur_s, used))
        cur_s, cur_e = s, e
        used = e - s
    out.append(Extent(cur_s, cur_e - cur_s, used))
    return out


def chunk_ranges(rg, column_filter: Optional[Set[str]] = None
                 ) -> List[Tuple[int, int]]:
    """The data byte ranges of one row group's (selected) column chunks —
    dictionary page through last data page, exactly what
    ``read_column_chunk`` fetches."""
    from ..format.file_read import _chunk_byte_range

    ranges = []
    for chunk in rg.columns or []:
        meta = chunk.meta_data
        if meta is None:
            continue  # diagnosed later by read_column_chunk, with context
        if column_filter and meta.path_in_schema and \
                meta.path_in_schema[0] not in column_filter:
            continue
        if meta.data_page_offset is None or \
                meta.total_compressed_size is None:
            # corrupt meta (a thrift flip can erase a field and still
            # parse): planning skips the chunk; read_column_chunk hits
            # the same hole inside the classified-error ladder and
            # raises/quarantines WITH context — the planner must not
            # crash ahead of it with a bare TypeError
            continue
        start, length = _chunk_byte_range(meta)
        ranges.append((int(start), int(length)))
    return ranges


def pruned_chunk_ranges(reader, rg, covered,
                        column_filter: Optional[Set[str]] = None):
    """Byte ranges of exactly what ``read_row_group_ranges`` will read
    for a page-pruned group: each selected chunk's dictionary page plus
    the data pages whose rows intersect ``covered`` (OffsetIndex truth).
    Returns ``(ranges, pages_pruned)``; only called for groups whose
    every selected chunk HAS an OffsetIndex (``page_cover`` returned a
    partial cover, which requires one)."""
    n = int(rg.num_rows or 0)
    ranges: List[Tuple[int, int]] = []
    pruned = 0
    for chunk in rg.columns or []:
        meta = chunk.meta_data
        if meta is None:
            continue
        if column_filter and meta.path_in_schema and \
                meta.path_in_schema[0] not in column_filter:
            continue
        oi = reader.read_offset_index(chunk)
        locs = oi.page_locations if oi is not None else None
        if not locs:
            # page_cover's contract makes this unreachable for pruned
            # groups; fall back to the whole chunk rather than dropping it
            if meta.data_page_offset is not None and \
                    meta.total_compressed_size is not None:
                from ..format.file_read import _chunk_byte_range

                start, length = _chunk_byte_range(meta)
                ranges.append((int(start), int(length)))
            continue
        doff = meta.dictionary_page_offset
        if doff is not None and doff > 0:
            ranges.append((int(doff), int(locs[0].offset) - int(doff)))
        from ..format.file_read import page_row_spans, spans_overlap

        for pl, a, b in page_row_spans(oi, n):
            if spans_overlap(a, b, covered):
                ranges.append((int(pl.offset), int(pl.compressed_page_size)))
            else:
                pruned += 1
    return ranges, pruned


def index_ranges(rg, column_filter: Optional[Set[str]] = None
                 ) -> List[Tuple[int, int]]:
    """Page-index (OffsetIndex/ColumnIndex) byte ranges of a row group's
    selected chunks — tiny, footer-adjacent, and read by ``page_cover``/
    predicates; prefetching them spares one seek each."""
    ranges = []
    for chunk in rg.columns or []:
        meta = chunk.meta_data
        if column_filter and meta is not None and meta.path_in_schema and \
                meta.path_in_schema[0] not in column_filter:
            continue
        for off, ln in (
            (chunk.offset_index_offset, chunk.offset_index_length),
            (chunk.column_index_offset, chunk.column_index_length),
        ):
            if off is not None and ln:
                ranges.append((int(off), int(ln)))
    return ranges


def plan_file(reader, column_filter: Optional[Set[str]] = None,
              keep: Optional[Set[int]] = None,
              options: Optional[ScanOptions] = None,
              covered_by_group: Optional[dict] = None) -> FilePlan:
    """Plan every (kept) row group of an open ``ParquetFileReader``.

    ``keep`` restricts to a predicate's surviving group indices (None =
    all).  ``covered_by_group`` maps a group index to the page-aligned
    row cover ``ScanOptions.page_prune`` narrowed it to: those groups
    plan only their candidate pages' byte ranges (dictionary page
    included), record the cover on the :class:`GroupPlan`, and count the
    skipped data pages as ``scan.pages_pruned``.  Counters land in
    ``trace``; per-file totals also surface as a ``scan.plan`` trace
    decision.
    """
    opts = options or ScanOptions()
    # None = auto-tune, which the EXECUTOR resolves (it owns the RTT
    # measurements) by handing plan_file an already-resolved options
    # object; a direct caller just gets the default
    gap = (
        opts.max_gap_bytes if opts.max_gap_bytes is not None
        else DEFAULT_MAX_GAP_BYTES
    )
    plan = FilePlan()
    idx_ranges: List[Tuple[int, int]] = []
    for gi, rg in enumerate(reader.row_groups):
        if keep is not None and gi not in keep:
            continue
        covered = (covered_by_group or {}).get(gi)
        if covered is not None:
            ranges, pruned = pruned_chunk_ranges(
                reader, rg, covered, column_filter
            )
            trace.count("scan.pages_pruned", pruned)
        else:
            ranges = chunk_ranges(rg, column_filter)
        extents = coalesce(ranges, gap, opts.max_extent_bytes)
        gp = GroupPlan(
            group_index=gi,
            extents=extents,
            read_bytes=sum(e.length for e in extents),
            used_bytes=sum(e.used for e in extents),
            uncompressed_bytes=sum(
                int(c.meta_data.total_uncompressed_size or 0)
                for c in rg.columns or []
                if c.meta_data is not None and (
                    not column_filter
                    or not c.meta_data.path_in_schema
                    or c.meta_data.path_in_schema[0] in column_filter
                )
            ),
            num_rows=int(rg.num_rows or 0),
            covered=covered,
        )
        plan.groups.append(gp)
        idx_ranges.extend(index_ranges(rg, column_filter))
        trace.count("scan.ranges_planned", len(ranges))
        trace.count("scan.extents_planned", len(extents))
        trace.count("scan.bytes_read", gp.read_bytes)
        trace.count("scan.bytes_used", gp.used_bytes)
        trace.count("scan.overread_bytes", gp.read_bytes - gp.used_bytes)
    plan.index_extents = coalesce(
        idx_ranges, gap, opts.max_extent_bytes
    )
    trace.count("scan.ranges_planned", len(idx_ranges))
    trace.count("scan.extents_planned", len(plan.index_extents))
    idx_read = sum(e.length for e in plan.index_extents)
    idx_used = sum(e.used for e in plan.index_extents)
    trace.count("scan.bytes_read", idx_read)
    trace.count("scan.bytes_used", idx_used)
    trace.count("scan.overread_bytes", idx_read - idx_used)
    trace.decision("scan.plan", {
        "path": getattr(reader.source, "name", None),
        "groups": len(plan.groups),
        "extents": sum(len(g.extents) for g in plan.groups)
        + len(plan.index_extents),
        "bytes_read": sum(g.read_bytes for g in plan.groups) + idx_read,
        "bytes_used": sum(g.used_bytes for g in plan.groups) + idx_used,
    })
    return plan
