"""Minimal XSpace (``.xplane.pb``) reader for the one-clock timeline.

``jax.profiler.trace`` always writes the raw profiler capture as an
``XSpace`` protobuf (``plugins/profile/<run>/<host>.xplane.pb``) —
planes (one per device / host component) → lines (one per thread or
hardware queue) → events with picosecond offsets.  Converting it to a
viewable trace normally requires the TensorFlow profiler toolchain;
this module reads the few fields the unified export needs with a
hand-rolled varint walker instead (the package already speaks thrift
compact, snappy, and RLE by hand — one more wire format keeps the
no-new-dependencies rule).

Field numbers follow ``tsl/profiler/protobuf/xplane.proto``:

* ``XSpace.planes = 1``
* ``XPlane``: ``id=1 name=2 lines=3 event_metadata=4`` (map entries:
  ``key=1 value=2``)
* ``XLine``: ``id=1 name=2 timestamp_ns=3 events=4 display_name=11``
* ``XEvent``: ``metadata_id=1 offset_ps=2 duration_ps=3``
* ``XEventMetadata``: ``id=1 name=2 display_name=4``

Unknown fields are skipped by wire type, so schema growth upstream
cannot break the walk.  Docs: ``docs/observability.md``.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

# trace-event pids for device-origin processes: past Linux's maximum
# kernel.pid_max (2**22) so the host process row can never collide
_DEVICE_PID_BASE = 1 << 22


def _varint(buf: bytes, i: int) -> Tuple[int, int]:
    r = 0
    s = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << s
        if not b & 0x80:
            return r, i
        s += 7


def _fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield ``(field_number, wire_type, value)`` triples of one
    message.  Varints come back as ints, length-delimited fields as
    ``bytes`` slices; 32/64-bit fields are skipped over but yielded raw
    so callers may ignore them."""
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _varint(buf, i)
        fn, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 2:
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        yield fn, wt, v


class XEvent:
    __slots__ = ("name", "start_ns", "duration_ns")

    def __init__(self, name: str, start_ns: float, duration_ns: float):
        self.name = name
        self.start_ns = start_ns
        self.duration_ns = duration_ns


class XLine:
    __slots__ = ("line_id", "name", "timestamp_ns", "events")

    def __init__(self, line_id: int, name: str, timestamp_ns: int,
                 events: List[XEvent]):
        self.line_id = line_id
        self.name = name
        self.timestamp_ns = timestamp_ns
        self.events = events


class XPlane:
    __slots__ = ("name", "lines")

    def __init__(self, name: str, lines: List[XLine]):
        self.name = name
        self.lines = lines


def _parse_line(buf: bytes, meta: Dict[int, str]) -> XLine:
    line_id = 0
    name = ""
    ts_ns = 0
    raw_events: List[bytes] = []
    display = None
    for fn, wt, v in _fields(buf):
        if fn == 1 and wt == 0:
            line_id = v
        elif fn == 2 and wt == 2:
            name = v.decode("utf-8", "replace")
        elif fn == 11 and wt == 2:
            display = v.decode("utf-8", "replace")
        elif fn == 3 and wt == 0:
            ts_ns = v
        elif fn == 4 and wt == 2:
            raw_events.append(v)
    events: List[XEvent] = []
    for ev in raw_events:
        mid = 0
        off_ps = 0
        dur_ps = 0
        for fn, wt, v in _fields(ev):
            if fn == 1 and wt == 0:
                mid = v
            elif fn == 2 and wt == 0:
                off_ps = v
            elif fn == 3 and wt == 0:
                dur_ps = v
        events.append(XEvent(
            meta.get(mid, f"event#{mid}"),
            ts_ns + off_ps / 1e3,
            dur_ps / 1e3,
        ))
    return XLine(line_id, display or name, ts_ns, events)


def _parse_plane(buf: bytes) -> XPlane:
    name = ""
    meta: Dict[int, str] = {}
    raw_lines: List[bytes] = []
    for fn, wt, v in _fields(buf):
        if fn == 2 and wt == 2:
            name = v.decode("utf-8", "replace")
        elif fn == 4 and wt == 2:
            # map<int64, XEventMetadata> entry: key=1, value=2
            k = None
            md = None
            for fn2, wt2, v2 in _fields(v):
                if fn2 == 1 and wt2 == 0:
                    k = v2
                elif fn2 == 2 and wt2 == 2:
                    md = v2
            if k is None or md is None:
                continue
            mname = None
            for fn3, wt3, v3 in _fields(md):
                if fn3 == 2 and wt3 == 2:
                    mname = v3.decode("utf-8", "replace")
            if mname:
                meta[k] = mname
        elif fn == 3 and wt == 2:
            raw_lines.append(v)
    return XPlane(name, [_parse_line(b, meta) for b in raw_lines])


def parse_xplane(path: str) -> List[XPlane]:
    """Every plane of one ``.xplane.pb`` capture."""
    with open(path, "rb") as fh:
        buf = fh.read()
    return [_parse_plane(v) for fn, wt, v in _fields(buf)
            if fn == 1 and wt == 2]


def find_sync_event(planes: List[XPlane],
                    marker: str) -> Optional[float]:
    """Profiler-clock start time (µs) of the planted clock-sync
    annotation, or None when the capture does not carry it."""
    for plane in planes:
        for line in plane.lines:
            for ev in line.events:
                if ev.name == marker:
                    return ev.start_ns / 1e3
    return None


def device_trace_events(xplane_path: str, sync_marker: str,
                        host_sync_us: float,
                        skip_python: bool = True) -> List[dict]:
    """The capture as Chrome trace-event dicts REBASED onto the host
    tracer clock: ``offset = host_sync_us - marker's profiler-clock
    time``, applied to every event.  Without the marker (dropped
    annotation) the earliest captured event is pinned to the host sync
    point instead — degraded alignment beats a second clock.

    Every event is a complete ("X") event tagged ``cat="xla"`` /
    ``args.origin="device"``, so consumers (and the CI smoke) can tell
    XLA-capture events from the host tracer's ``cat="pftpu"`` spans;
    plane/line names ride along as process/thread metadata.

    ``skip_python`` (default) drops the host python-tracer's
    per-source-line events (names like ``$module.py:42 fn`` — tens of
    thousands per capture, and the host side of the story is already
    told by the tracer's own spans); XLA runtime/kernel events have no
    ``$`` prefix and always survive."""
    planes = parse_xplane(xplane_path)
    sync_us = find_sync_event(planes, sync_marker)
    if sync_us is None:
        starts = [ev.start_ns / 1e3
                  for p in planes for ln in p.lines for ev in ln.events]
        if not starts:
            return []
        sync_us = min(starts)
    offset_us = host_sync_us - sync_us
    out: List[dict] = []
    for pi, plane in enumerate(planes):
        # the planted sync marker is rebase INPUT, not capture output:
        # emitting it would let "the file contains device-origin
        # events" be satisfied by an event the exporter itself wrote
        # (a broken capture must fail that check, not ship green)
        pid = _DEVICE_PID_BASE + pi
        plane_meta_done = False
        # fallback tids must never collide with a REAL line id in the
        # same plane (two queues merged onto one trace row); allocate
        # around the taken ids
        taken = {ln.line_id for ln in plane.lines if ln.line_id}
        next_tid = 1
        for li, line in enumerate(plane.lines):
            events = [ev for ev in line.events if ev.name != sync_marker]
            if skip_python:
                events = [ev for ev in events
                          if not ev.name.startswith("$")]
            if not events:
                continue
            if not plane_meta_done:
                plane_meta_done = True
                out.append({
                    "name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": plane.name or f"plane#{pi}"},
                })
            if line.line_id:
                tid = line.line_id
            else:
                while next_tid in taken:
                    next_tid += 1
                tid = next_tid
                taken.add(tid)
            out.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": line.name or f"line#{li}"},
            })
            for ev in events:
                out.append({
                    "name": ev.name, "ph": "X", "cat": "xla",
                    "pid": pid, "tid": tid,
                    "ts": round(ev.start_ns / 1e3 + offset_us, 3),
                    "dur": round(ev.duration_ns / 1e3, 3),
                    "args": {"origin": "device"},
                })
    return out
