"""Shared utilities."""
