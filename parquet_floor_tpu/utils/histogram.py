"""Mergeable log-bucketed histograms — the distribution half of the
observability layer (``docs/observability.md``).

The counter/gauge/span ``Tracer`` (PR 4) answers "how much" and "how
long in total"; a serving tier living by tail-latency SLOs (*The Tail
at Scale*, Dean & Barroso 2013) needs "what is the p99 **right now**"
— a question only a distribution can answer.  :class:`LogHistogram`
records values into exponentially-growing buckets whose boundaries are
a pure function of the ``growth`` factor, so two histograms recorded
anywhere (threads, tenants, processes, epochs) merge **associatively**
by adding per-bucket counts — the same serialize/merge law
:class:`~parquet_floor_tpu.utils.trace.ScanReport` established
(``as_dict``/``from_dict``/``merge``), reused verbatim by the SLO
monitor (``serve/slo.py``), the Prometheus exporter
(``utils/metrics_export.py``), and the bench JSON.

Accuracy: a value lands in the bucket ``(growth^(i-1), growth^i]``;
:meth:`percentile` interpolates linearly inside the straddled bucket
and clamps to the exact recorded min/max, so the relative error of any
quantile is bounded by the bucket width (``growth - 1``, ~9% at the
default ``2**(1/8)``) — pinned against numpy in
``tests/test_histogram.py``.  Values ``<= 0`` (a clock that did not
advance) go to a dedicated zero bucket and never touch ``log``.

Instances are NOT thread-safe on their own: the
:class:`~parquet_floor_tpu.utils.trace.Tracer` records into them under
its lock (``Tracer.observe``), which is where concurrent writers meet.
"""

from __future__ import annotations

import math
import random
from typing import Dict, Optional, Sequence, Tuple

#: default bucket growth factor: 2**(1/8) ~= +9.05% per bucket, 8
#: buckets per octave — sub-decibel quantile error at ~100 buckets
#: across the ns..minutes latency range
GROWTH = 2.0 ** 0.125

#: the exemplar reservoir's coin — module-level and seedable
#: (:func:`seed_exemplar_rng`) so reservoir replacement is
#: deterministic under test while staying uniform in production
_EXEMPLAR_RNG = random.Random()


def seed_exemplar_rng(seed: int) -> None:
    """Re-seed the shared exemplar-reservoir rng (tests pin it so the
    surviving exemplars are reproducible)."""
    _EXEMPLAR_RNG.seed(seed)


class LogHistogram:
    """One mergeable log-bucketed distribution (module docstring).

    ``record`` is O(1); ``merge``/``percentile`` are O(buckets).  The
    exact ``count``/``total``/``min``/``max`` ride along, so means and
    extreme quantiles stay exact even though the interior is bucketed.
    """

    __slots__ = ("growth", "_lng", "count", "total", "min", "max",
                 "zeros", "buckets", "exemplars")

    def __init__(self, growth: float = GROWTH):
        if growth <= 1.0:
            raise ValueError(f"growth must be > 1, got {growth}")
        self.growth = float(growth)
        self._lng = math.log(self.growth)
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.zeros = 0                       # values <= 0
        self.buckets: Dict[int, int] = {}    # bucket index -> count
        # per-bucket exemplar slot: bucket index -> (trace_id, value) —
        # a size-1 reservoir linking a (tail) bucket to one request
        # trace that landed there (docs/observability.md).  Empty until
        # a recorder OFFERS exemplars (Tracer.observe under an active
        # TraceContext); plain record() calls never touch it, so the
        # tracing-disabled path costs nothing here.
        self.exemplars: Dict[int, Tuple[str, float]] = {}

    # -- recording -----------------------------------------------------------

    def record(self, value: float, n: int = 1,
               exemplar: Optional[str] = None) -> bool:
        """Add ``n`` observations of ``value``.  ``exemplar`` (a
        trace_id) additionally offers the sample to the bucket's
        reservoir slot; returns True iff the slot stored it (an empty
        slot always accepts; an occupied one is replaced with
        probability 1/bucket_count — a size-1 uniform reservoir over
        the bucket's samples)."""
        v = float(value)
        n = int(n)
        if n <= 0:
            return False
        self.count += n
        self.total += v * n
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v
        if v <= 0.0:
            self.zeros += n
            return False
        # bucket i holds (growth^(i-1), growth^i]: ceil of the log puts
        # exact boundaries in the LOWER bucket, so bucket_hi(i) is an
        # inclusive upper bound
        i = math.ceil(math.log(v) / self._lng - 1e-9)
        c = self.buckets.get(i, 0) + n
        self.buckets[i] = c
        if exemplar is None:
            return False
        if i not in self.exemplars or _EXEMPLAR_RNG.random() * c < 1.0:
            self.exemplars[i] = (str(exemplar), v)
            return True
        return False

    # -- bucket geometry -----------------------------------------------------

    def bucket_hi(self, i: int) -> float:
        """Inclusive upper bound of bucket ``i`` (``growth ** i``)."""
        return self.growth ** i

    def bucket_lo(self, i: int) -> float:
        return self.growth ** (i - 1)

    # -- quantiles -----------------------------------------------------------

    def percentile(self, p: float) -> Optional[float]:
        """The ``p``-th percentile (0..100), or None when empty.
        Linear interpolation inside the straddled bucket, clamped to
        the exact recorded min/max."""
        if self.count == 0:
            return None
        if not 0.0 <= p <= 100.0:
            raise ValueError(f"percentile wants 0..100, got {p}")
        target = (p / 100.0) * self.count
        seen = float(self.zeros)
        if self.zeros and target <= seen:
            # the rank falls inside the zero bucket (values <= 0)
            return min(0.0, self.min)
        for i in sorted(self.buckets):
            c = self.buckets[i]
            if seen + c >= target:
                lo, hi = self.bucket_lo(i), self.bucket_hi(i)
                frac = (target - seen) / c
                v = lo + (hi - lo) * frac
                if self.min is not None:
                    v = max(v, self.min)
                if self.max is not None:
                    v = min(v, self.max)
                return v
            seen += c
        return self.max

    def count_above(self, threshold: float) -> int:
        """How many recorded values exceed ``threshold`` — the SLO
        monitor's violation count.  Values inside the straddled bucket
        are apportioned linearly (consistent with :meth:`percentile`)."""
        t = float(threshold)
        if self.count == 0:
            return 0
        if t < 0.0 or (self.max is not None and t >= self.max):
            # above-the-max is exact; below zero everything qualifies
            return self.count if t < 0.0 else 0
        above = 0.0
        for i, c in self.buckets.items():
            lo, hi = self.bucket_lo(i), self.bucket_hi(i)
            if t < lo:
                above += c
            elif t < hi:
                above += c * (hi - t) / (hi - lo)
        return min(self.count, int(round(above)))

    @property
    def mean(self) -> Optional[float]:
        return (self.total / self.count) if self.count else None

    # -- serialize / merge (the ScanReport law) ------------------------------

    def as_dict(self) -> dict:
        """JSON-ready form; ``from_dict`` round-trips it exactly.  The
        ``exemplars`` key appears only when slots are occupied, so
        pre-exemplar consumers of the serialized shape see the exact
        dict they always did."""
        d = {
            "growth": self.growth,
            "count": self.count,
            "sum": self.total,
            "min": self.min,
            "max": self.max,
            "zeros": self.zeros,
            # JSON objects key by string; indexes may be negative
            "buckets": {str(i): c for i, c in sorted(self.buckets.items())},
        }
        if self.exemplars:
            d["exemplars"] = {str(i): [t, v]
                              for i, (t, v) in sorted(self.exemplars.items())}
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "LogHistogram":
        h = cls(growth=float(d.get("growth", GROWTH)))
        h.count = int(d.get("count", 0))
        h.total = float(d.get("sum", 0.0))
        h.min = None if d.get("min") is None else float(d["min"])
        h.max = None if d.get("max") is None else float(d["max"])
        h.zeros = int(d.get("zeros", 0))
        h.buckets = {int(i): int(c)
                     for i, c in (d.get("buckets") or {}).items()}
        h.exemplars = {int(i): (str(e[0]), float(e[1]))
                       for i, e in (d.get("exemplars") or {}).items()}
        return h

    def copy(self) -> "LogHistogram":
        h = LogHistogram(growth=self.growth)
        h.count, h.total = self.count, self.total
        h.min, h.max, h.zeros = self.min, self.max, self.zeros
        h.buckets = dict(self.buckets)
        h.exemplars = dict(self.exemplars)
        return h

    def merge_in(self, other: "LogHistogram") -> "LogHistogram":
        """Fold ``other`` into self (additive, associative,
        commutative).  Mismatched growth factors cannot share buckets
        and are rejected rather than silently skewed."""
        if abs(other.growth - self.growth) > 1e-12:
            raise ValueError(
                f"cannot merge histograms with growth {other.growth} "
                f"into {self.growth}"
            )
        self.count += other.count
        self.total += other.total
        self.zeros += other.zeros
        if other.min is not None and (self.min is None
                                      or other.min < self.min):
            self.min = other.min
        if other.max is not None and (self.max is None
                                      or other.max > self.max):
            self.max = other.max
        for i, c in other.buckets.items():
            self.buckets[i] = self.buckets.get(i, 0) + c
        # exemplar slots: a size-1 reservoir cannot be merged exactly;
        # keep a present slot, and when BOTH sides hold one prefer the
        # incoming ``other`` (newer by convention in the snapshot fold)
        # — a deterministic rule, so the cross-process merge is stable
        for i, ex in other.exemplars.items():
            self.exemplars[i] = ex
        return self

    @classmethod
    def merge(cls, hists: Sequence["LogHistogram"]) -> "LogHistogram":
        """Fold many histograms into one — the cross-process /
        cross-tenant aggregation face, associative like
        ``ScanReport.merge``."""
        hists = list(hists)
        if not hists:
            raise ValueError("LogHistogram.merge needs at least one")
        out = hists[0].copy()
        for h in hists[1:]:
            out.merge_in(h)
        return out

    @classmethod
    def fold_dicts(cls, into: Dict[str, "LogHistogram"],
                   items: Dict[str, dict]) -> Dict[str, "LogHistogram"]:
        """Fold a name→``as_dict`` mapping into live histograms — THE
        one implementation of the serialized-merge law, shared by
        ``ScanReport.merge`` and ``metrics_export.merge_snapshots`` so
        the two aggregation paths can never diverge."""
        for k, d in (items or {}).items():
            h = cls.from_dict(d)
            if k in into:
                into[k].merge_in(h)
            else:
                into[k] = h
        return into

    def subtract(self, earlier: "LogHistogram") -> "LogHistogram":
        """The increase since ``earlier`` (an older snapshot of the SAME
        cumulative histogram) — the windowed-delta face the SLO monitor
        evaluates over.  A tracer reset between snapshots (total count
        went DOWN) degrades to "everything is new" — the whole current
        histogram — never to a blind window of clamped zeros."""
        if self.count < earlier.count:
            return self.copy()
        out = LogHistogram(growth=self.growth)
        out.count = max(0, self.count - earlier.count)
        out.total = max(0.0, self.total - earlier.total)
        out.zeros = max(0, self.zeros - earlier.zeros)
        for i, c in self.buckets.items():
            d = c - earlier.buckets.get(i, 0)
            if d > 0:
                out.buckets[i] = d
                # the slot's exemplar MAY predate the window; it is a
                # pointer, not a count, so carrying it is conservative
                if i in self.exemplars:
                    out.exemplars[i] = self.exemplars[i]
        if out.count:
            # a delta cannot recover the window's exact extremes; the
            # cumulative ones are conservative bounds
            out.min, out.max = self.min, self.max
        return out

    def render(self, unit: str = "s") -> str:
        """One compact human line: count, mean, p50/p90/p99, max."""
        if not self.count:
            return "(empty)"

        def fmt(v):
            return "n/a" if v is None else (
                f"{v * 1e3:.2f} ms" if unit == "s" else f"{v:.4g}{unit}"
            )

        return (
            f"n={self.count} mean={fmt(self.mean)} "
            f"p50={fmt(self.percentile(50))} p90={fmt(self.percentile(90))} "
            f"p99={fmt(self.percentile(99))} max={fmt(self.max)}"
        )

    def __repr__(self) -> str:
        return f"LogHistogram({self.render()})"
