"""Per-stage tracing/metrics — the observability subsystem SURVEY.md §5
prescribes for the new framework (the reference has none: its only output
is ``e.printStackTrace()`` in shims, ``FSDataInputStream.java:26,35,43``).

Three layers, all zero-cost when disabled:

* ``span(stage)`` — context manager accumulating wall time + byte counts
  per stage name (read / stage / ship / decode / assemble).
* ``count(name, n)`` / ``gauge_max(name, v)`` — plain integer counters
  (additive) and high-water gauges, for subsystems whose health is a
  number rather than a duration (the scan scheduler's extents planned /
  bytes over-read / prefetch queue depth live here).
* ``stats()`` / ``counters()`` / ``report()`` — snapshot (thread-safe).
* ``device_trace(dir)`` — wraps ``jax.profiler.trace`` so the device side
  of a decode shows up in TensorBoard/Perfetto alongside the host spans.

Enable with ``PFTPU_TRACE=1`` or ``trace.enable()``.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, Iterator

_enabled = os.environ.get("PFTPU_TRACE", "0") == "1"
_lock = threading.Lock()


@dataclass
class StageStat:
    count: int = 0
    seconds: float = 0.0
    bytes: int = 0

    def as_dict(self) -> dict:
        mbps = (self.bytes / self.seconds / 1e6) if self.seconds else 0.0
        return {
            "count": self.count,
            "seconds": round(self.seconds, 6),
            "bytes": self.bytes,
            "MB_per_s": round(mbps, 1),
        }


_stats: Dict[str, StageStat] = {}
_decisions: list = []  # bounded log of routing/policy decisions
_counters: Dict[str, int] = {}   # additive integer counters
_gauges: Dict[str, int] = {}     # high-water gauges (max ever seen)


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def reset() -> None:
    with _lock:
        _stats.clear()
        _decisions.clear()
        _counters.clear()
        _gauges.clear()


def count(name: str, n: int = 1) -> None:
    """Add ``n`` to the additive counter ``name`` (no-op when disabled)."""
    if not _enabled:
        return
    with _lock:
        _counters[name] = _counters.get(name, 0) + int(n)


def gauge_max(name: str, value: int) -> None:
    """Raise the high-water gauge ``name`` to at least ``value`` (no-op
    when disabled).  Gauges record peaks — e.g. the deepest a prefetch
    queue ever got — where an additive counter would be meaningless."""
    if not _enabled:
        return
    v = int(value)
    with _lock:
        if v > _gauges.get(name, -(1 << 62)):
            _gauges[name] = v


def counters() -> Dict[str, int]:
    """Snapshot of additive counters and high-water gauges (gauges appear
    under their own name; names are disjoint by convention —
    ``scan.queue_depth_max`` vs ``scan.extents_planned``)."""
    with _lock:
        out = dict(_counters)
        out.update(_gauges)
        return out


def decision(name: str, detail: dict) -> None:
    """Record a policy decision (e.g. engine="auto" routing) so consumers
    can see WHY a path was taken.  No-op when disabled; bounded."""
    if not _enabled:
        return
    with _lock:
        if len(_decisions) >= 64:
            _decisions.pop(0)
        _decisions.append({"decision": name, **detail})


def decisions() -> list:
    """Snapshot of recorded policy decisions (most recent last)."""
    with _lock:
        return list(_decisions)


def add(stage: str, seconds: float, nbytes: int = 0) -> None:
    if not _enabled:
        return
    with _lock:
        st = _stats.get(stage)
        if st is None:
            st = _stats[stage] = StageStat()
        st.count += 1
        st.seconds += seconds
        st.bytes += nbytes


@contextlib.contextmanager
def span(stage: str, nbytes: int = 0) -> Iterator[None]:
    """Accumulate one timed span under ``stage`` (no-op when disabled)."""
    if not _enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        add(stage, time.perf_counter() - t0, nbytes)


def stats() -> Dict[str, dict]:
    """Snapshot of all stage counters."""
    with _lock:
        return {k: v.as_dict() for k, v in sorted(_stats.items())}


def report() -> str:
    """Human-readable one-line-per-stage report (+ recorded decisions)."""
    lines = []
    for name, st in stats().items():
        lines.append(
            f"{name:<12} n={st['count']:<6} {st['seconds']*1e3:9.1f} ms"
            + (f"  {st['MB_per_s']:8.1f} MB/s" if st["bytes"] else "")
        )
    for name, v in sorted(counters().items()):
        lines.append(f"{name:<32} {v}")
    for d in decisions():
        kv = " ".join(f"{k}={v}" for k, v in d.items() if k != "decision")
        lines.append(f"[{d['decision']}] {kv}")
    return "\n".join(lines) or "(no spans recorded — is tracing enabled?)"


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Wrap a region in ``jax.profiler.trace`` so XLA device activity lands
    in TensorBoard/Perfetto next to the host spans."""
    import jax

    with jax.profiler.trace(log_dir):
        yield
