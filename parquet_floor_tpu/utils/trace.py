"""Scoped tracing/metrics — the observability subsystem SURVEY.md §5
prescribes for the new framework (the reference has none: its only output
is ``e.printStackTrace()`` in shims, ``FSDataInputStream.java:26,35,43``).

Everything lives on a :class:`Tracer`.  The module-level functions
(``span``/``count``/``gauge_max``/``decision``/…) delegate to the
**active** tracer: the process-global one by default (enable with
``PFTPU_TRACE=1`` or ``trace.enable()`` — every pre-existing call site
keeps working), or an isolated one inside ``with trace.scope() as t:``.
The scope rides a ``contextvars.ContextVar``, and the scan executor /
TPU engine worker pools bind each task to the scope that submitted it
(``Tracer.run``), so two concurrent ``DatasetScanner``\\ s or device
scans get correctly attributed, non-interfering metrics.

Five layers, all zero-cost when the active tracer is disabled (the no-op
path allocates nothing and takes no lock):

* ``span(stage, nbytes, attrs)`` — context manager accumulating wall
  time + byte counts per stage name (read / stage / ship / decode /
  assemble / io.read / scan.consumer_stall), and appending begin/end
  events with thread id + structured attrs (file, row group, column,
  extent offset, retry attempt) to the bounded raw-event timeline.
* ``count(name, n)`` / ``gauge_max(name, v)`` — additive integer
  counters and high-water gauges; snapshots are namespaced
  (``counters()`` / ``gauges()``, merged compat view in ``metrics()``).
* ``observe(name, seconds)`` — log-bucketed latency/size distributions
  (:class:`~parquet_floor_tpu.utils.histogram.LogHistogram`):
  mergeable across threads/tenants/processes, the substrate under
  per-tenant p99s, the SLO monitor (``serve/slo.py``), and the
  Prometheus exporter (``utils/metrics_export.py`` /
  :func:`serve_metrics`).
* ``decision(name, detail)`` — bounded log of routing/policy decisions
  (cap configurable per tracer; evictions bump
  ``trace.decisions_dropped`` — no silent caps), mirrored as instant
  events on the timeline.
* ``export_chrome_trace(path)`` — the timeline as Chrome/Perfetto
  trace-event JSON, so the host-side read‖stage‖ship‖decode overlap is
  visible next to ``device_trace``'s XLA capture — and
  :func:`unified_trace` merges BOTH captures onto one rebased clock in
  a single Perfetto file; ``scan_report()`` distills the same snapshot
  into a :class:`ScanReport` health summary, and ``report()`` renders
  everything for humans.

Metric names used by the package are registered in :class:`names`;
floorlint rule FL-OBS001 rejects unregistered literals (typo'd metric
names fail the lint gate).  Docs: ``docs/observability.md``.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence

from .histogram import LogHistogram


class names:
    """Central metric-name registry: every counter, gauge, decision, and
    span stage the package emits, in one place (the table in
    ``docs/observability.md`` documents each).  floorlint FL-OBS001
    checks ``trace.count/gauge_max/decision/span/add`` string literals in
    package code against these sets — a typo'd name fails the lint gate
    instead of silently splitting a metric in two."""

    COUNTERS = frozenset({
        "scan.ranges_planned",
        "scan.extents_planned",
        "scan.bytes_read",
        "scan.bytes_used",
        "scan.overread_bytes",
        "scan.bytes_prefetched",
        "scan.cache_miss_bytes",
        "io.retries",
        "io.retry_exhausted",
        # the device decode launch path (tpu/engine.py, docs/perf.md)
        "engine.launches",
        "engine.exec_cache_hits",
        "engine.exec_cache_misses",
        "engine.compile_ms",
        # the remote-storage failure domain (io/remote.py, docs/remote.md)
        "io.remote.requests",
        "io.remote.bytes",
        "io.remote.faults",
        "io.remote.throttles",
        "io.remote.deadlines",
        "io.remote.hedges",
        "io.remote.hedge_wins",
        "io.remote.hedges_cancelled",
        "io.remote.breaker_trips",
        "io.remote.breaker_fast_fails",
        "salvage.pages_skipped",
        "salvage.chunks_quarantined",
        "salvage.rows_quarantined",
        "salvage.rows_dropped",
        "salvage.map_skips",
        "trace.decisions_dropped",
        "trace.events_dropped",
        # predicate page pruning on the scan face (scan/plan.py,
        # docs/scan.md): data pages skipped via row_ranges→OffsetIndex
        "scan.pages_pruned",
        # device pushdown compute (tpu/compute.py, docs/pushdown.md)
        "engine.pushdown_groups",
        "engine.pushdown_rows_in",
        "engine.pushdown_rows_selected",
        "engine.pushdown_overflows",
        "scan.rows_filtered_device",
        "serve.aggregate_probes",
        # the multi-tenant serving layer (serve/, docs/serving.md)
        "serve.cache_hits",
        "serve.cache_misses",
        "serve.cache_hit_bytes",
        "serve.cache_miss_bytes",
        "serve.cache_evictions",
        "serve.meta_evictions",
        "serve.singleflight_waits",
        "serve.fair_share_waits",
        "serve.lookup_probes",
        "serve.lookup_groups_pruned",
        "serve.lookup_bloom_skips",
        "serve.lookup_pages_read",
        "serve.lookup_rows",
        # process-scale serving (serve/shm_cache.py, serve/daemon.py,
        # docs/serving.md): the cross-process cache tier, the negative
        # cache, the streaming cursor, device-time WFQ, and the daemon
        "serve.shm_hits",
        "serve.shm_misses",
        "serve.shm_hit_bytes",
        "serve.shm_miss_bytes",
        "serve.shm_evictions",
        "serve.shm_meta_evictions",
        "serve.shm_singleflight_waits",
        "serve.shm_takeovers",
        "serve.negative_hits",
        "serve.cursor_pages",
        "serve.device_waits",
        "serve.daemon_requests",
        "serve.daemon_rejected",
        "serve.daemon_connections",
        # the cross-host fleet cache fabric (serve/fleet.py,
        # docs/serving.md): consistent-hash ownership, the peer leg's
        # failure domain, replication, fencing, and admission limiting
        "serve.fleet_served",
        "serve.fleet_origin_reads",
        "serve.fleet_peer_fetches",
        "serve.fleet_peer_hits",
        "serve.fleet_peer_hit_bytes",
        "serve.fleet_peer_errors",
        "serve.fleet_peer_fallbacks",
        "serve.fleet_epoch_fenced",
        "serve.fleet_replications",
        "serve.ratelimit_rejected",
        # second-chance rescues in the shm tier's rings (shm_cache.py)
        "serve.shm_rescues",
        # the training input pipeline (data.DataLoader, docs/data.md)
        "data.rows_emitted",
        "data.batches_emitted",
        "data.rows_padded",
        "data.rows_dropped",
        "data.epochs_completed",
        "data.units_scheduled",
        "data.units_quarantined",
        "data.prefetch_to_device_batches",
        # host-leg pushdown row compaction (scan/executor.py,
        # docs/pushdown.md): rows the predicate dropped on the host leg
        "scan.rows_filtered_host",
        # the device write path (write/, tpu/encode_kernels.py,
        # docs/write.md)
        "write.launches",
        "write.groups",
        "write.rows",
        "write.device_columns",
        "write.host_columns",
        "write.bytes_written",
        # the dataset compactor (write/compactor.py, docs/write.md)
        "compact.units_in",
        "compact.rows_in",
        "compact.rows_dropped",
        "compact.groups_out",
        # the multi-chip scan mesh (parallel/mesh.py, tpu/engine.py,
        # docs/multichip.md): groups placed on a mesh device
        "engine.mesh_groups",
        # host inflate moved into the stage task (decompressed output
        # bytes of the arena's codec jobs, docs/multichip.md)
        "scan.inflate_bytes",
        # ranged salvage reads: chunks whose pruned decode tripped a
        # salvageable error and widened to the whole-chunk ladder
        "salvage.ranged_widens",
        # fleet-wide distributed tracing (docs/observability.md
        # "Distributed tracing"): contexts deserialized off wire hops,
        # exemplars stored into histogram tail buckets, flight-recorder
        # ring evictions, incident bundles written, and peers a metrics
        # scrape could not reach (degraded, never failed)
        "trace.ctx_propagated",
        "trace.exemplars_recorded",
        "trace.flight_spans_dropped",
        "trace.flight_traces_dropped",
        "serve.flight_dumps",
        "serve.metrics_peer_unreachable",
        # the query subsystem (query/, docs/query.md): computed
        # expression rows on the scan face, sorted-merge join pages and
        # rows, serving-side expression probes, and the secondary-index
        # rung of the point-probe ladder
        "query.expr_rows",
        "query.join_pages",
        "query.join_rows",
        "serve.select_probes",
        "serve.select_rows",
        "serve.index_hits",
        "serve.index_skips",
        # sidecar keys emitted per index at compaction time
        "compact.index_keys",
    })
    GAUGES = frozenset({
        "scan.inflight_bytes_max",
        "scan.queue_depth_max",
        "scan.adaptive_budget_bytes",
        "engine.stage_queue_depth_max",
        "data.carry_rows_max",
        "data.prefetch_to_device_depth_max",
        "serve.inflight_storage_bytes_max",
        "serve.daemon_inflight_max",
        "write.inflight_groups_max",
        # mesh width the pipeline actually scheduled across
        "engine.mesh_devices",
        # largest ABSOLUTE per-peer clock offset (microseconds) the
        # fleet client has estimated via the midpoint method — a
        # high-water alarm on fleet clock skew (docs/observability.md)
        "trace.clock_offset_us",
    })
    DECISIONS = frozenset({
        "engine.auto",
        "engine.exec_cache",
        "chunk_fallback",
        "io.retry",
        "io.retry_exhausted",
        "io.retry_deadline_exceeded",
        "io.hedge",
        "io.breaker",
        "salvage.report",
        "salvage.skip_page",
        "salvage.quarantine_chunk",
        "salvage.row_mask",
        "salvage.dict_recovery",
        "salvage.map_skip",
        "salvage.device_host_decode",
        "scan.plan",
        "scan.adaptive_budget",
        "scan.adaptive_depth",
        "data.epoch_plan",
        "data.resume",
        "data.unit_quarantined",
        "serve.tenant",
        "serve.admission",
        "engine.pushdown",
        "write.engine",
        "compact.plan",
        "compact.unit_dropped",
        # the per-tenant SLO monitor (serve/slo.py, docs/serving.md)
        "serve.slo_breach",
        # the serving daemon's lifecycle (serve/daemon.py):
        # start / drain / overload events
        "serve.daemon",
        # the fleet cache fabric (serve/fleet.py): membership installs,
        # breaker-guarded peer failover, origin fallbacks
        "serve.fleet",
        # remote-chain coalescing-gap auto-tune (scan/executor.py)
        "scan.max_gap_autotuned",
        # the multi-chip scan mesh: one event per pipeline that went
        # multi-device (device count + platform)
        "engine.mesh",
        # flight-recorder incident dumps: one event per bundle written
        # (trigger reason + bundle path)
        "serve.flight",
        # secondary-index lifecycle on the serving face (query/index.py,
        # serve/lookup.py): install events with key/file counts
        "serve.index",
    })
    SPANS = frozenset({
        "read",
        "stage",
        "ship",
        "decode",
        "decode_chunk",
        "assemble",
        "io.read",
        "io.remote.get",
        "scan.consumer_stall",
        "data.next_batch",
        "data.prefetch_to_device",
        "serve.lookup",
        "serve.aggregate",
        "write.encode",
        "write.emit",
        # host codec decompression inside the stage task (the overlap
        # the multichip bench leg measures, docs/multichip.md)
        "inflate",
        # the distributed-tracing wire hops (docs/observability.md):
        # client send→reply, daemon dispatch→reply, the fleet peer leg
        # (asker and server side), and the origin fallback
        "serve.client_request",
        "serve.daemon_request",
        "serve.fleet_peer_fetch",
        "serve.fleet_serve",
        "serve.fleet_origin_read",
        # the query subsystem (query/join.py, serve/lookup.py)
        "query.join",
        "serve.select",
    })
    # latency/size distributions (Tracer.observe -> LogHistogram;
    # docs/observability.md).  Values are SECONDS unless the name says
    # otherwise; the ``.kind`` suffixes split one metric by a static
    # outcome (source kind, hedge outcome) without dynamic names.
    HISTOGRAMS = frozenset({
        # the serving face, per-tenant through the scoped tracers
        "serve.lookup_seconds",          # one lookup()/range() probe wall
        "serve.aggregate_seconds",       # one aggregate() query wall
        "serve.fair_wait_seconds",       # WFQ gate grant wait (contended)
        "serve.singleflight_wait_seconds",  # wait on another's in-flight read
        "serve.device_seconds",          # one metered decode-engine slice
        "serve.device_wait_seconds",     # device WFQ lane wait (contended)
        "serve.shm_wait_seconds",        # wait on another WORKER's read
        "serve.daemon_request_seconds",  # one daemon request, arrival→reply
        "serve.fleet_peer_wait_seconds",  # one peer range fetch, send→bytes
        # storage read latency, split by source kind and hedge outcome
        "io.read_seconds.file",          # FileSource vectored read wall
        "io.remote.get_seconds.primary",    # remote fetch, primary won
        "io.remote.get_seconds.hedge",      # remote fetch, hedge won
        # the decode pipeline's stage walls
        "scan.unit_decode_seconds",      # one scan unit's host decode wall
        "engine.stage_seconds",          # one group's host staging wall
        "engine.ship_seconds",           # one H2D transfer wall
        "engine.launch_seconds",         # one fused decode dispatch wall
        "scan.inflate_seconds",          # one group's host inflate wall
        # the training loader and the write path
        "data.next_batch_seconds",       # one loader next() wall
        "write.emit_seconds",            # one group's ordered sink emission
        # the query subsystem (docs/query.md)
        "query.join_seconds",            # one join next_page() wall
        "serve.select_seconds",          # one select() expression scan wall
    })
    ALL = COUNTERS | GAUGES | DECISIONS | SPANS | HISTOGRAMS


@dataclass
class StageStat:
    """Per-stage accumulator.  ``seconds`` is INCLUSIVE wall (what it
    always was); ``self_seconds`` is the stage's EXCLUSIVE time — the
    same spans minus any nested span recorded on the same thread of the
    same tracer.  Nested stages (the host reader's per-chunk
    ``decode_chunk`` spans under the scan executor's group ``decode``
    span) therefore never double-count in a sum over ``self_seconds``,
    while each stage's inclusive total stays directly comparable to the
    pre-nesting numbers."""

    count: int = 0
    seconds: float = 0.0
    bytes: int = 0
    self_seconds: float = 0.0

    def as_dict(self) -> dict:
        mbps = (self.bytes / self.seconds / 1e6) if self.seconds else 0.0
        return {
            "count": self.count,
            "seconds": round(self.seconds, 6),
            "bytes": self.bytes,
            "MB_per_s": round(mbps, 1),
            "self_seconds": round(self.self_seconds, 6),
        }


class _NullSpan:
    """The disabled-path span: one immortal, attribute-free instance —
    entering/exiting it allocates nothing and takes no lock."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def add_bytes(self, n: int) -> None:
        pass


_NULL_SPAN = _NullSpan()


# ---------------------------------------------------------------------------
# Distributed tracing: request contexts + the flight recorder
# (docs/observability.md "Distributed tracing")
# ---------------------------------------------------------------------------

#: perf_counter ↔ wall-clock bridge, captured ONCE per process at
#: import: ``_UNIX_EPOCH + (t - _PERF_EPOCH)`` maps any perf_counter
#: reading onto a unix timeline that is monotonic within the process
#: (``time.time()`` alone can step under NTP).  Cross-process alignment
#: is NOT assumed — that is what the measured peer clock offsets and
#: :func:`merge_fleet_trace` are for.
_PERF_EPOCH = time.perf_counter()
_UNIX_EPOCH = time.time()


def perf_to_unix(t: float) -> float:
    """Map a ``time.perf_counter`` reading onto this process's unix
    timeline (see ``_PERF_EPOCH`` — monotonic within the process)."""
    return _UNIX_EPOCH + (t - _PERF_EPOCH)


def _new_id() -> str:
    return os.urandom(8).hex()


class TraceContext:
    """One request's identity at one point in its causal chain:
    ``trace_id`` names the whole fleet-wide request, ``span_id`` this
    hop, ``parent_id`` the hop that caused it (None at the root), and
    ``tenant`` rides along for attribution.  Serialized into every wire
    hop (``to_wire``/``from_wire`` — short keys; the daemon line
    protocol carries it under the ``"trace"`` field)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "tenant")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None,
                 tenant: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.tenant = tenant

    @classmethod
    def root(cls, tenant: Optional[str] = None) -> "TraceContext":
        return cls(_new_id(), _new_id(), None, tenant)

    def child(self) -> "TraceContext":
        """A context one causal step below this one (fresh span_id,
        parent = this hop) — what entering a span or serializing an
        outgoing wire request does."""
        return TraceContext(self.trace_id, _new_id(), self.span_id,
                            self.tenant)

    def to_wire(self) -> dict:
        d = {"t": self.trace_id, "s": self.span_id}
        if self.parent_id is not None:
            d["p"] = self.parent_id
        if self.tenant is not None:
            d["u"] = self.tenant
        return d

    @classmethod
    def from_wire(cls, d) -> Optional["TraceContext"]:
        """Rebuild a context from its wire form; None for anything that
        is not one (an old client, a missing field) — receivers need no
        version branching.  Every successful deserialization counts
        ``trace.ctx_propagated`` on the ambient tracer, so cross-hop
        propagation is itself observable."""
        if not isinstance(d, dict):
            return None
        t, s = d.get("t"), d.get("s")
        if not isinstance(t, str) or not isinstance(s, str):
            return None
        count("trace.ctx_propagated")
        return cls(t, s, d.get("p"), d.get("u"))

    def __repr__(self) -> str:
        return (f"TraceContext({self.trace_id}/{self.span_id}"
                f" parent={self.parent_id} tenant={self.tenant})")


_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "pftpu_trace_ctx", default=None
)


class FlightRecorder:
    """Always-on bounded ring of recently COMPLETED request traces.
    Every span closed under an active :class:`TraceContext` lands here
    as a record grouped by trace_id; when the last open span of a trace
    exits locally, the fragment seals into the completed ring (each
    daemon seals its OWN fragment of a cross-host trace — the fleet
    merge joins fragments by trace_id).  Bounded both ways, and the
    evictions are counted (``dropped_traces``/``dropped_spans``,
    surfaced by :meth:`stats` and mirrored onto tracer counters by the
    daemon's snapshot export) — never silent.  ``host`` labels every
    record so the merge keeps per-node identity even for an in-process
    fleet."""

    def __init__(self, host: Optional[str] = None, max_traces: int = 64,
                 max_spans_per_trace: int = 256):
        self.host = host or f"pid{os.getpid()}"
        self.max_traces = int(max_traces)
        self.max_spans = int(max_spans_per_trace)
        self._lock = threading.Lock()
        self._depth: Dict[str, int] = {}
        self._open: Dict[str, list] = {}
        self._sealed: deque = deque()  # (trace_id, [records], sealed_ts)
        self.dropped_traces = 0
        self.dropped_spans = 0

    def begin(self, trace_id: str) -> None:
        with self._lock:
            self._depth[trace_id] = self._depth.get(trace_id, 0) + 1

    def end(self, record: dict) -> None:
        tid = record.get("trace_id")
        if tid is None:
            return
        record.setdefault("node", self.host)
        with self._lock:
            buf = self._open.setdefault(tid, [])
            if len(buf) >= self.max_spans:
                self.dropped_spans += 1
            else:
                buf.append(record)
            d = self._depth.get(tid, 1) - 1
            if d <= 0:
                self._depth.pop(tid, None)
                spans = self._open.pop(tid, [])
                if spans:
                    self._seal_locked(tid, spans)
            else:
                self._depth[tid] = d

    def _seal_locked(self, trace_id: str, spans: list) -> None:
        self._sealed.append(
            (trace_id, spans, perf_to_unix(time.perf_counter()))
        )
        while len(self._sealed) > self.max_traces:
            self._sealed.popleft()
            self.dropped_traces += 1

    def traces(self, last_s: Optional[float] = None,
               now: Optional[float] = None) -> List[dict]:
        """The sealed ring, oldest first: ``{"trace_id", "sealed_ts",
        "spans": [...]}`` dicts.  ``last_s`` keeps only fragments sealed
        within the trailing window — the incident bundle's "last N
        seconds of traces"."""
        with self._lock:
            items = list(self._sealed)
        if last_s is not None:
            cut = (now if now is not None
                   else perf_to_unix(time.perf_counter())) - last_s
            items = [it for it in items if it[2] >= cut]
        return [{"trace_id": t, "sealed_ts": ts, "spans": list(sp)}
                for t, sp, ts in items]

    def stats(self) -> dict:
        with self._lock:
            return {
                "host": self.host,
                "sealed": len(self._sealed),
                "open": len(self._open),
                "dropped_traces": self.dropped_traces,
                "dropped_spans": self.dropped_spans,
            }

    def clear(self) -> None:
        with self._lock:
            self._depth.clear()
            self._open.clear()
            self._sealed.clear()


_flight = FlightRecorder()
_recorder: contextvars.ContextVar = contextvars.ContextVar(
    "pftpu_flight_recorder", default=None
)


def flight_recorder() -> FlightRecorder:
    """The recorder span records land in: the innermost
    :func:`use_flight_recorder` scope, else the process-global ring
    (daemons install their own, so an in-process fleet keeps per-node
    fragments apart)."""
    r = _recorder.get()
    return _flight if r is None else r


@contextlib.contextmanager
def use_flight_recorder(rec: FlightRecorder) -> Iterator[FlightRecorder]:
    """Route span records to ``rec`` for the dynamic extent of the
    block (the :func:`using` shape, for the flight ring)."""
    token = _recorder.set(rec)
    try:
        yield rec
    finally:
        _recorder.reset(token)


class _NullTraceHandle:
    """Disabled-path ``start_trace`` result: one immortal no-op context
    manager (the ``_NULL_SPAN`` discipline — no allocation, no lock)."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_TRACE = _NullTraceHandle()


class _TraceHandle:
    """Live ``start_trace`` scope: installs a fresh root context, and
    on exit records the root span into the flight recorder (the local
    fragment seals once every nested span has closed)."""

    __slots__ = ("_name", "_attrs", "ctx", "_token", "_rec", "_t0")

    def __init__(self, name: str, tenant: Optional[str],
                 attrs: Optional[dict]):
        self._name = name
        self._attrs = attrs
        self.ctx = TraceContext.root(tenant)

    def __enter__(self) -> TraceContext:
        self._token = _ctx.set(self.ctx)
        self._rec = flight_recorder()
        self._rec.begin(self.ctx.trace_id)
        self._t0 = time.perf_counter()
        return self.ctx

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        rec = {
            "trace_id": self.ctx.trace_id,
            "span_id": self.ctx.span_id,
            "parent_id": None,
            "name": self._name,
            "ts": perf_to_unix(self._t0),
            "dur": t1 - self._t0,
            "tenant": self.ctx.tenant,
            "tid": threading.get_ident(),
        }
        if self._attrs:
            rec["attrs"] = dict(self._attrs)
        self._rec.end(rec)
        _ctx.reset(self._token)
        return False


class _Span:
    """One live timed span: records a begin event on ``__enter__`` and a
    matching end event + stage accumulation on ``__exit__`` (same thread
    by construction — it is a ``with`` block).  With ``observe`` set,
    the exit also records the span's wall into that histogram — ONE
    clock read serves both, so stage seconds and histogram samples are
    definitionally identical."""

    __slots__ = ("_tracer", "_stage", "_nbytes", "_attrs", "_t0",
                 "_observe", "_ctx", "_token", "_rec")

    def __init__(self, tracer: "Tracer", stage: str, nbytes: int,
                 attrs: Optional[dict], observe: Optional[str] = None):
        self._tracer = tracer
        self._stage = stage
        self._nbytes = nbytes
        self._attrs = attrs
        self._observe = observe

    def add_bytes(self, n: int) -> None:
        """Attribute ``n`` more bytes to this span (for byte counts only
        known after the work — e.g. how much a prefetch load fetched)."""
        self._nbytes += int(n)

    def __enter__(self):
        # per-thread nesting stack (child-time accumulators): what turns
        # inclusive span walls into the exclusive ``self_seconds`` stats
        stack = getattr(self._tracer._tls, "stack", None)
        if stack is None:
            stack = self._tracer._tls.stack = []
        stack.append(0.0)
        # distributed-tracing hook: under an active TraceContext the
        # span becomes a child hop (fresh span_id, parent link) and its
        # close will land in the flight recorder — outside any trace
        # this is one ContextVar read (enabled path only; the disabled
        # path returned _NULL_SPAN long before here)
        ctx = _ctx.get()
        if ctx is not None:
            self._ctx = ctx.child()
            self._token = _ctx.set(self._ctx)
            self._rec = flight_recorder()
            self._rec.begin(ctx.trace_id)
        else:
            self._ctx = None
            self._token = None
            self._rec = None
        self._t0 = time.perf_counter()
        self._tracer._event("B", self._stage, self._t0, self._attrs)
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        dur = t1 - self._t0
        stack = self._tracer._tls.stack
        child = stack.pop()
        if stack:
            stack[-1] += dur
        self._tracer.add(
            self._stage, dur, self._nbytes, self_seconds=dur - child
        )
        if self._observe is not None:
            self._tracer.observe(self._observe, dur)
            charge = self._tracer.device_charge
            if charge is not None and self._observe in (
                "engine.ship_seconds", "engine.launch_seconds",
            ):
                # device-time spans bill the owning tenant's WFQ ledger
                # (serve/tenancy.py wires the hook; no-op otherwise)
                charge(dur)
        if self._token is not None:
            rec = {
                "trace_id": self._ctx.trace_id,
                "span_id": self._ctx.span_id,
                "parent_id": self._ctx.parent_id,
                "name": self._stage,
                "ts": perf_to_unix(self._t0),
                "dur": dur,
                "tenant": self._ctx.tenant,
                "tid": threading.get_ident(),
            }
            if self._attrs:
                rec["attrs"] = dict(self._attrs)
            if self._nbytes:
                rec["bytes"] = self._nbytes
            self._rec.end(rec)
            _ctx.reset(self._token)
        self._tracer._event("E", self._stage, t1, None)
        return False


@dataclass
class ScanReport:
    """Consumable health summary of one scan (or any traced region),
    distilled from a tracer snapshot: per-stage throughput, overlap /
    stall fraction, budget utilization, over-read ratio, retries, and
    quarantines.  ``DatasetScanner.report()`` / ``scan_device_groups``'s
    ``on_report`` build one per scan; ``bench.py`` writes it into the
    bench JSON; ``render()`` (and ``trace.report()``) print it."""

    wall_seconds: Optional[float]
    stages: Dict[str, dict]
    consumer_stall_seconds: float
    stall_fraction: Optional[float]      # stall / wall (needs wall)
    overlap_fraction: Optional[float]    # 1 - stall_fraction
    budget_bytes: Optional[int]
    budget_utilization: Optional[float]  # inflight high-water / budget
    bytes_read: int
    bytes_used: int
    overread_ratio: float                # (read - used) / read
    bytes_prefetched: int
    cache_miss_bytes: int
    retries: int
    retry_exhausted: int
    pages_quarantined: int
    chunks_quarantined: int
    decisions_dropped: int
    events_dropped: int
    counters: Dict[str, int] = field(default_factory=dict)
    gauges: Dict[str, int] = field(default_factory=dict)
    #: latency/size distributions in ``LogHistogram.as_dict`` form —
    #: serializable like everything else here, merged bucket-wise
    histograms: Dict[str, dict] = field(default_factory=dict)

    def as_dict(self) -> dict:
        out = {
            "wall_seconds": (
                round(self.wall_seconds, 6)
                if self.wall_seconds is not None else None
            ),
            "stages": self.stages,
            "consumer_stall_seconds": round(self.consumer_stall_seconds, 6),
            "stall_fraction": self.stall_fraction,
            "overlap_fraction": self.overlap_fraction,
            "budget_bytes": self.budget_bytes,
            "budget_utilization": self.budget_utilization,
            "bytes_read": self.bytes_read,
            "bytes_used": self.bytes_used,
            "overread_ratio": self.overread_ratio,
            "bytes_prefetched": self.bytes_prefetched,
            "cache_miss_bytes": self.cache_miss_bytes,
            "retries": self.retries,
            "retry_exhausted": self.retry_exhausted,
            "pages_quarantined": self.pages_quarantined,
            "chunks_quarantined": self.chunks_quarantined,
            "decisions_dropped": self.decisions_dropped,
            "events_dropped": self.events_dropped,
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
            "histograms": {k: dict(v) for k, v in self.histograms.items()},
        }
        return out

    def histogram(self, name: str) -> Optional[LogHistogram]:
        """The named distribution as a live :class:`LogHistogram`, or
        None — the convenient face over the serialized field
        (``report.histogram("serve.lookup_seconds").percentile(99)``)."""
        d = self.histograms.get(name)
        return None if d is None else LogHistogram.from_dict(d)

    def render(self) -> str:
        lines = ["scan health:"]

        def pct(v):
            return "n/a" if v is None else f"{v * 100.0:.1f}%"

        if self.wall_seconds is not None:
            lines.append(f"  wall              {self.wall_seconds * 1e3:.1f} ms")
        lines.append(
            f"  consumer stall    {self.consumer_stall_seconds * 1e3:.1f} ms"
            f"  (stall {pct(self.stall_fraction)},"
            f" overlap {pct(self.overlap_fraction)})"
        )
        if self.budget_bytes:
            lines.append(
                f"  budget            {self.budget_bytes} B,"
                f" utilization {pct(self.budget_utilization)}"
            )
        lines.append(
            f"  bytes read/used   {self.bytes_read}/{self.bytes_used}"
            f"  (over-read {pct(self.overread_ratio)})"
        )
        if self.cache_miss_bytes:
            lines.append(f"  cache misses      {self.cache_miss_bytes} B")
        lines.append(
            f"  retries           {self.retries}"
            f" (exhausted {self.retry_exhausted})"
        )
        if self.pages_quarantined or self.chunks_quarantined:
            lines.append(
                f"  quarantined       {self.pages_quarantined} page(s),"
                f" {self.chunks_quarantined} chunk(s)"
            )
        if self.decisions_dropped or self.events_dropped:
            lines.append(
                f"  trace evictions   {self.decisions_dropped} decision(s),"
                f" {self.events_dropped} event(s) dropped"
            )
        for name, st in sorted(self.stages.items()):
            lines.append(
                f"  {name:<16} n={st['count']:<6}"
                f" {st['seconds'] * 1e3:9.1f} ms"
                + (f"  {st['MB_per_s']:8.1f} MB/s" if st["bytes"] else "")
            )
        return "\n".join(lines)

    @classmethod
    def from_dict(cls, d: dict) -> "ScanReport":
        """Rebuild a report from its :meth:`as_dict` form — the
        serialization half of the cross-process contract: per-host
        loaders/scans ship ``as_dict()`` JSON over whatever transport the
        deployment has (a collective, files, an RPC), and the coordinator
        rebuilds and :meth:`merge`\\ s them."""
        import dataclasses

        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"not a ScanReport dict: unknown keys {sorted(unknown)}"
            )
        kwargs = {name: d.get(name) for name in known}
        # as_dict() emits every field; tolerate older/partial dicts by
        # zero-filling the additive fields and None-filling the optional ones
        for name in ("bytes_read", "bytes_used", "bytes_prefetched",
                     "cache_miss_bytes", "retries", "retry_exhausted",
                     "pages_quarantined", "chunks_quarantined",
                     "decisions_dropped", "events_dropped"):
            kwargs[name] = int(kwargs[name] or 0)
        kwargs["consumer_stall_seconds"] = float(
            kwargs["consumer_stall_seconds"] or 0.0
        )
        kwargs["overread_ratio"] = float(kwargs["overread_ratio"] or 0.0)
        kwargs["stages"] = dict(kwargs["stages"] or {})
        kwargs["counters"] = dict(kwargs["counters"] or {})
        kwargs["gauges"] = dict(kwargs["gauges"] or {})
        kwargs["histograms"] = dict(kwargs["histograms"] or {})
        return cls(**kwargs)

    @classmethod
    def merge(cls, reports: Sequence["ScanReport"]) -> "ScanReport":
        """Fold per-host (or per-epoch) reports into one dataset-level
        summary — the serializable merge the sharded loader needs
        (``trace.scope()`` is contextvar-based and never crosses process
        boundaries, so each host reports into its own tracer; this is
        where those snapshots meet).

        Aggregation rules: additive fields (bytes, retries, quarantines,
        stall seconds, stage count/seconds/bytes, counters) SUM; gauges
        (high-water marks) take the MAX; ``wall_seconds`` takes the max
        (hosts run concurrently) while the stall/overlap fractions are
        recomputed from summed stall over summed wall (aggregate
        utilization, not an average of ratios); ``budget_bytes`` sums
        and utilization is recomputed from the summed in-flight
        high-water."""
        reports = list(reports)
        if not reports:
            raise ValueError("ScanReport.merge needs at least one report")
        stages: Dict[str, dict] = {}
        for r in reports:
            for name, st in r.stages.items():
                acc = stages.setdefault(
                    name,
                    {"count": 0, "seconds": 0.0, "bytes": 0,
                     "self_seconds": 0.0},
                )
                acc["count"] += int(st.get("count", 0))
                acc["seconds"] += float(st.get("seconds", 0.0))
                acc["bytes"] += int(st.get("bytes", 0))
                acc["self_seconds"] += float(
                    st.get("self_seconds", st.get("seconds", 0.0))
                )
        for st in stages.values():
            st["seconds"] = round(st["seconds"], 6)
            st["self_seconds"] = round(st["self_seconds"], 6)
            st["MB_per_s"] = round(
                (st["bytes"] / st["seconds"] / 1e6) if st["seconds"] else 0.0,
                1,
            )
        counters: Dict[str, int] = {}
        gauges: Dict[str, int] = {}
        hists: Dict[str, LogHistogram] = {}
        for r in reports:
            for k, v in r.counters.items():
                counters[k] = counters.get(k, 0) + int(v)
            for k, v in r.gauges.items():
                gauges[k] = max(gauges.get(k, -(1 << 62)), int(v))
            LogHistogram.fold_dicts(hists, r.histograms)
        walls = [r.wall_seconds for r in reports if r.wall_seconds is not None]
        wall = max(walls) if walls else None
        wall_sum = sum(walls)
        stall = sum(r.consumer_stall_seconds for r in reports)
        stall_frac = overlap = None
        if wall_sum > 0:
            stall_frac = round(min(stall / wall_sum, 1.0), 4)
            overlap = round(1.0 - stall_frac, 4)
        budgets = [r.budget_bytes for r in reports if r.budget_bytes]
        budget = sum(budgets) if budgets else None
        hwms = [
            r.gauges.get("scan.inflight_bytes_max", 0)
            for r in reports
            if r.budget_bytes
        ]
        util = round(sum(hwms) / budget, 4) if budget else None
        read = sum(r.bytes_read for r in reports)
        used = sum(r.bytes_used for r in reports)
        return cls(
            wall_seconds=wall,
            stages=stages,
            consumer_stall_seconds=round(stall, 6),
            stall_fraction=stall_frac,
            overlap_fraction=overlap,
            budget_bytes=budget,
            budget_utilization=util,
            bytes_read=read,
            bytes_used=used,
            overread_ratio=round((read - used) / read, 4) if read else 0.0,
            bytes_prefetched=sum(r.bytes_prefetched for r in reports),
            cache_miss_bytes=sum(r.cache_miss_bytes for r in reports),
            retries=sum(r.retries for r in reports),
            retry_exhausted=sum(r.retry_exhausted for r in reports),
            pages_quarantined=sum(r.pages_quarantined for r in reports),
            chunks_quarantined=sum(r.chunks_quarantined for r in reports),
            decisions_dropped=sum(r.decisions_dropped for r in reports),
            events_dropped=sum(r.events_dropped for r in reports),
            counters=counters,
            gauges=gauges,
            histograms={k: h.as_dict() for k, h in hists.items()},
        )


def scan_report_from(stats: Dict[str, dict], counters: Dict[str, int],
                     gauges: Dict[str, int],
                     wall_seconds: Optional[float] = None,
                     budget_bytes: Optional[int] = None,
                     histograms: Optional[Dict[str, dict]] = None
                     ) -> ScanReport:
    """Build a :class:`ScanReport` from explicit snapshots — the shared
    derivation behind :meth:`Tracer.scan_report`, also usable on DELTA
    snapshots (the loader's per-epoch reports subtract an epoch-start
    snapshot from an epoch-end one before calling this)."""
    stall = stats.get("scan.consumer_stall", {}).get("seconds", 0.0)
    stall_frac = overlap = None
    if wall_seconds is not None and wall_seconds > 0:
        stall_frac = round(min(stall / wall_seconds, 1.0), 4)
        overlap = round(1.0 - stall_frac, 4)
    util = None
    if budget_bytes:
        util = round(
            gauges.get("scan.inflight_bytes_max", 0) / budget_bytes, 4
        )
    read = counters.get("scan.bytes_read", 0)
    used = counters.get("scan.bytes_used", 0)
    return ScanReport(
        wall_seconds=wall_seconds,
        stages=stats,
        consumer_stall_seconds=stall,
        stall_fraction=stall_frac,
        overlap_fraction=overlap,
        budget_bytes=budget_bytes,
        budget_utilization=util,
        bytes_read=read,
        bytes_used=used,
        overread_ratio=round((read - used) / read, 4) if read else 0.0,
        bytes_prefetched=counters.get("scan.bytes_prefetched", 0),
        cache_miss_bytes=counters.get("scan.cache_miss_bytes", 0),
        retries=counters.get("io.retries", 0),
        retry_exhausted=counters.get("io.retry_exhausted", 0),
        pages_quarantined=counters.get("salvage.pages_skipped", 0),
        chunks_quarantined=counters.get("salvage.chunks_quarantined", 0),
        decisions_dropped=counters.get("trace.decisions_dropped", 0),
        events_dropped=counters.get("trace.events_dropped", 0),
        counters=counters,
        gauges=gauges,
        histograms=dict(histograms or {}),
    )


class GaugeWindow:
    """A per-interval view of a tracer's high-water gauges (see
    :meth:`Tracer.gauge_window`): records only the ``gauge_max`` writes
    made while open, under the tracer's own lock, so worker threads
    carried by :meth:`Tracer.run` land in the window too."""

    def __init__(self, tracer: "Tracer"):
        self._tracer = tracer
        self._gauges: Dict[str, int] = {}

    def gauges(self) -> Dict[str, int]:
        """Snapshot of the maxima recorded while this window was open."""
        with self._tracer._lock:
            return dict(self._gauges)

    def close(self) -> Dict[str, int]:
        """Detach from the tracer and return the window's maxima;
        idempotent."""
        with self._tracer._lock:
            if self in self._tracer._windows:
                self._tracer._windows.remove(self)
            return dict(self._gauges)


class HistogramWindow:
    """A per-interval view of a tracer's histograms (see
    :meth:`Tracer.histogram_window`), the :class:`GaugeWindow` shape
    applied to distributions: records only the ``observe()`` writes made
    while open, under the tracer's own lock, so worker threads carried
    by :meth:`Tracer.run` land in the window too.  Per-epoch/per-scan
    latency deltas fall out without subtracting cumulative snapshots."""

    def __init__(self, tracer: "Tracer"):
        self._tracer = tracer
        self._hists: Dict[str, LogHistogram] = {}

    def histograms(self) -> Dict[str, LogHistogram]:
        """Snapshot (copies) of the distributions recorded while this
        window was open."""
        with self._tracer._lock:
            return {k: h.copy() for k, h in self._hists.items()}

    def close(self) -> Dict[str, LogHistogram]:
        """Detach from the tracer and return the window's histograms;
        idempotent."""
        with self._tracer._lock:
            if self in self._tracer._hwindows:
                self._tracer._hwindows.remove(self)
            return {k: h.copy() for k, h in self._hists.items()}


class Tracer:
    """One isolated metrics/timeline store.  Thread-safe; every method is
    a no-op while disabled.  ``max_decisions``/``max_events`` bound the
    two append-only stores — evictions are COUNTED
    (``trace.decisions_dropped`` / ``trace.events_dropped``), never
    silent."""

    def __init__(self, enabled: bool = False, max_decisions: int = 64,
                 max_events: int = 1 << 16):
        if max_decisions < 1:
            raise ValueError(f"max_decisions must be >= 1, got {max_decisions}")
        if max_events < 2:
            raise ValueError(f"max_events must be >= 2, got {max_events}")
        self._enabled = bool(enabled)
        self.max_decisions = int(max_decisions)
        self.max_events = int(max_events)
        self._lock = threading.Lock()
        self._tls = threading.local()   # per-thread span nesting stack
        self._stats: Dict[str, StageStat] = {}
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, int] = {}
        self._hists: Dict[str, LogHistogram] = {}
        self._windows: List["GaugeWindow"] = []
        self._hwindows: List["HistogramWindow"] = []
        self._decisions: deque = deque()
        self._events: deque = deque()   # (ph, name, ts, tid, attrs)
        self._thread_names: Dict[int, str] = {}
        self._epoch = time.perf_counter()
        # fairness-ledger hook (serve/tenancy.py): when a Tenant owns
        # this tracer it sets device_charge = tenant.charge_device, and
        # every ship/launch span recorded under the scope bills its
        # wall to the WFQ ledger automatically — the engine needs no
        # tenancy import, and a mesh's per-device workers charge from
        # whatever thread they run on (docs/serving.md)
        self.device_charge = None

    # -- switches -----------------------------------------------------------

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    def enabled(self) -> bool:
        return self._enabled

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            for w in self._windows:
                w._gauges.clear()
            for hw in self._hwindows:
                hw._hists.clear()
            self._decisions.clear()
            self._events.clear()
            self._thread_names.clear()
            self._epoch = time.perf_counter()

    # -- scope plumbing -----------------------------------------------------

    def run(self, fn, *args, **kwargs):
        """Call ``fn(*args, **kwargs)`` with THIS tracer active — how the
        scan executor / engine pools carry the submitting scope onto
        their worker threads (contextvars do not cross thread spawns on
        their own)."""
        token = _active.set(self)
        try:
            return fn(*args, **kwargs)
        finally:
            _active.reset(token)

    # -- counters / gauges --------------------------------------------------

    def count(self, name: str, n: int = 1) -> None:
        """Add ``n`` to the additive counter ``name`` (no-op when
        disabled)."""
        if not self._enabled:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + int(n)

    def gauge_max(self, name: str, value: int) -> None:
        """Raise the high-water gauge ``name`` to at least ``value``
        (no-op when disabled).  Gauges record peaks — e.g. the deepest a
        prefetch queue ever got — where an additive counter would be
        meaningless."""
        if not self._enabled:
            return
        v = int(value)
        with self._lock:
            if v > self._gauges.get(name, -(1 << 62)):
                self._gauges[name] = v
            for w in self._windows:
                if v > w._gauges.get(name, -(1 << 62)):
                    w._gauges[name] = v

    def counters(self) -> Dict[str, int]:
        """Snapshot of the ADDITIVE counters only (gauges live in
        :meth:`gauges`; :meth:`metrics` is the merged compat view)."""
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> Dict[str, int]:
        """Snapshot of the high-water gauges only."""
        with self._lock:
            return dict(self._gauges)

    def gauge_window(self) -> "GaugeWindow":
        """Open a windowed view of the high-water gauges: the returned
        :class:`GaugeWindow` records only ``gauge_max`` writes made while
        it is open.  A cumulative max cannot be delta'd the way counters
        can (an epoch whose peak is below the run's peak never moves the
        cumulative gauge), so per-interval reporters — the
        ``DataLoader``'s per-epoch reports — observe the writes directly
        instead.  Close it with :meth:`GaugeWindow.close`."""
        w = GaugeWindow(self)
        with self._lock:
            self._windows.append(w)
        return w

    # -- histograms ---------------------------------------------------------

    def observe(self, name: str, value: float) -> None:
        """Record one sample into the log-bucketed distribution
        ``name`` (seconds for the latency histograms in
        :class:`names`.HISTOGRAMS).  No-op when disabled — the hot path
        allocates nothing and takes no lock, same discipline as
        :meth:`count`."""
        if not self._enabled:
            return
        v = float(value)
        # exemplar: under an active TraceContext the sample also offers
        # its trace_id to the bucket's reservoir slot, linking a tail
        # bucket straight to a replayable trace (docs/observability.md).
        # One ContextVar read on the enabled path; the disabled path
        # returned above, allocation-free as ever.
        ctx = _ctx.get()
        ex = None if ctx is None else ctx.trace_id
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = LogHistogram()
            if h.record(v, exemplar=ex):
                self._counters["trace.exemplars_recorded"] = (
                    self._counters.get("trace.exemplars_recorded", 0) + 1
                )
            for w in self._hwindows:
                wh = w._hists.get(name)
                if wh is None:
                    wh = w._hists[name] = LogHistogram()
                wh.record(v)

    def histograms(self) -> Dict[str, LogHistogram]:
        """Snapshot (copies) of every recorded distribution."""
        with self._lock:
            return {k: h.copy() for k, h in self._hists.items()}

    def histograms_dict(self) -> Dict[str, dict]:
        """The histograms in their serializable ``as_dict`` form — what
        :class:`ScanReport` carries and the exporters merge."""
        with self._lock:
            return {k: h.as_dict() for k, h in self._hists.items()}

    def histogram_window(self) -> "HistogramWindow":
        """Open a windowed view of the distributions: the returned
        :class:`HistogramWindow` records only ``observe`` writes made
        while it is open (the :meth:`gauge_window` shape — cumulative
        distributions delta awkwardly; per-interval reporters observe
        the writes directly).  Close with
        :meth:`HistogramWindow.close`."""
        w = HistogramWindow(self)
        with self._lock:
            self._hwindows.append(w)
        return w

    def metrics(self) -> Dict[str, int]:
        """Merged counters+gauges snapshot — the pre-scope ``counters()``
        shape, kept for consumers that want one flat mapping.  Names are
        disjoint by construction (:class:`names` keeps the two sets
        apart; FL-OBS001 enforces it)."""
        with self._lock:
            out = dict(self._counters)
            out.update(self._gauges)
            return out

    # -- decisions ----------------------------------------------------------

    def decision(self, name: str, detail: dict) -> None:
        """Record a policy decision (e.g. engine="auto" routing) so
        consumers can see WHY a path was taken.  No-op when disabled.
        Bounded at ``max_decisions``: evicting the oldest entry bumps
        ``trace.decisions_dropped`` (the "no silent caps" rule) — totals
        that must survive eviction belong in counters (e.g.
        ``io.retries``)."""
        if not self._enabled:
            return
        ts = time.perf_counter()
        with self._lock:
            if len(self._decisions) >= self.max_decisions:
                self._decisions.popleft()
                self._counters["trace.decisions_dropped"] = (
                    self._counters.get("trace.decisions_dropped", 0) + 1
                )
            self._decisions.append({"decision": name, **detail})
            self._event_locked("i", name, ts, detail)

    def decisions(self) -> list:
        """Snapshot of recorded policy decisions (most recent last)."""
        with self._lock:
            return list(self._decisions)

    # -- spans / stats ------------------------------------------------------

    def add(self, stage: str, seconds: float, nbytes: int = 0,
            self_seconds: Optional[float] = None) -> None:
        """Accumulate one span's worth of wall/bytes.

        A BARE ``add`` (``self_seconds`` omitted) records time the
        caller just spent on this thread — all of it exclusive
        (``self_seconds = seconds``), and charged to the enclosing open
        span's child accumulator so the parent's exclusive time
        excludes it (the scan executor's ``scan.consumer_stall`` under
        the loader's ``data.next_batch`` span is the motivating case —
        summing ``self_seconds`` must never count one second twice).
        Live spans pass ``self_seconds`` explicitly (their wall minus
        nested child time) and do their own parent charging on exit."""
        if not self._enabled:
            return
        if self_seconds is None:
            self_seconds = seconds
            stack = getattr(self._tls, "stack", None)
            if stack:
                stack[-1] += seconds
        with self._lock:
            st = self._stats.get(stage)
            if st is None:
                st = self._stats[stage] = StageStat()
            st.count += 1
            st.seconds += seconds
            st.bytes += nbytes
            st.self_seconds += self_seconds

    def span(self, stage: str, nbytes: int = 0,
             attrs: Optional[dict] = None,
             observe: Optional[str] = None):
        """One timed span under ``stage``: accumulates into
        :meth:`stats` and appends begin/end events (thread id + ``attrs``)
        to the timeline.  ``observe`` additionally records the span's
        wall into the named histogram on exit (FL-OBS001 checks the
        name against :class:`names`.HISTOGRAMS like any other literal).
        Returns the shared no-op span when disabled."""
        if not self._enabled:
            return _NULL_SPAN
        return _Span(self, stage, nbytes, attrs, observe)

    def stats(self) -> Dict[str, dict]:
        """Snapshot of all stage accumulators."""
        with self._lock:
            return {k: v.as_dict() for k, v in sorted(self._stats.items())}

    # -- raw-event timeline -------------------------------------------------

    def _event(self, ph: str, name: str, ts: float,
               attrs: Optional[dict]) -> None:
        if not self._enabled:
            return
        with self._lock:
            self._event_locked(ph, name, ts, attrs)

    def _event_locked(self, ph: str, name: str, ts: float,
                      attrs: Optional[dict]) -> None:
        t = threading.current_thread()
        tid = t.ident or 0
        if tid not in self._thread_names:
            self._thread_names[tid] = t.name
        if len(self._events) >= self.max_events:
            self._events.popleft()
            self._counters["trace.events_dropped"] = (
                self._counters.get("trace.events_dropped", 0) + 1
            )
        self._events.append((ph, name, ts, tid, attrs))

    def events(self) -> list:
        """Snapshot of the raw timeline: ``(ph, name, ts, tid, attrs)``
        tuples in record order (``ph``: "B" span begin, "E" span end,
        "i" instant/decision; ``ts`` in ``time.perf_counter`` seconds)."""
        with self._lock:
            return list(self._events)

    def export_chrome_trace(self, path: str) -> int:
        """Write the timeline as Chrome/Perfetto trace-event JSON
        (``chrome://tracing`` / https://ui.perfetto.dev) and return the
        number of events written.

        Emits duration ("B"/"E") pairs per thread plus instant ("i")
        events for decisions, with ``ts`` in microseconds since the
        tracer epoch.  Pairs are balanced per thread on the way out:
        orphaned ends (their begin was evicted from the bounded buffer)
        are dropped, and spans still open at export get a synthetic end
        at the last seen timestamp — a Perfetto load never sees a
        mismatched stack."""
        out = self.chrome_events()
        payload = {"traceEvents": out, "displayTimeUnit": "ms"}
        with open(path, "w") as fh:
            fh.write(json.dumps(payload))
        return len(out)

    def chrome_events(self) -> List[dict]:
        """The balanced, ts-sorted Chrome trace-event dicts of the
        host timeline (``ts`` in µs since the tracer epoch) — the
        shared derivation behind :meth:`export_chrome_trace` and the
        merged host+device export (:func:`unified_trace`)."""
        with self._lock:
            events = list(self._events)
            tnames = dict(self._thread_names)
        # record order is lock order, which can lag the timestamps taken
        # just before the lock on a contended tracer — a stable sort by
        # ts makes the output monotonic while preserving each thread's
        # relative order (per-thread timestamps are non-decreasing, so
        # B/E nesting survives the sort)
        events.sort(key=lambda e: e[2])
        pid = os.getpid()
        out: List[dict] = []
        for tid, tname in sorted(tnames.items()):
            out.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": tname},
            })
        depth: Dict[int, list] = {}
        last_ts = self._epoch
        for ph, name, ts, tid, attrs in events:
            last_ts = max(last_ts, ts)
            us = round((ts - self._epoch) * 1e6, 3)
            if ph == "B":
                depth.setdefault(tid, []).append(name)
            elif ph == "E":
                stack = depth.get(tid)
                if not stack:
                    continue  # begin evicted: skip the orphaned end
                stack.pop()
            ev = {"name": name, "ph": ph, "ts": us, "pid": pid, "tid": tid}
            if ph != "E":
                ev["cat"] = "pftpu"
                if ph == "i":
                    ev["s"] = "t"
                if attrs:
                    ev["args"] = dict(attrs)
            out.append(ev)
        end_us = round((last_ts - self._epoch) * 1e6, 3)
        for tid, stack in depth.items():
            for name in reversed(stack):  # still-open spans: close them
                out.append({
                    "name": name, "ph": "E", "ts": end_us,
                    "pid": pid, "tid": tid,
                })
        return out

    # -- health summary -----------------------------------------------------

    def scan_report(self, wall_seconds: Optional[float] = None,
                    budget_bytes: Optional[int] = None) -> ScanReport:
        """Distill the current snapshot into a :class:`ScanReport`.
        ``wall_seconds`` (scan start → finish) turns the consumer-stall
        total into stall/overlap fractions; ``budget_bytes`` (the scan's
        ``prefetch_bytes``) turns the in-flight high-water into a budget
        utilization."""
        return scan_report_from(
            self.stats(), self.counters(), self.gauges(),
            wall_seconds=wall_seconds, budget_bytes=budget_bytes,
            histograms=self.histograms_dict(),
        )

    def report(self) -> str:
        """Human-readable report: one line per stage, counters, gauges
        (labelled ``max=`` — they are peaks, not totals), decisions, and
        — when scan counters are present — the :class:`ScanReport`
        health block."""
        lines = []
        for name, st in self.stats().items():
            lines.append(
                f"{name:<12} n={st['count']:<6} {st['seconds']*1e3:9.1f} ms"
                + (f"  {st['MB_per_s']:8.1f} MB/s" if st["bytes"] else "")
            )
        for name, v in sorted(self.counters().items()):
            lines.append(f"{name:<32} {v}")
        for name, v in sorted(self.gauges().items()):
            lines.append(f"{name:<32} max={v}")
        for name, h in sorted(self.histograms().items()):
            lines.append(f"{name:<32} {h.render()}")
        for d in self.decisions():
            kv = " ".join(f"{k}={v}" for k, v in d.items() if k != "decision")
            lines.append(f"[{d['decision']}] {kv}")
        if any(k.startswith("scan.") for k in self.metrics()):
            lines.append(self.scan_report().render())
        return "\n".join(lines) or "(no spans recorded — is tracing enabled?)"


# ---------------------------------------------------------------------------
# The active-tracer scope
# ---------------------------------------------------------------------------

_global = Tracer(enabled=os.environ.get("PFTPU_TRACE", "0") == "1")
_active: contextvars.ContextVar = contextvars.ContextVar(
    "pftpu_tracer", default=None
)


def current() -> Tracer:
    """The tracer module-level calls delegate to: the innermost
    ``scope()`` on this thread's context, else the process-global one."""
    t = _active.get()
    return _global if t is None else t


@contextlib.contextmanager
def using(tracer: Tracer) -> Iterator[Tracer]:
    """Activate an existing tracer for the dynamic extent of the block
    (what :func:`scope` does, minus creating the tracer)."""
    token = _active.set(tracer)
    try:
        yield tracer
    finally:
        _active.reset(token)


@contextlib.contextmanager
def scope(max_decisions: int = 64,
          max_events: int = 1 << 16) -> Iterator[Tracer]:
    """Run the block under a fresh, ENABLED, isolated tracer::

        with trace.scope() as t:
            for unit in DatasetScanner(paths):
                ...
        t.export_chrome_trace("scan.json")
        print(t.report())

    Module-level ``span``/``count``/… inside the block (and inside any
    worker task the scan executor / engine submit from it) land on ``t``
    instead of the process-global tracer, so concurrent scans under
    separate scopes never mix their metrics."""
    with using(Tracer(enabled=True, max_decisions=max_decisions,
                      max_events=max_events)) as t:
        yield t


# ---------------------------------------------------------------------------
# Module-level delegates (the stable call-site surface)
# ---------------------------------------------------------------------------

def enable() -> None:
    current().enable()


def disable() -> None:
    current().disable()


def enabled() -> bool:
    return current().enabled()


def reset() -> None:
    current().reset()


def count(name: str, n: int = 1) -> None:
    t = _active.get()
    (_global if t is None else t).count(name, n)


def gauge_max(name: str, value: int) -> None:
    t = _active.get()
    (_global if t is None else t).gauge_max(name, value)


def observe(name: str, value: float) -> None:
    t = _active.get()
    (_global if t is None else t).observe(name, value)


def histograms() -> Dict[str, LogHistogram]:
    return current().histograms()


def counters() -> Dict[str, int]:
    return current().counters()


def gauges() -> Dict[str, int]:
    return current().gauges()


def metrics() -> Dict[str, int]:
    return current().metrics()


def decision(name: str, detail: dict) -> None:
    t = _active.get()
    (_global if t is None else t).decision(name, detail)


def decisions() -> list:
    return current().decisions()


def add(stage: str, seconds: float, nbytes: int = 0,
        self_seconds: Optional[float] = None) -> None:
    t = _active.get()
    (_global if t is None else t).add(stage, seconds, nbytes, self_seconds)


def span(stage: str, nbytes: int = 0, attrs: Optional[dict] = None,
         observe: Optional[str] = None):
    t = _active.get()
    return (_global if t is None else t).span(stage, nbytes, attrs, observe)


def start_trace(name: str = "request", tenant: Optional[str] = None,
                attrs: Optional[dict] = None):
    """Begin a new fleet-wide request trace for the ``with`` block:
    installs a fresh root :class:`TraceContext`, so every span recorded
    under it — on this thread, on carried worker threads, and on every
    daemon the request touches over the wire — shares one trace_id with
    correct parent links, and every closed span lands in the active
    :class:`FlightRecorder`.  Yields the root context (``ctx.trace_id``
    is the handle to grep a fleet timeline for).  Returns the shared
    no-op handle when the active tracer is disabled — the disabled hot
    path allocates nothing and takes no lock."""
    t = _active.get()
    if not (_global if t is None else t)._enabled:
        return _NULL_TRACE
    return _TraceHandle(name, tenant, attrs)


def current_context() -> Optional[TraceContext]:
    """The innermost active :class:`TraceContext`, or None outside any
    trace (one ContextVar read — no allocation)."""
    return _ctx.get()


def child_context() -> Optional[TraceContext]:
    """A wire-ready child of the current context (fresh span_id, parent
    = the current hop), or None outside any trace — what every client
    serializes into an outgoing request line."""
    ctx = _ctx.get()
    return None if ctx is None else ctx.child()


@contextlib.contextmanager
def use_context(ctx: Optional[TraceContext]) -> Iterator[
        Optional[TraceContext]]:
    """Activate ``ctx`` (e.g. one deserialized off a wire hop) for the
    dynamic extent of the block; ``None`` is a no-op, so receivers need
    no branching on whether the caller sent a context."""
    if ctx is None:
        yield None
        return
    token = _ctx.set(ctx)
    try:
        yield ctx
    finally:
        _ctx.reset(token)


def carry_context(fn):
    """Bind ``fn`` to the CALLER's active tracer, trace context, and
    flight recorder for submission to a worker pool — contextvars do
    not cross thread spawns on their own, and :meth:`Tracer.run`
    carries only the tracer.  Used by the hedged remote reader and the
    daemon's executor so off-thread work stays inside the request's
    causal chain."""
    tracer = _active.get()
    ctx = _ctx.get()
    rec = _recorder.get()

    def _carried(*args, **kwargs):
        tok_t = _active.set(tracer) if tracer is not None else None
        tok_c = _ctx.set(ctx) if ctx is not None else None
        tok_r = _recorder.set(rec) if rec is not None else None
        try:
            return fn(*args, **kwargs)
        finally:
            if tok_r is not None:
                _recorder.reset(tok_r)
            if tok_c is not None:
                _ctx.reset(tok_c)
            if tok_t is not None:
                _active.reset(tok_t)

    return _carried


def stats() -> Dict[str, dict]:
    return current().stats()


def events() -> list:
    return current().events()


def export_chrome_trace(path: str) -> int:
    return current().export_chrome_trace(path)


def scan_report(wall_seconds: Optional[float] = None,
                budget_bytes: Optional[int] = None) -> ScanReport:
    return current().scan_report(wall_seconds, budget_bytes)


def report() -> str:
    return current().report()


def serve_metrics(port: int = 0, tracer: Optional[Tracer] = None,
                  host: str = "127.0.0.1",
                  snapshot_dir: Optional[str] = None,
                  peers: Optional[Sequence] = None,
                  peer_timeout_s: float = 2.0):
    """Start a metrics HTTP endpoint over ``tracer`` (default: the
    tracer active HERE, at call time) and return the running
    :class:`~parquet_floor_tpu.utils.metrics_export.MetricsServer`
    (``.port`` holds the bound port — pass 0 for an ephemeral one;
    ``.close()`` stops it).  ``GET /metrics`` answers Prometheus text
    exposition, ``GET /metrics.json`` the JSON snapshot
    (docs/observability.md).  ``snapshot_dir`` folds per-worker
    ``write_snapshot`` files into every scrape (the multi-process
    aggregation story — docs/serving.md); ``peers`` — a list of
    ``(host, port)`` ServeDaemon addresses — extends the fold across
    hosts via each peer's ``metrics`` op, with a dead peer degrading to
    a counted ``serve.metrics_peer_unreachable``, never a failed
    scrape."""
    from .metrics_export import MetricsServer

    return MetricsServer(tracer if tracer is not None else current(),
                         port=port, host=host,
                         snapshot_dir=snapshot_dir, peers=peers,
                         peer_timeout_s=peer_timeout_s)


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Wrap a region in ``jax.profiler.trace`` so XLA device activity lands
    in TensorBoard/Perfetto next to the host spans."""
    import jax

    with jax.profiler.trace(log_dir):
        yield


#: the clock-sync annotation unified_trace plants inside the XLA
#: capture: its profiler timestamp + the host perf_counter taken at the
#: same instant are the shared epoch marker the rebase solves against
CLOCK_SYNC_MARKER = "pftpu_clock_sync"


class UnifiedTrace:
    """Handle yielded by :func:`unified_trace`: ``path`` is where the
    merged file lands on exit; ``events``/``device_events`` are filled
    in after the block closes."""

    def __init__(self, path: str):
        self.path = path
        self.events = 0
        self.device_events = 0


@contextlib.contextmanager
def unified_trace(log_dir: str, path: str) -> Iterator[UnifiedTrace]:
    """Run the block under BOTH the host tracer's timeline and the XLA
    profiler, then merge the two captures onto ONE clock and write a
    single Perfetto-loadable trace-event JSON to ``path`` — XLA kernels
    render next to the host ``ship``/``decode``/``emit`` spans in one
    view (the ROADMAP observability follow-on; docs/observability.md).

    The clock bridge: the profiler's event timestamps live on its own
    session clock, the host tracer's on ``time.perf_counter`` since the
    tracer epoch.  On entry a :data:`CLOCK_SYNC_MARKER` annotation is
    planted INSIDE the XLA capture with the host ``perf_counter`` taken
    at the same instant; on exit the marker is located in the captured
    ``.xplane.pb`` (``utils/xplane.py``) and every device event is
    rebased by the one offset that aligns the pair.  Host spans must be
    recorded by the CURRENT tracer (enable it, or run inside
    ``trace.scope()``)."""
    import glob as _glob

    import jax

    tracer = current()
    handle = UnifiedTrace(path)
    with jax.profiler.trace(log_dir):
        sync_perf = time.perf_counter()
        with jax.profiler.TraceAnnotation(CLOCK_SYNC_MARKER):
            pass
        yield handle
    from .xplane import device_trace_events

    runs = sorted(_glob.glob(
        os.path.join(log_dir, "plugins", "profile", "*", "*.xplane.pb")
    ))
    host_events = tracer.chrome_events()
    dev_events: List[dict] = []
    if runs:
        host_sync_us = (sync_perf - tracer._epoch) * 1e6
        dev_events = device_trace_events(
            runs[-1], sync_marker=CLOCK_SYNC_MARKER,
            host_sync_us=host_sync_us,
        )
    merged = host_events + dev_events
    # one monotonic stream for the whole file: metadata first, then
    # everything by rebased timestamp (stable — per-pid B/E order and
    # nesting survive)
    merged.sort(key=lambda e: (0 if e.get("ph") == "M" else 1,
                               e.get("ts", 0.0)))
    payload = {"traceEvents": merged, "displayTimeUnit": "ms"}
    with open(path, "w") as fh:
        fh.write(json.dumps(payload))
    handle.events = len(merged)
    handle.device_events = sum(
        1 for e in dev_events if e.get("ph") != "M"
    )


# ---------------------------------------------------------------------------
# The fleet timeline merge + incident bundles
# (docs/observability.md "Distributed tracing")
# ---------------------------------------------------------------------------

def _compose_offsets(nodes: Sequence[str],
                     measured: Dict[str, Dict[str, float]]
                     ) -> Dict[str, float]:
    """Per-node clock offset to the REFERENCE node (first in sorted
    order), composed over the measured peer-offset graph by BFS.
    ``measured[c][s]`` is c's midpoint estimate of ``s_clock −
    c_clock`` (seconds); rebasing subtracts the composed offset from a
    node's timestamps.  A direct measurement beats a reversed edge;
    nodes unreachable in the graph fall back to offset 0 — recorded as
    such in the merge output, never a silent guess."""
    ordered = sorted(nodes)
    if not ordered:
        return {}
    adj: Dict[str, Dict[str, float]] = {n: {} for n in ordered}
    for c, peers in measured.items():
        for s, off in (peers or {}).items():
            if c in adj and s in adj:
                adj[c][s] = float(off)
                adj[s].setdefault(c, -float(off))
    ref = ordered[0]
    out = {ref: 0.0}
    queue = deque([ref])
    while queue:
        n = queue.popleft()
        for m, off in adj[n].items():
            if m not in out:
                out[m] = out[n] + off
                queue.append(m)
    for n in ordered:
        out.setdefault(n, 0.0)
    return out


def merge_fleet_trace(snaps: Sequence[dict], path: Optional[str] = None,
                      extra_events: Optional[Sequence[dict]] = None) -> dict:
    """Merge per-node worker snapshots into ONE Perfetto timeline with
    a track per host.  Each snapshot dict carries ``node`` (its host
    label), ``traces`` (a :meth:`FlightRecorder.traces` export), and
    optionally ``clock_offsets`` — that node's midpoint estimates of
    each peer's clock minus its own (seconds), taken from the fleet
    protocol's request/response RTT pairs.  Offsets are composed to the
    reference node (BFS over the measurement graph) and every span is
    rebased onto the reference clock before emission, so one request's
    cross-host causal chain lines up on one time axis.

    Emits complete ("X") events — one Perfetto process per node
    (``process_name`` metadata), threads preserved as sub-tracks, and
    ``args`` carrying trace_id/span_id/parent_id/tenant for the parent
    links.  ``extra_events`` (e.g. the rebased device sub-track of a
    :func:`unified_trace` capture) are appended verbatim.  Returns the
    payload dict — ``clock_offsets_s`` records the applied per-node
    offsets, ``trace_ids`` the distinct traces present — and writes it
    as JSON to ``path`` when given."""
    by_node: Dict[str, list] = {}
    measured: Dict[str, Dict[str, float]] = {}
    for snap in snaps:
        if not isinstance(snap, dict):
            continue
        node = str(snap.get("node") or f"node{len(by_node)}")
        by_node.setdefault(node, [])
        for tr in snap.get("traces") or []:
            by_node[node].extend(tr.get("spans") or [])
        co = snap.get("clock_offsets")
        if co:
            measured.setdefault(node, {}).update(
                {str(k): float(v) for k, v in co.items()}
            )
    nodes = sorted(by_node)
    offsets = _compose_offsets(nodes, measured)
    rebased: Dict[str, list] = {}
    base = None
    for node in nodes:
        off = offsets.get(node, 0.0)
        recs = []
        for rec in by_node[node]:
            ts = float(rec.get("ts", 0.0)) - off
            recs.append((ts, rec))
            if base is None or ts < base:
                base = ts
        recs.sort(key=lambda p: p[0])
        rebased[node] = recs
    base = base if base is not None else 0.0
    events: List[dict] = []
    trace_ids = set()
    for pid, node in enumerate(nodes, start=1):
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": node},
        })
        for ts, rec in rebased[node]:
            args = {
                k: rec[k]
                for k in ("trace_id", "span_id", "parent_id", "tenant")
                if rec.get(k) is not None
            }
            if rec.get("attrs"):
                args.update(rec["attrs"])
            events.append({
                "name": rec.get("name", "span"), "ph": "X",
                "cat": "pftpu",
                "ts": round((ts - base) * 1e6, 3),
                "dur": round(float(rec.get("dur", 0.0)) * 1e6, 3),
                "pid": pid, "tid": int(rec.get("tid", 0)),
                "args": args,
            })
            if rec.get("trace_id"):
                trace_ids.add(rec["trace_id"])
    if extra_events:
        events.extend(extra_events)
    events.sort(key=lambda e: (0 if e.get("ph") == "M" else 1,
                               e.get("ts", 0.0)))
    out = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "clock_offsets_s": {n: round(offsets.get(n, 0.0), 9)
                            for n in nodes},
        "trace_ids": sorted(trace_ids),
        "events": len(events),
    }
    if path is not None:
        with open(path, "w") as fh:
            fh.write(json.dumps(out))
    return out


def verify_fleet_timeline(merged: dict) -> dict:
    """Structural validation of a :func:`merge_fleet_trace` payload —
    the shared truth check behind the fleet-trace smoke, the chaos
    bench, and ``check_bench_report.check_fleet_trace``.  Verifies the
    three properties an incident bundle's timeline must hold: every
    span's parent resolves WITHIN its trace (the cross-host causal
    chain is closed), every (process, thread) track is balanced
    (non-negative ts/dur complete events) and time-ordered, and
    reports which traces span >= 2 nodes (the distributed ones)."""
    events = merged.get("traceEvents") or []
    node_of: Dict[object, str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            node_of[e.get("pid")] = str((e.get("args") or {}).get("name"))
    spans = [e for e in events if e.get("ph") == "X"]
    by_trace: Dict[str, list] = {}
    ids_by_trace: Dict[str, set] = {}
    for e in spans:
        a = e.get("args") or {}
        t = a.get("trace_id")
        if not t:
            continue
        by_trace.setdefault(t, []).append(e)
        if a.get("span_id"):
            ids_by_trace.setdefault(t, set()).add(a["span_id"])
    trace_nodes: Dict[str, list] = {}
    cross: List[str] = []
    for t, evs in sorted(by_trace.items()):
        nodes = sorted({
            node_of.get(e.get("pid"), str(e.get("pid"))) for e in evs
        })
        trace_nodes[t] = nodes
        if len(nodes) >= 2:
            cross.append(t)
    dangling = 0
    for t, evs in by_trace.items():
        ids = ids_by_trace.get(t, set())
        for e in evs:
            p = (e.get("args") or {}).get("parent_id")
            if p is not None and p not in ids:
                dangling += 1
    balanced_ok = True
    monotonic_ok = True
    last_ts: Dict[tuple, float] = {}
    for e in spans:
        ts = float(e.get("ts", 0.0))
        dur = float(e.get("dur", 0.0))
        if ts < 0.0 or dur < 0.0:
            balanced_ok = False
        track = (e.get("pid"), e.get("tid"))
        prev = last_ts.get(track)
        if prev is not None and ts < prev:
            monotonic_ok = False
        last_ts[track] = ts
    return {
        "span_events": len(spans),
        "tracks": len(last_ts),
        "trace_nodes": trace_nodes,
        "cross_node_traces": cross,
        "parent_links_ok": dangling == 0,
        "dangling_parents": dangling,
        "balanced_ok": balanced_ok,
        "monotonic_ok": monotonic_ok,
        "ok": bool(spans) and dangling == 0
              and balanced_ok and monotonic_ok,
    }


def _slug(s: str) -> str:
    return "".join(
        c if c.isalnum() or c in "-_" else "-" for c in str(s)
    )[:48] or "incident"


def write_incident_bundle(out_dir: str, reason: str, *,
                          traces: Sequence[dict],
                          snaps: Sequence[dict] = (),
                          metrics: Optional[dict] = None,
                          health_text: str = "",
                          detail: Optional[dict] = None) -> str:
    """Write one incident bundle directory under ``out_dir`` and return
    its path.  Layout (docs/observability.md):

    * ``meta.json``     — trigger reason, unix timestamp, free detail
    * ``traces.json``   — the flight-recorder window that fired
    * ``metrics.json``  — the merged metrics snapshot at dump time
    * ``health.txt``    — the serving layer's ``health()`` rendering
    * ``timeline.json`` — :func:`merge_fleet_trace` over ``snaps``
      (every worker snapshot individually — per-node identity is what
      makes the cross-host chain visible)
    """
    ts = perf_to_unix(time.perf_counter())
    name = f"incident-{int(ts * 1000):013d}-{_slug(reason)}"
    bdir = os.path.join(out_dir, name)
    os.makedirs(bdir, exist_ok=True)
    with open(os.path.join(bdir, "meta.json"), "w") as fh:
        fh.write(json.dumps(
            {"reason": reason, "ts": ts, "detail": detail or {}}
        ))
    with open(os.path.join(bdir, "traces.json"), "w") as fh:
        fh.write(json.dumps(list(traces)))
    if metrics is not None:
        with open(os.path.join(bdir, "metrics.json"), "w") as fh:
            fh.write(json.dumps(metrics))
    with open(os.path.join(bdir, "health.txt"), "w") as fh:
        fh.write(health_text or "")
    merge_fleet_trace(list(snaps), os.path.join(bdir, "timeline.json"))
    return bdir


# ---------------------------------------------------------------------------
# The flight-recorder trigger bus: SLO breaches (serve/slo.py), breaker
# trips (io/remote.py), and fleet epoch fences (serve/fleet.py) fire it;
# daemons subscribe their snapshot push (phase 0) and bundle dump
# (phase 1), so an in-process fleet's dump sees every node's freshly
# pushed snapshot.
# ---------------------------------------------------------------------------

_flight_subs: List[tuple] = []
_flight_subs_lock = threading.Lock()


def install_flight_trigger(fn, phase: int = 1):
    """Register ``fn(reason, detail)`` to run on every
    :func:`flight_fire`.  Phase-0 subscribers (snapshot pushers) all
    run before any phase-1 subscriber (bundle dumpers).  Returns a
    ``remove()`` callable — daemons deregister on close."""
    entry = (int(phase), fn)
    with _flight_subs_lock:
        _flight_subs.append(entry)

    def remove() -> None:
        with _flight_subs_lock:
            try:
                _flight_subs.remove(entry)
            except ValueError:
                pass

    return remove


def flight_fire(reason: str, detail: Optional[dict] = None) -> int:
    """Fire the flight-recorder trigger bus (an SLO burn, a breaker
    trip, an epoch fence).  Subscriber exceptions are swallowed — an
    incident dump must never take the serving path down with it.
    Returns the number of subscribers invoked."""
    with _flight_subs_lock:
        subs = sorted(_flight_subs, key=lambda e: e[0])
    n = 0
    for _, fn in subs:
        try:
            fn(reason, dict(detail or {}))
        except Exception:
            pass
        n += 1
    return n
