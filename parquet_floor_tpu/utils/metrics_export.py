"""Live metrics export: Prometheus text exposition + JSON snapshots.

The :class:`~parquet_floor_tpu.utils.trace.Tracer` keeps everything a
deployment wants to scrape — additive counters, high-water gauges,
per-stage walls, and the log-bucketed latency histograms — but until
now the only ways out were in-process snapshots and one-shot file
exports.  This module is the always-on face (*Dapper*'s "observability
must not require redeploying" rule):

* :func:`render_prometheus` — the text exposition format (version
  0.0.4) scrapers speak: counters as ``counter``, gauges as ``gauge``,
  stage stats as labelled counters, and each
  :class:`~parquet_floor_tpu.utils.histogram.LogHistogram` as a native
  Prometheus histogram (cumulative ``_bucket{le=…}`` series + ``_sum``
  + ``_count``) using the log-bucket upper bounds as ``le`` edges.
* :func:`snapshot` / :func:`merge_snapshots` — the JSON form and its
  cross-process fold, the same additive/max/bucket-wise law
  ``ScanReport.merge`` established (per-worker processes emit
  snapshots; an aggregator merges and re-renders).
* :class:`MetricsServer` — a stdlib ``ThreadingHTTPServer`` behind
  ``trace.serve_metrics(port)``: ``/metrics`` (Prometheus) and
  ``/metrics.json``.
* :class:`FileMetricsEmitter` — a periodic file writer (atomic rename)
  for scrape-less runs: batch jobs land their final metrics on disk
  even when nothing ever polls them.

Everything is stdlib-only.  Docs: ``docs/observability.md``.
"""

from __future__ import annotations

import contextlib
import json
import os
import pathlib
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Sequence

from .histogram import LogHistogram

#: every exported series name is prefixed, so a shared Prometheus has
#: one obvious namespace to query
PREFIX = "pftpu_"

_SAN = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize(name: str) -> str:
    """Registry name → Prometheus metric name (dots become
    underscores; the kind suffixes survive as plain segments)."""
    return PREFIX + _SAN.sub("_", name)


def _fmt(v: float) -> str:
    """Prometheus sample value: integers render bare, floats as
    repr-round-trippable decimals."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int) or float(v).is_integer():
        return str(int(v))
    return repr(float(v))


# ---------------------------------------------------------------------------
# snapshots (the serializable form everything else derives from)
# ---------------------------------------------------------------------------

def snapshot(tracer) -> dict:
    """One JSON-ready snapshot of a tracer: counters, gauges, stage
    stats, histograms (``LogHistogram.as_dict`` form)."""
    return {
        "counters": tracer.counters(),
        "gauges": tracer.gauges(),
        "stages": tracer.stats(),
        "histograms": tracer.histograms_dict(),
    }


def merge_snapshots(snaps: Sequence[dict]) -> dict:
    """Fold per-process :func:`snapshot` dicts into one — counters and
    stage stats sum, gauges take the max, histograms merge bucket-wise
    (the ``ScanReport.merge`` aggregation law, reused)."""
    snaps = list(snaps)
    if not snaps:
        raise ValueError("merge_snapshots needs at least one snapshot")
    counters: Dict[str, int] = {}
    gauges: Dict[str, int] = {}
    stages: Dict[str, dict] = {}
    hists: Dict[str, LogHistogram] = {}
    for s in snaps:
        for k, v in (s.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + int(v)
        for k, v in (s.get("gauges") or {}).items():
            gauges[k] = max(gauges.get(k, -(1 << 62)), int(v))
        for k, st in (s.get("stages") or {}).items():
            acc = stages.setdefault(
                k, {"count": 0, "seconds": 0.0, "bytes": 0,
                    "self_seconds": 0.0},
            )
            acc["count"] += int(st.get("count", 0))
            acc["seconds"] += float(st.get("seconds", 0.0))
            acc["bytes"] += int(st.get("bytes", 0))
            acc["self_seconds"] += float(
                st.get("self_seconds", st.get("seconds", 0.0))
            )
        LogHistogram.fold_dicts(hists, s.get("histograms") or {})
    return {
        "counters": counters,
        "gauges": gauges,
        "stages": stages,
        "histograms": {k: h.as_dict() for k, h in hists.items()},
    }


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

def write_snapshot(snap: dict, path: str) -> None:
    """Persist one :func:`snapshot`-shaped dict as JSON via
    write-to-temp + atomic rename — the per-worker half of the
    multi-process fold: each serving worker lands its snapshot in a
    shared directory, and any aggregator (:func:`merge_snapshot_dir`,
    the daemon's metrics op, ``MetricsServer(snapshot_dir=)``) folds
    the directory through :func:`merge_snapshots`."""
    import tempfile

    d, base = os.path.split(str(path))
    fd, tmp = tempfile.mkstemp(dir=d or ".", prefix=base + ".tmp.")
    try:
        with os.fdopen(fd, "w") as fh:
            json.dump(snap, fh)
        os.replace(tmp, str(path))
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def merge_snapshot_dir(dir_path: str, extra: Sequence[dict] = (),
                       exclude: Sequence[str] = ()) -> dict:
    """Fold every ``*.json`` worker snapshot under ``dir_path`` (plus
    any ``extra`` in-memory snapshots — e.g. the aggregator's own live
    state; minus ``exclude``\\ d file names — e.g. the aggregator's own
    stale push) through :func:`merge_snapshots`.  A torn or
    non-snapshot file fails loudly (ValueError): a silent skip would
    under-report a worker, which is exactly the lie a fleet dashboard
    must not tell — :func:`write_snapshot`'s atomic rename is what
    makes "every file parses" a fair requirement."""
    snaps = list(extra)
    root = pathlib.Path(dir_path)
    skip = set(exclude)
    for p in sorted(root.glob("*.json")):
        if p.name in skip:
            continue
        try:
            snaps.append(json.loads(p.read_text()))
        except ValueError as e:
            raise ValueError(
                f"worker snapshot {p} does not parse: {e}"
            ) from e
    if not snaps:
        raise ValueError(f"no worker snapshots under {dir_path}")
    return merge_snapshots(snaps)


def render_prometheus_snapshot(snap: dict) -> str:
    """Render one :func:`snapshot`-shaped dict as text exposition."""
    lines = []
    for name, v in sorted((snap.get("counters") or {}).items()):
        m = sanitize(name)
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m} {_fmt(v)}")
    for name, v in sorted((snap.get("gauges") or {}).items()):
        m = sanitize(name)
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m} {_fmt(v)}")
    stages = snap.get("stages") or {}
    if stages:
        for series, key in (
            ("stage_count", "count"),
            ("stage_seconds_total", "seconds"),
            ("stage_bytes_total", "bytes"),
        ):
            m = PREFIX + series
            lines.append(f"# TYPE {m} counter")
            for stage, st in sorted(stages.items()):
                lines.append(
                    f'{m}{{stage="{stage}"}} {_fmt(st.get(key, 0))}'
                )
    for name, d in sorted((snap.get("histograms") or {}).items()):
        h = LogHistogram.from_dict(d)
        m = sanitize(name)
        lines.append(f"# TYPE {m} histogram")
        cum = h.zeros
        if h.zeros:
            lines.append(f'{m}_bucket{{le="0"}} {h.zeros}')
        for i in sorted(h.buckets):
            cum += h.buckets[i]
            line = f'{m}_bucket{{le="{h.bucket_hi(i):.9g}"}} {cum}'
            ex = h.exemplars.get(i)
            if ex is not None:
                # OpenMetrics exemplar syntax: the bucket's reservoir
                # slot links the series straight to one request trace
                line += f' # {{trace_id="{ex[0]}"}} {_fmt(ex[1])}'
            lines.append(line)
        lines.append(f'{m}_bucket{{le="+Inf"}} {h.count}')
        lines.append(f"{m}_sum {_fmt(h.total)}")
        lines.append(f"{m}_count {h.count}")
    return "\n".join(lines) + "\n"


def render_prometheus(tracer) -> str:
    """Text exposition (version 0.0.4) of one tracer's live state."""
    return render_prometheus_snapshot(snapshot(tracer))


def parse_prometheus(text: str) -> Dict[str, float]:
    """Tiny stdlib parser of the exposition format: sample name (with
    its ``{labels}`` verbatim) → value.  Enough for round-trip tests
    and the CI scrape validation — not a general client."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        # an OpenMetrics exemplar suffix (` # {trace_id="…"} v`) is
        # annotation, not the sample — strip it before splitting
        line = line.split(" # ", 1)[0].rstrip()
        try:
            name, value = line.rsplit(None, 1)
        except ValueError as e:
            raise ValueError(f"bad exposition line {line!r}") from e
        out[name] = float(value)
    return out


# ---------------------------------------------------------------------------
# the live endpoint
# ---------------------------------------------------------------------------

def fetch_peer_metrics(host: str, port: int,
                       timeout_s: float = 2.0) -> Optional[dict]:
    """One hello-free ``metrics`` op against a ServeDaemon peer (its
    line protocol answers ``metrics``/``health`` on the protocol plane,
    no tenant registration needed).  Returns the peer's folded snapshot
    dict, or None when the peer is unreachable or answers garbage — the
    cross-host scrape DEGRADES (counted upstream), it never fails."""
    import socket

    try:
        with socket.create_connection((host, int(port)),
                                      timeout=timeout_s) as sock:
            sock.settimeout(timeout_s)
            sock.sendall(json.dumps({"op": "metrics"}).encode(
                "utf-8", "surrogateescape") + b"\n")
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = sock.recv(1 << 20)
                if not chunk:
                    return None
                buf += chunk
        reply = json.loads(buf.decode("utf-8", "surrogateescape"))
    except (OSError, ValueError):
        return None
    if not isinstance(reply, dict) or not reply.get("ok"):
        return None
    snap = reply.get("metrics")
    return snap if isinstance(snap, dict) else None


class MetricsServer:
    """``ThreadingHTTPServer`` over one tracer — created via
    ``trace.serve_metrics(port)``.  Binds at construction (``port=0``
    picks an ephemeral one, read it back from ``.port``), serves on a
    daemon thread, stops on :meth:`close` (idempotent; also a context
    manager).

    ``snapshot_dir`` turns the endpoint into a multi-worker
    aggregator: every scrape folds the directory's per-worker
    :func:`write_snapshot` files together with this process's own live
    tracer state (:func:`merge_snapshot_dir`), so one scrape sees the
    whole worker fleet — the push-gateway story for N serving
    processes per host.

    ``peers`` extends the fold ACROSS hosts: each ``(host, port)`` is a
    ServeDaemon whose ``metrics`` op is queried on every scrape
    (:func:`fetch_peer_metrics`) and merged in.  A dead peer degrades
    to a counted ``serve.metrics_peer_unreachable`` on this server's
    tracer — never a failed scrape (docs/observability.md)."""

    def __init__(self, tracer, port: int = 0, host: str = "127.0.0.1",
                 snapshot_dir: Optional[str] = None,
                 peers: Optional[Sequence] = None,
                 peer_timeout_s: float = 2.0):
        self.tracer = tracer
        self.snapshot_dir = snapshot_dir
        self.peers = [(str(h), int(p)) for h, p in (peers or [])]
        self.peer_timeout_s = float(peer_timeout_s)
        outer = self

        def _snap() -> dict:
            extra = [snapshot(outer.tracer)]
            for ph, pp in outer.peers:
                peer_snap = fetch_peer_metrics(
                    ph, pp, timeout_s=outer.peer_timeout_s
                )
                if peer_snap is None:
                    outer.tracer.count("serve.metrics_peer_unreachable")
                    # re-snapshot so the count just taken is visible in
                    # THIS scrape, not only the next one
                    extra[0] = snapshot(outer.tracer)
                else:
                    extra.append(peer_snap)
            if outer.snapshot_dir is None:
                return (extra[0] if len(extra) == 1
                        else merge_snapshots(extra))
            return merge_snapshot_dir(outer.snapshot_dir, extra=extra)

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):       # noqa: N802 (http.server contract)
                if self.path.split("?")[0] == "/metrics":
                    body = render_prometheus_snapshot(_snap()).encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.split("?")[0] == "/metrics.json":
                    body = json.dumps(_snap()).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):   # scrapes are not stdout news
                pass

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"pftpu-metrics:{self.port}", daemon=True,
        )
        self._thread.start()
        self._closed = False

    def url(self, path: str = "/metrics") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class FileMetricsEmitter:
    """Periodic exposition-to-file writer for scrape-less runs: every
    ``interval_s`` (and once on :meth:`close`) the tracer's Prometheus
    text lands at ``path`` via write-to-temp + atomic rename, so a
    reader never sees a torn file.  Daemon thread; context manager."""

    def __init__(self, tracer, path: str, interval_s: float = 15.0):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.tracer = tracer
        self.path = str(path)
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="pftpu-metrics-emitter", daemon=True,
        )
        self._thread.start()

    def emit(self) -> None:
        """Write one snapshot now (atomic rename).  The temp name is
        unique PER CALL (mkstemp), so even a close() racing a stalled
        loop-thread emit can never interleave writes into one file —
        the never-torn guarantee holds unconditionally."""
        import tempfile

        d, base = os.path.split(self.path)
        fd, tmp = tempfile.mkstemp(dir=d or ".", prefix=base + ".tmp.")
        try:
            with os.fdopen(fd, "w") as fh:
                fh.write(render_prometheus(self.tracer))
            os.replace(tmp, self.path)
        except BaseException:
            with contextlib.suppress(OSError):
                os.unlink(tmp)
            raise

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.emit()

    def close(self) -> None:
        """Stop the thread and write the final snapshot; idempotent."""
        if not self._stop.is_set():
            self._stop.set()
            self._thread.join(timeout=5)
            self.emit()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
