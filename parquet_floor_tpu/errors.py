"""Error taxonomy — structured, fail-loudly exceptions for every corruption
and unsupported-feature path (SURVEY.md §5: the reference *swallows* I/O
errors, ``FSDataInputStream.java:21-29``; this framework refuses to).

Every error carries structured context — file path, column path, row-group
index, page ordinal, byte offset — so a failure inside a directory scan of a
thousand files names exactly which bytes are bad.  The hierarchy keeps
``ValueError``/``EOFError`` as secondary bases where pre-taxonomy callers
(and tests) catch those builtins:

    ParquetError (Exception)
    ├── CorruptFooterError        (also ValueError)   footer/magic/metadata
    ├── CorruptPageError          (also ValueError)   page header/payload
    │   └── ChecksumMismatchError                     CRC32 says bytes changed
    ├── TruncatedFileError        (also EOFError)     read past physical end
    ├── UnsupportedFeatureError   (also ValueError)   valid file, missing code
    │   └── format.codecs.UnsupportedCodec            codec not available
    ├── IoRetryExhaustedError     (also OSError)      transient faults persisted
    ├── RemoteTransientError      (also OSError)      retryable remote fetch failure
    │   ├── RemoteThrottledError                      store said slow down (carries retry_after_s)
    │   └── BreakerOpenError                          circuit breaker failing fast
    ├── RemoteFatalError          (NOT OSError)       non-retryable remote failure
    └── format.thrift.ThriftDecodeError (also ValueError)  bad compact thrift

The remote classes are the connection-level classification contract of
``io.remote`` (docs/remote.md): **transient** failures are ``OSError``\\ s so
the existing ``RetryingSource`` retry/deadline machinery picks them up
unchanged; **throttled** is transient plus a server-suggested
``retry_after_s`` that throttle-aware backoff honors; **fatal** is
deliberately NOT an ``OSError`` — a denied credential or a deleted bucket
must never burn a retry schedule, and it is not corruption either, so it
passes through :func:`classified_decode_errors` annotated, un-wrapped.

Raise with whatever context is known at the raise site; ``annotate`` lets an
outer frame fill in fields an inner frame could not know (e.g. the decoder
knows the page ordinal, the file reader knows the path)::

    raise CorruptPageError("dictionary index out of range",
                           path=src.name, column="s", row_group=2, page=0)

Two shared idioms live here so the classification rules exist in ONE place
(and so ``floorlint`` — :mod:`parquet_floor_tpu.analysis` — has a single
blessed spelling to check for):

* :func:`classified_decode_errors` — the transient-vs-corruption except
  ladder every decode boundary needs (annotate taxonomy, pass through
  ``OSError``/``MemoryError``, wrap anything else as corruption).
* :func:`checked_alloc_size` — the i32 size cap every allocation whose
  length came out of a parsed file field must flow through, so a flipped
  size bit surfaces as :class:`CorruptPageError` instead of a multi-GiB
  allocation attempt (or ``MemoryError`` misread as host pressure).
"""

from __future__ import annotations

import contextlib
from typing import Optional

_CONTEXT_FIELDS = ("path", "column", "row_group", "page", "offset")


class ParquetError(Exception):
    """Base of the taxonomy; carries structured location context.

    ``message`` is the bare defect description; ``str()`` appends whatever
    context fields are set, so logs stay greppable by file/column.
    """

    def __init__(
        self,
        message: str = "",
        *,
        path: Optional[str] = None,
        column: Optional[str] = None,
        row_group: Optional[int] = None,
        page: Optional[int] = None,
        offset: Optional[int] = None,
    ):
        super().__init__(message)
        self.message = message
        self.path = path
        self.column = column
        self.row_group = row_group
        self.page = page
        self.offset = offset

    @property
    def context(self) -> dict:
        """The non-None context fields as a dict (stable key order)."""
        return {
            k: getattr(self, k)
            for k in _CONTEXT_FIELDS
            if getattr(self, k) is not None
        }

    def __str__(self) -> str:
        ctx = self.context
        if not ctx:
            return self.message
        suffix = ", ".join(f"{k}={v!r}" for k, v in ctx.items())
        return f"{self.message} [{suffix}]"


def annotate(err: ParquetError, **context) -> ParquetError:
    """Fill context fields the raise site could not know (outer frames call
    this before re-raising).  Already-set fields win — the innermost frame
    had the most precise location."""
    for key, value in context.items():
        if key in _CONTEXT_FIELDS and value is not None and getattr(err, key) is None:
            setattr(err, key, value)
    return err


class CorruptFooterError(ParquetError, ValueError):
    """The footer (magic, length word, or Thrift metadata) does not parse;
    nothing in the file can be located without it."""


class CorruptPageError(ParquetError, ValueError):
    """A page header or payload is damaged (bad framing, undecodable
    payload, value/footer count disagreement)."""


class ChecksumMismatchError(CorruptPageError):
    """The page's CRC32 does not match its payload: the bytes changed
    between writer and reader."""

    def __init__(self, message: str = "", *, expected_crc: Optional[int] = None,
                 actual_crc: Optional[int] = None, **context):
        super().__init__(message, **context)
        self.expected_crc = expected_crc
        self.actual_crc = actual_crc


class TruncatedFileError(ParquetError, EOFError):
    """A read reached past the physical end of the file (file shorter than
    its metadata claims, or cut mid-structure)."""


class UnsupportedFeatureError(ParquetError, ValueError):
    """The file is (as far as we can tell) valid, but uses a format feature
    this engine does not implement — fail loudly rather than guess."""


class IoRetryExhaustedError(ParquetError, OSError):
    """Transient I/O failures persisted beyond the configured retry budget
    (``ReaderOptions.io_retries``)."""

    def __init__(self, message: str = "", *, attempts: Optional[int] = None,
                 **context):
        super().__init__(message, **context)
        self.attempts = attempts


class RemoteTransientError(ParquetError, OSError):
    """A remote range fetch failed in a way a retry may fix (connection
    reset, 5xx, a fetch that crossed its per-range deadline).  An
    ``OSError`` on purpose: every retry layer in the package —
    ``RetryingSource`` above all — already treats ``OSError`` as the
    transient class, so remote flakiness rides the existing budgets.

    ``retry_after_s``, when set, is the earliest time a retry is worth
    issuing (seconds from now); throttle-aware backoff never sleeps less.
    """

    def __init__(self, message: str = "", *,
                 retry_after_s: Optional[float] = None, **context):
        super().__init__(message, **context)
        self.retry_after_s = retry_after_s


class RemoteThrottledError(RemoteTransientError):
    """The store asked for back-pressure (HTTP 429/503-class).  Transient
    — but distinct, because a throttle must neither trip the circuit
    breaker (the endpoint is UP, just busy) nor be retried ahead of its
    ``retry_after_s``."""


class BreakerOpenError(RemoteTransientError):
    """The per-source circuit breaker is open and failing fast: the last
    ``breaker_threshold`` requests all failed, so new requests are
    refused without touching the network until the cooldown passes.
    ``retry_after_s`` carries the remaining cooldown, so a retry layer
    above sleeps exactly long enough to meet the half-open probe."""


class RemoteFatalError(ParquetError):
    """A remote failure no retry can fix: credentials refused, bucket or
    object gone, a transport-level invariant broken.  Deliberately NOT an
    ``OSError`` (retry layers must give up immediately) and not a
    corruption class either (salvage must not quarantine healthy data
    over a dead endpoint) — it propagates annotated through
    :func:`classified_decode_errors`."""


@contextlib.contextmanager
def classified_decode_errors(wrap, what, ctx=None, reclassify=()):
    """The ONE transient-vs-corruption ladder for decode boundaries.

    Wraps a decode region so every way it can fail lands in the taxonomy
    with the right class:

    * taxonomy errors pass through, annotated with ``ctx`` (inner frames
      win on fields they already set);
    * ``OSError``/``MemoryError`` pass through untouched — the transient
      I/O class and host memory pressure are environmental facts, and
      wrapping either as corruption would let salvage quarantine healthy
      data on a flaky mount;
    * anything else hostile bytes tripped (IndexError deep in an encoding,
      RecursionError in schema building, …) is re-raised as ``wrap`` —
      ``wrap(f"{what}: {err}", **ctx)`` with the cause chained.

    ``reclassify`` lists taxonomy classes that must STILL be wrapped (e.g.
    ``ThriftDecodeError`` inside footer parsing becomes
    :class:`CorruptFooterError` so sniff loops see one class).

    Usage::

        with classified_decode_errors(CorruptPageError,
                                      "data page decode failed", ctx):
            ... decode ...
    """
    try:
        yield
    except reclassify as e:
        raise wrap(f"{what}: {e}", **(ctx or {})) from e
    except ParquetError as e:
        raise annotate(e, **(ctx or {}))
    except (OSError, MemoryError):
        raise  # transient I/O or host pressure, not corruption
    except Exception as e:
        raise wrap(f"{what}: {e}", **(ctx or {})) from e


#: The format stores every size as i32; anything at or past this ceiling
#: coming out of a parsed field is a corrupt header, not a real length.
ALLOC_CAP = 1 << 31


def checked_alloc_size(n, what="allocation", *, cap=ALLOC_CAP, **context) -> int:
    """Validate an allocation size that was derived from a parsed file
    field; returns it as a plain ``int``.

    Every ``bytes(n)`` / ``np.empty(n)`` whose ``n`` came off the wire
    must flow through here (floorlint rule FL-ALLOC001): a flipped size
    bit then surfaces as :class:`CorruptPageError` with location context
    instead of a multi-GiB allocation attempt whose ``MemoryError`` would
    be misread as host pressure."""
    n = int(n)
    if n < 0 or n >= cap:
        raise CorruptPageError(
            f"implausible {what} size {n} (valid range is [0, {cap}))",
            **context,
        )
    return n
