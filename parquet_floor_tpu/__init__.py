"""parquet-floor-tpu: a TPU-native (JAX/XLA/Pallas) Parquet framework.

Brand-new implementation with the capability surface of the Java reference
``Pablete1234/parquet-floor`` (see SURVEY.md): a declarative
Hydrator/Dehydrator API over a from-scratch Parquet format engine, with the
columnar decode hot path offloaded to TPU kernels.
"""

from .errors import (
    BreakerOpenError,
    ChecksumMismatchError,
    CorruptFooterError,
    CorruptPageError,
    IoRetryExhaustedError,
    ParquetError,
    RemoteFatalError,
    RemoteThrottledError,
    RemoteTransientError,
    TruncatedFileError,
    UnsupportedFeatureError,
)
from .format.schema import (
    ColumnDescriptor,
    GroupType,
    LogicalAnnotation,
    MessageType,
    PrimitiveType,
    types,
)
from .format.parquet_thrift import CompressionCodec, Encoding, Type
from .format.codecs import UnsupportedCodec, register_codec
from .format.metadata import ParquetMetadata
from .format.file_read import (
    ParquetFileReader,
    ReaderOptions,
    SalvageReport,
    SalvageSkip,
)
from .format.file_write import ColumnData, ParquetFileWriter, WriterOptions
from .api.hydrate import (
    BatchHydrator,
    BatchHydratorSupplier,
    Dehydrator,
    Hydrator,
    HydratorSupplier,
    ValueWriter,
)
from .api.reader import ParquetReader
from .api.writer import ParquetWriter
from .batch.columns import BatchColumn, batch_to_arrow
from .batch.nested import NestedColumn, assemble_nested, shred_nested
from .batch.aggregate import Aggregate
from .batch.predicate import Predicate, col
from .utils import trace

from ._version import __version__  # noqa: F401  (re-export)

__all__ = [
    "Aggregate",
    "BatchColumn", "BatchHydrator", "BatchHydratorSupplier",
    "BreakerOpenError",
    "ChecksumMismatchError", "ColumnData",
    "ColumnDescriptor", "CompressionCodec", "CorruptFooterError",
    "CorruptPageError", "Dehydrator",
    "DeviceColumn", "Encoding", "GroupType", "Hydrator", "HydratorSupplier",
    "IoRetryExhaustedError",
    "LogicalAnnotation", "MessageType", "NestedColumn", "ParquetError",
    "ParquetFileReader",
    "ParquetFileWriter", "ParquetMetadata", "ParquetReader", "ParquetWriter",
    "DataLoader", "LoaderBatch",
    "Predicate", "PrimitiveType", "ReaderOptions",
    "RemoteFatalError", "RemoteThrottledError", "RemoteTransientError",
    "SalvageReport",
    "SalvageSkip", "ScanOptions", "ScanReport", "DatasetScanner",
    "TpuRowGroupReader", "TruncatedFileError", "Type",
    "UnsupportedCodec", "UnsupportedFeatureError",
    "assemble_nested", "batch_to_arrow", "col", "data",
    "read_sharded_global", "register_codec", "scan", "scan_batches",
    "serve", "SharedBufferCache", "Serving",
    "shred_nested", "testing",
    "trace", "types", "ValueWriter", "WriterOptions",
]

_LAZY = {
    # the TPU engine (and jax with it) loads only on first use, keeping
    # plain format/API imports light; the fault-injection harness
    # (parquet_floor_tpu.testing) likewise loads only when asked for
    "TpuRowGroupReader": ("parquet_floor_tpu.tpu.engine", "TpuRowGroupReader"),
    "DeviceColumn": ("parquet_floor_tpu.tpu.engine", "DeviceColumn"),
    "read_sharded_global": (
        "parquet_floor_tpu.parallel.multihost", "read_sharded_global",
    ),
    "testing": ("parquet_floor_tpu.testing", None),
    # the scan scheduler (docs/scan.md) — lazy like the engine, so plain
    # format/API imports stay light
    "scan": ("parquet_floor_tpu.scan", None),
    "ScanOptions": ("parquet_floor_tpu.scan", "ScanOptions"),
    "ScanReport": ("parquet_floor_tpu.utils.trace", "ScanReport"),
    "DatasetScanner": ("parquet_floor_tpu.scan", "DatasetScanner"),
    "scan_batches": ("parquet_floor_tpu.scan", "scan_batches"),
    # the training input pipeline (docs/data.md) — lazy so that format/API
    # imports never pay for it (the device face pulls in jax on use only)
    "data": ("parquet_floor_tpu.data", None),
    "DataLoader": ("parquet_floor_tpu.data", "DataLoader"),
    "LoaderBatch": ("parquet_floor_tpu.data", "LoaderBatch"),
    # the multi-tenant serving layer (docs/serving.md) — lazy like scan
    "serve": ("parquet_floor_tpu.serve", None),
    "SharedBufferCache": ("parquet_floor_tpu.serve", "SharedBufferCache"),
    "Serving": ("parquet_floor_tpu.serve", "Serving"),
}


def __getattr__(name):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    module = importlib.import_module(target[0])
    value = module if target[1] is None else getattr(module, target[1])
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))
