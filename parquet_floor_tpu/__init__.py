"""parquet-floor-tpu: a TPU-native (JAX/XLA/Pallas) Parquet framework.

Brand-new implementation with the capability surface of the Java reference
``Pablete1234/parquet-floor`` (see SURVEY.md): a declarative
Hydrator/Dehydrator API over a from-scratch Parquet format engine, with the
columnar decode hot path offloaded to TPU kernels.
"""

from .format.schema import (
    ColumnDescriptor,
    GroupType,
    LogicalAnnotation,
    MessageType,
    PrimitiveType,
    types,
)
from .format.parquet_thrift import CompressionCodec, Encoding, Type
from .format.metadata import ParquetMetadata
from .format.file_read import ParquetFileReader
from .format.file_write import ColumnData, ParquetFileWriter, WriterOptions
from .api.hydrate import Dehydrator, Hydrator, HydratorSupplier, ValueWriter
from .api.reader import ParquetReader
from .api.writer import ParquetWriter
from .batch.nested import NestedColumn, assemble_nested, shred_nested
from .batch.predicate import Predicate, col
from .utils import trace

__version__ = "0.1.0"

__all__ = [
    "ColumnData", "ColumnDescriptor", "CompressionCodec", "Dehydrator",
    "Encoding", "GroupType", "Hydrator", "HydratorSupplier",
    "LogicalAnnotation", "MessageType", "NestedColumn", "ParquetFileReader",
    "ParquetFileWriter", "ParquetMetadata", "ParquetReader", "ParquetWriter",
    "Predicate", "PrimitiveType", "Type", "assemble_nested", "col",
    "shred_nested", "trace", "types", "ValueWriter", "WriterOptions",
]
