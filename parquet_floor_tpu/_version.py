"""Single source of the package version (imported by __init__ and by the
writer's created_by stamp without a circular import)."""

__version__ = "0.5.0"
