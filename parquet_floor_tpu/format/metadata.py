"""File metadata: footer parse/serialize + user-facing ParquetMetadata.

Parity with the metadata surface the reference exposes raw
(``ParquetReader.readMetadata`` at ``ParquetReader.java:109-117`` and
``metaData()`` at ``:229-231``): file-level schema, created_by, row groups,
column-chunk stats.

Layout (Parquet spec): ``PAR1 ... footer-thrift footer-len:u32le PAR1``.
"""

from __future__ import annotations

from typing import List, Optional

from ..errors import CorruptFooterError, classified_decode_errors
from ..io.source import FileSource
from .parquet_thrift import FileMetaData, RowGroup
from .schema import MessageType
from .thrift import CompactReader, CompactWriter, ThriftDecodeError

MAGIC = b"PAR1"
MAGIC_ENCRYPTED = b"PARE"
FOOTER_TAIL = 8  # u32 length + magic


class ParquetMetadata:
    """Parsed footer: raw thrift + derived schema tree."""

    __slots__ = ("file_meta", "schema")

    def __init__(self, file_meta: FileMetaData):
        self.file_meta = file_meta
        self.schema: MessageType = MessageType.from_thrift(file_meta.schema or [])

    @property
    def num_rows(self) -> int:
        return self.file_meta.num_rows or 0

    @property
    def created_by(self) -> Optional[str]:
        return self.file_meta.created_by

    @property
    def row_groups(self) -> List[RowGroup]:
        return self.file_meta.row_groups or []

    @property
    def key_value_metadata(self) -> dict:
        kvs = self.file_meta.key_value_metadata or []
        return {kv.key: kv.value for kv in kvs}

    def __repr__(self):
        return (
            f"ParquetMetadata(rows={self.num_rows}, "
            f"row_groups={len(self.row_groups)}, created_by={self.created_by!r})"
        )


def read_footer(source: FileSource) -> ParquetMetadata:
    path = getattr(source, "name", None)
    size = source.size
    if size < len(MAGIC) + FOOTER_TAIL:
        # CorruptFooterError, not TruncatedFileError: this is the
        # sniff-a-directory path and stays a ValueError, matching the
        # pre-taxonomy raise callers may already catch
        raise CorruptFooterError(
            f"not a parquet file: only {size} bytes "
            f"(a valid file is at least {len(MAGIC) + FOOTER_TAIL})",
            path=path,
        )
    head = bytes(source.read_at(0, 4))
    tail = bytes(source.read_at(size - FOOTER_TAIL, FOOTER_TAIL))
    if tail[4:] == MAGIC_ENCRYPTED:
        from ..errors import UnsupportedFeatureError

        raise UnsupportedFeatureError(
            "encrypted parquet files are not supported", path=path
        )
    if head != MAGIC or tail[4:] != MAGIC:
        raise CorruptFooterError("not a parquet file: bad magic", path=path)
    footer_len = int.from_bytes(tail[:4], "little")
    if footer_len + FOOTER_TAIL + len(MAGIC) > size:
        raise CorruptFooterError(
            f"corrupt footer length {footer_len} (file is {size} bytes)",
            path=path, offset=size - FOOTER_TAIL,
        )
    footer_start = size - FOOTER_TAIL - footer_len
    footer_bytes = source.read_at(footer_start, footer_len)
    # the shared ladder, with two footer-specific twists: hostile footer
    # bytes can trip ANY decoder invariant (recursion, index, type errors
    # deep in schema building), and ThriftDecodeError — the common
    # corrupt-footer outcome — is reclassified so `except
    # CorruptFooterError` sniff loops see ONE class (cause preserved)
    with classified_decode_errors(
        CorruptFooterError, "footer metadata does not parse",
        {"path": path, "offset": footer_start},
        reclassify=(ThriftDecodeError,),
    ):
        fm = FileMetaData.read(CompactReader(footer_bytes))
        return ParquetMetadata(fm)


def serialize_footer(file_meta: FileMetaData) -> bytes:
    w = CompactWriter()
    file_meta.write(w)
    body = w.getvalue()
    return body + len(body).to_bytes(4, "little") + MAGIC
