"""Page-level encode/decode: data pages V1 and V2, dictionary pages, and
definition/repetition level framing.

This is the core of L2 (SURVEY.md §1): the engine parquet-mr provides to the
reference behind ``readNextRowGroup`` (``ParquetReader.java:183``) and the v2
page writer behind the pinned ``PARQUET_2_0`` default
(``ParquetWriter.java:66``).  Pure host-side NumPy here; the TPU engine
consumes the same raw page payloads and runs the decode on device.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass
from typing import List, Optional, Union

import numpy as np

from ..errors import (
    ALLOC_CAP,
    ChecksumMismatchError,
    CorruptPageError,
    ParquetError,
    UnsupportedFeatureError,
    annotate,
    classified_decode_errors,
)
from . import codecs
from .encodings import plain as e_plain
from .encodings import rle_hybrid as e_rle
from .encodings import delta as e_delta
from .encodings import byte_stream_split as e_bss
from .encodings.dictionary import decode_dict_indices, gather
from .encodings.plain import ByteArrayColumn
from .parquet_thrift import (
    CompressionCodec,
    DataPageHeader,
    DataPageHeaderV2,
    DictionaryPageHeader,
    Encoding,
    PageHeader,
    PageType,
    Statistics,
    Type,
)
from .schema import ColumnDescriptor
from .thrift import CompactReader

try:
    from ..native import binding as _native
except Exception:  # pragma: no cover - native lib is optional
    _native = None


def _split_pages_native(chunk, num_values: int):
    """Build RawPage objects from the native header scan's slot table;
    returns ``(pages, payload_offsets)`` (offsets chunk-relative, for
    error context)."""
    tbl = _native.split_pages(chunk, num_values)
    mv = memoryview(chunk)
    pages: List[RawPage] = []
    offsets: List[int] = []
    for row in tbl:
        ptype = int(row[0])
        header = PageHeader(
            type=ptype,
            uncompressed_page_size=int(row[3]),
            compressed_page_size=int(row[2]),
            crc=int(row[4]) if row[15] > 0 else None,
        )
        if ptype == PageType.DATA_PAGE:
            header.data_page_header = DataPageHeader(
                num_values=int(row[5]),
                encoding=int(row[6]),
                definition_level_encoding=int(row[7]) if row[7] >= 0 else None,
                repetition_level_encoding=int(row[8]) if row[8] >= 0 else None,
            )
        elif ptype == PageType.DATA_PAGE_V2:
            header.data_page_header_v2 = DataPageHeaderV2(
                num_values=int(row[5]),
                num_nulls=int(row[9]) if row[9] >= 0 else None,
                num_rows=int(row[13]) if row[13] >= 0 else None,
                encoding=int(row[6]),
                definition_levels_byte_length=int(row[10]) if row[10] >= 0 else None,
                repetition_levels_byte_length=int(row[11]) if row[11] >= 0 else None,
                is_compressed=None if row[12] < 0 else bool(row[12]),
            )
        elif ptype == PageType.DICTIONARY_PAGE:
            header.dictionary_page_header = DictionaryPageHeader(
                num_values=int(row[13]) if row[13] >= 0 else None,
                encoding=int(row[14]) if row[14] >= 0 else None,
            )
        off, size = int(row[1]), int(row[2])
        # zero-copy: a view into the chunk buffer (kept alive by the
        # page's reference; staging consumes pages while the source is
        # open, and every consumer takes buffers, not bytes).  The
        # page's header starts where the previous payload ended.
        start = pages[-1].end if pages else 0
        pages.append(RawPage(header, mv[off : off + size], start, off + size))
        offsets.append(off)
    return pages, offsets

_NUMPY_DTYPE = {
    Type.INT32: np.dtype("<i4"),
    Type.INT64: np.dtype("<i8"),
    Type.FLOAT: np.dtype("<f4"),
    Type.DOUBLE: np.dtype("<f8"),
}


@dataclass
class RawPage:
    """A parsed page header + its (still compressed) payload bytes.

    ``payload`` may be a zero-copy memoryview into the column-chunk
    buffer — consume it while the source is open (mmap-backed).

    ``start``/``end`` are the page's chunk-relative byte span (header
    through payload, ``end`` exclusive) when the parser knows it — the
    quarantine map records it so a later scan can skip a known-bad
    page's bytes without re-reading them (docs/robustness.md)."""

    header: PageHeader
    payload: Union[bytes, memoryview]  # compressed_page_size bytes
    start: Optional[int] = None        # chunk-relative header offset
    end: Optional[int] = None          # chunk-relative payload end

    @property
    def page_type(self) -> int:
        return self.header.type


# the format stores page sizes as i32: anything past this ceiling is a
# corrupt header, and refusing it here keeps a flipped size bit from
# turning into a multi-GiB allocation attempt downstream
_PAGE_SIZE_CAP = ALLOC_CAP


def _check_page_sizes(header: PageHeader, ctx: Optional[dict],
                      ordinal: Optional[int],
                      err_off: Optional[int] = None) -> None:
    """Reject sizes outside the format's i32 range — shared by the
    Python parser AND the native fast path (whose C scanner bounds the
    compressed size against the buffer but never checks the declared
    uncompressed size, the one that drives decompress allocation)."""
    size = header.compressed_page_size
    if size is None or size < 0 or size >= _PAGE_SIZE_CAP:
        raise CorruptPageError(
            f"page header declares invalid compressed size {size}",
            page=ordinal, offset=err_off, **(ctx or {}),
        )
    usize = header.uncompressed_page_size
    if usize is not None and (usize < 0 or usize >= _PAGE_SIZE_CAP):
        raise CorruptPageError(
            f"page header declares invalid uncompressed size {usize}",
            page=ordinal, offset=err_off, **(ctx or {}),
        )


def parse_page_at(buf, pos: int, ctx: Optional[dict] = None,
                  ordinal: Optional[int] = None,
                  offset_base: Optional[int] = None):
    """Parse ONE page (header + still-compressed payload) at ``buf[pos]``;
    returns ``(RawPage, end_pos)``.  The single framing validator shared
    by the chunk scan (:func:`split_pages`) and the ranged-read path
    (``ParquetFileReader._read_raw_page``) — framing rules live here
    once.  ``offset_base`` is the absolute file offset of ``buf[0]`` for
    error context."""
    err_off = pos if offset_base is None else offset_base + pos
    reader = CompactReader(buf, pos)
    try:
        header = PageHeader.read(reader)
    except ParquetError as e:
        raise annotate(e, page=ordinal, offset=err_off, **(ctx or {}))
    _check_page_sizes(header, ctx, ordinal, err_off)
    size = header.compressed_page_size
    payload = bytes(buf[reader.pos : reader.pos + size])
    if len(payload) != size:
        raise CorruptPageError(
            f"page payload truncated: header said {size} bytes, "
            f"buffer holds {len(payload)}",
            page=ordinal, offset=err_off, **(ctx or {}),
        )
    return RawPage(header, payload, pos, reader.pos + size), reader.pos + size


def split_pages(chunk: bytes, num_values: int, ctx: Optional[dict] = None,
                offset_base: Optional[int] = None) -> List[RawPage]:
    """Scan a column chunk byte range into raw pages (header parse only).

    Native single-pass scan when the library is built (the Thrift header
    chain is the staging loop's hottest pure-Python cost); exact Python
    fallback below.  ``ctx`` (path/column/row_group) contextualizes the
    :class:`CorruptPageError` raised on bad framing; ``offset_base`` (the
    chunk's absolute file offset) makes those errors name absolute byte
    offsets, like every other taxonomy raise site."""
    if _native is not None and _native.available():
        native = None
        try:
            native = _split_pages_native(chunk, num_values)
        except ValueError:
            pass  # malformed per the native parser: let Python diagnose
        if native is not None:
            native_pages, offsets = native
            for i, (p, off) in enumerate(zip(native_pages, offsets)):
                _check_page_sizes(
                    p.header, ctx, i,
                    off if offset_base is None else offset_base + off,
                )
            return native_pages
    pages: List[RawPage] = []
    pos = 0
    end = len(chunk)
    seen_values = 0
    while seen_values < num_values and pos < end:
        page_start = pos if offset_base is None else offset_base + pos
        page, pos = parse_page_at(chunk, pos, ctx, len(pages), offset_base)
        pages.append(page)
        header = page.header
        sub = None
        if header.type == PageType.DATA_PAGE:
            sub = header.data_page_header
        elif header.type == PageType.DATA_PAGE_V2:
            sub = header.data_page_header_v2
        if header.type in (PageType.DATA_PAGE, PageType.DATA_PAGE_V2):
            if sub is None or sub.num_values is None:
                raise CorruptPageError(
                    "data page header is missing its num_values",
                    page=len(pages) - 1, offset=page_start, **(ctx or {}),
                )
            seen_values += sub.num_values
    return pages


@dataclass
class DecodedPage:
    """One data page after decode.

    ``values`` holds only the non-null (def == max_def) values, in page
    order; ``def_levels``/``rep_levels`` are None for required/flat columns.
    """

    num_values: int
    values: Union[np.ndarray, ByteArrayColumn]
    def_levels: Optional[np.ndarray]
    rep_levels: Optional[np.ndarray]


def _verify_crc(header: PageHeader, payload: bytes, verify: bool,
                ctx: Optional[dict] = None) -> None:
    """CRC32 the payload against the page header's stamp (when present and
    verification is on — ``ReaderOptions(verify_crc=True)``)."""
    if verify and header.crc is not None:
        actual = zlib.crc32(payload) & 0xFFFFFFFF
        expected = header.crc & 0xFFFFFFFF
        if actual != expected:
            raise ChecksumMismatchError(
                f"page CRC mismatch: computed {actual:#010x}, "
                f"header says {expected:#010x}",
                expected_crc=expected, actual_crc=actual, **(ctx or {}),
            )


def decode_dictionary_page(
    page: RawPage, column: ColumnDescriptor, codec: int, verify_crc: bool = False,
    ctx: Optional[dict] = None,
):
    # hostile payload bytes can trip any decoder invariant; the shared
    # ladder turns every such path into annotated taxonomy, never a raw
    # IndexError deep in an encoding
    with classified_decode_errors(CorruptPageError,
                                  "dictionary page decode failed", ctx):
        dh: DictionaryPageHeader = page.header.dictionary_page_header
        if dh is None:
            raise CorruptPageError("dictionary page without its header struct")
        enc = dh.encoding if dh.encoding is not None else Encoding.PLAIN
        if enc not in (Encoding.PLAIN, Encoding.PLAIN_DICTIONARY):
            raise UnsupportedFeatureError(
                f"unsupported dictionary page encoding {Encoding.name(enc)}"
            )
        _verify_crc(page.header, page.payload, verify_crc)
        data = codecs.decompress(codec, page.payload, page.header.uncompressed_page_size)
        values, _ = e_plain.decode_plain(
            data, dh.num_values, column.physical_type, column.type_length
        )
        return values


def _decode_values(
    data,
    pos: int,
    encoding: int,
    n: int,
    column: ColumnDescriptor,
    dictionary,
):
    """Decode ``n`` leaf values with the page's value encoding."""
    pt = column.physical_type
    if encoding in (Encoding.RLE_DICTIONARY, Encoding.PLAIN_DICTIONARY):
        if dictionary is None:
            raise CorruptPageError(
                "dictionary-encoded page but no dictionary page seen"
            )
        indices, _ = decode_dict_indices(data, n, pos)
        if np.any(indices >= _dict_len(dictionary)):
            raise CorruptPageError("dictionary index out of range")
        return gather(dictionary, indices)
    if encoding == Encoding.PLAIN:
        values, _ = e_plain.decode_plain(data, n, pt, column.type_length, offset=pos)
        return values
    if encoding == Encoding.RLE:
        # RLE-encoded BOOLEAN values (v2 writers); framed with u32 length.
        if pt != Type.BOOLEAN:
            raise CorruptPageError("RLE value encoding only defined for BOOLEAN")
        values, _ = e_rle.decode_length_prefixed(data, n, 1, pos)
        return values.astype(np.bool_)
    if encoding == Encoding.DELTA_BINARY_PACKED:
        if pt == Type.INT32:
            values, _ = e_delta.decode_delta_binary_packed(data, pos, out_dtype=np.int32)
        elif pt == Type.INT64:
            values, _ = e_delta.decode_delta_binary_packed(data, pos, out_dtype=np.int64)
        else:
            raise CorruptPageError("DELTA_BINARY_PACKED only valid for INT32/INT64")
        if len(values) < n:
            raise CorruptPageError("DELTA_BINARY_PACKED produced too few values")
        return values[:n]
    if encoding == Encoding.DELTA_LENGTH_BYTE_ARRAY:
        values, _ = e_delta.decode_delta_length_byte_array(data, pos)
        return values
    if encoding == Encoding.DELTA_BYTE_ARRAY:
        values, _ = e_delta.decode_delta_byte_array(data, pos)
        return values
    if encoding == Encoding.BYTE_STREAM_SPLIT:
        if pt in _NUMPY_DTYPE:
            return e_bss.decode_byte_stream_split(data, n, _NUMPY_DTYPE[pt], pos)
        raise UnsupportedFeatureError(
            "BYTE_STREAM_SPLIT only supported for fixed-width types here"
        )
    raise UnsupportedFeatureError(
        f"unsupported value encoding {Encoding.name(encoding)}"
    )


def _dict_len(dictionary) -> int:
    return len(dictionary)


def decode_data_page_v1(
    page: RawPage,
    column: ColumnDescriptor,
    codec: int,
    dictionary,
    verify_crc: bool = False,
    ctx: Optional[dict] = None,
) -> DecodedPage:
    h: DataPageHeader = page.header.data_page_header
    if h is None:
        raise CorruptPageError("v1 data page without its header struct",
                               **(ctx or {}))
    n = h.num_values
    _verify_crc(page.header, page.payload, verify_crc, ctx)
    data = codecs.decompress(codec, page.payload, page.header.uncompressed_page_size)
    pos = 0
    rep_levels = None
    def_levels = None
    def _levels(enc, max_level, what):
        nonlocal pos
        bw = e_rle.min_bit_width(max_level)
        if enc in (Encoding.RLE, None):
            levels, pos = e_rle.decode_length_prefixed(data, n, bw, pos)
        elif enc == Encoding.BIT_PACKED:  # deprecated legacy encoding
            levels, pos = e_rle.decode_bit_packed_legacy(data, n, bw, pos)
        else:
            raise UnsupportedFeatureError(
                f"unsupported {what} level encoding {Encoding.name(enc)}"
            )
        return levels

    if column.max_repetition_level > 0:
        rep_levels = _levels(
            h.repetition_level_encoding, column.max_repetition_level,
            "repetition",
        )
    if column.max_definition_level > 0:
        def_levels = _levels(
            h.definition_level_encoding, column.max_definition_level,
            "definition",
        )
        n_non_null = int(np.count_nonzero(def_levels == column.max_definition_level))
    else:
        n_non_null = n
    values = _decode_values(data, pos, h.encoding, n_non_null, column, dictionary)
    return DecodedPage(n, values, def_levels, rep_levels)


def decode_data_page_v2(
    page: RawPage,
    column: ColumnDescriptor,
    codec: int,
    dictionary,
    verify_crc: bool = False,
    ctx: Optional[dict] = None,
) -> DecodedPage:
    h: DataPageHeaderV2 = page.header.data_page_header_v2
    if h is None:
        raise CorruptPageError("v2 data page without its header struct",
                               **(ctx or {}))
    n = h.num_values
    _verify_crc(page.header, page.payload, verify_crc, ctx)
    rl_len = h.repetition_levels_byte_length or 0
    dl_len = h.definition_levels_byte_length or 0
    payload = page.payload
    rep_levels = None
    def_levels = None
    pos = 0
    # The v2 header's geometry fields (level byte lengths, num_nulls,
    # num_rows) live OUTSIDE the payload CRC: a flipped bit there would
    # silently shift the value region and decode garbage as data.  Every
    # claim is therefore cross-checked against what actually decodes —
    # disagreement is corruption, never a judgment call.
    if column.max_repetition_level > 0:
        bw = e_rle.min_bit_width(column.max_repetition_level)
        rep_levels, rend = e_rle.decode_rle_hybrid(payload, n, bw, pos)
        if rend - pos > rl_len:
            raise CorruptPageError(
                f"v2 repetition levels consumed {rend - pos} bytes but "
                f"the header declares {rl_len}", **(ctx or {}),
            )
    elif column.max_repetition_level == 0 and h.num_rows is not None \
            and h.num_rows != n:
        raise CorruptPageError(
            f"v2 header claims {h.num_rows} rows but {n} values on a "
            "flat column", **(ctx or {}),
        )
    pos += rl_len
    if column.max_definition_level > 0:
        bw = e_rle.min_bit_width(column.max_definition_level)
        def_levels, dend = e_rle.decode_rle_hybrid(payload, n, bw, pos)
        if dend - pos > dl_len:
            raise CorruptPageError(
                f"v2 definition levels consumed {dend - pos} bytes but "
                f"the header declares {dl_len}", **(ctx or {}),
            )
        n_non_null = int(np.count_nonzero(def_levels == column.max_definition_level))
        if h.num_nulls is not None and h.num_nulls != n - n_non_null:
            raise CorruptPageError(
                f"v2 header claims {h.num_nulls} nulls but the "
                f"definition levels encode {n - n_non_null}",
                **(ctx or {}),
            )
    else:
        n_non_null = n
        if h.num_nulls:
            raise CorruptPageError(
                f"v2 header claims {h.num_nulls} nulls on a REQUIRED "
                "column", **(ctx or {}),
            )
    pos += dl_len
    body = payload[pos:]
    expected = page.header.uncompressed_page_size - rl_len - dl_len
    if expected < 0:
        raise CorruptPageError(
            "v2 level byte lengths exceed the page size", **(ctx or {}),
        )
    # is_compressed defaults true when the chunk codec is not UNCOMPRESSED
    compressed = h.is_compressed if h.is_compressed is not None else True
    if compressed and codec != CompressionCodec.UNCOMPRESSED:
        body = codecs.decompress(codec, body, expected)
    elif len(body) != expected:
        raise CorruptPageError(
            f"v2 value region holds {len(body)} bytes but the header "
            f"geometry implies {expected}", **(ctx or {}),
        )
    values = _decode_values(body, 0, h.encoding, n_non_null, column, dictionary)
    return DecodedPage(n, values, def_levels, rep_levels)


def decode_data_page(
    page: RawPage, column: ColumnDescriptor, codec: int, dictionary,
    verify_crc: bool = False, ctx: Optional[dict] = None,
) -> DecodedPage:
    """Decode one data page (v1 or v2) into a :class:`DecodedPage`.

    Every failure mode surfaces as taxonomy (``ctx`` supplies file/column/
    row-group/page location): :class:`ChecksumMismatchError` when a CRC
    disagrees, :class:`UnsupportedFeatureError` for encodings this engine
    lacks, :class:`CorruptPageError` for everything hostile bytes can trip
    — including non-ValueError crashes deep inside an encoding decoder.
    """
    with classified_decode_errors(CorruptPageError,
                                  "data page decode failed", ctx):
        if page.page_type == PageType.DATA_PAGE:
            return decode_data_page_v1(page, column, codec, dictionary,
                                       verify_crc, ctx)
        if page.page_type == PageType.DATA_PAGE_V2:
            return decode_data_page_v2(page, column, codec, dictionary,
                                       verify_crc, ctx)
        raise CorruptPageError(f"not a data page: type {page.page_type}")


# ---------------------------------------------------------------------------
# Page encoding (write path)
# ---------------------------------------------------------------------------

@dataclass
class EncodedPage:
    header: PageHeader
    body: bytes  # compressed payload as it will land in the file
    _header_bytes: "bytes | None" = None

    def header_bytes(self) -> bytes:
        """The serialized header, thrift-encoded ONCE (headers are
        immutable after encoding — offsets live in the footer/indexes,
        never in page headers — so the write path's size accounting and
        the ordered sink emission share one serialization)."""
        if self._header_bytes is None:
            self._header_bytes = self.header.to_bytes()
        return self._header_bytes

    @property
    def total_size(self) -> int:
        return len(self.header_bytes()) + len(self.body)


def encode_dictionary_page(
    dictionary, column: ColumnDescriptor, codec: int, with_crc: bool = True,
    codec_level: "int | None" = None,
) -> EncodedPage:
    raw = e_plain.encode_plain(dictionary, column.physical_type, column.type_length)
    body = codecs.compress(codec, raw, codec_level)
    header = PageHeader(
        type=PageType.DICTIONARY_PAGE,
        uncompressed_page_size=len(raw),
        compressed_page_size=len(body),
        dictionary_page_header=DictionaryPageHeader(
            num_values=_dict_len(dictionary), encoding=Encoding.PLAIN
        ),
    )
    if with_crc:
        header.crc = _signed_crc(body)
    return EncodedPage(header, body)


def _signed_crc(data: bytes) -> int:
    crc = zlib.crc32(data) & 0xFFFFFFFF
    return crc - (1 << 32) if crc >= (1 << 31) else crc


def encode_data_page_v2(
    column: ColumnDescriptor,
    codec: int,
    num_rows: int,
    encoding: int,
    encoded_values: bytes,
    def_levels: Optional[np.ndarray],
    rep_levels: Optional[np.ndarray],
    statistics: Optional[Statistics] = None,
    with_crc: bool = True,
    codec_level: Optional[int] = None,
) -> EncodedPage:
    """Encode one v2 data page.  Levels stay uncompressed (spec)."""
    if rep_levels is not None and column.max_repetition_level > 0:
        n = len(rep_levels)
        rl = e_rle.encode_rle_hybrid(
            rep_levels, e_rle.min_bit_width(column.max_repetition_level)
        )
    else:
        n = num_rows if def_levels is None else len(def_levels)
        rl = b""
    if def_levels is not None and column.max_definition_level > 0:
        dl = e_rle.encode_rle_hybrid(
            def_levels, e_rle.min_bit_width(column.max_definition_level)
        )
        num_nulls = int(np.count_nonzero(def_levels != column.max_definition_level))
    else:
        dl = b""
        num_nulls = 0
    body_comp = codecs.compress(codec, encoded_values, codec_level)
    if len(body_comp) >= len(encoded_values):
        body_comp = encoded_values
        is_compressed = False
    else:
        is_compressed = codec != CompressionCodec.UNCOMPRESSED
    full_body = rl + dl + body_comp
    header = PageHeader(
        type=PageType.DATA_PAGE_V2,
        uncompressed_page_size=len(rl) + len(dl) + len(encoded_values),
        compressed_page_size=len(full_body),
        data_page_header_v2=DataPageHeaderV2(
            num_values=n,
            num_nulls=num_nulls,
            num_rows=num_rows,
            encoding=encoding,
            definition_levels_byte_length=len(dl),
            repetition_levels_byte_length=len(rl),
            is_compressed=is_compressed,
            statistics=statistics,
        ),
    )
    if with_crc:
        header.crc = _signed_crc(full_body)
    return EncodedPage(header, full_body)


def encode_data_page_v1(
    column: ColumnDescriptor,
    codec: int,
    encoding: int,
    encoded_values: bytes,
    def_levels: Optional[np.ndarray],
    rep_levels: Optional[np.ndarray],
    statistics: Optional[Statistics] = None,
    with_crc: bool = True,
    num_values: Optional[int] = None,
    codec_level: Optional[int] = None,
) -> EncodedPage:
    parts = []
    n = num_values
    if rep_levels is not None and column.max_repetition_level > 0:
        n = len(rep_levels)
        parts.append(
            e_rle.encode_length_prefixed(
                rep_levels, e_rle.min_bit_width(column.max_repetition_level)
            )
        )
    if def_levels is not None and column.max_definition_level > 0:
        if n is None:
            n = len(def_levels)
        parts.append(
            e_rle.encode_length_prefixed(
                def_levels, e_rle.min_bit_width(column.max_definition_level)
            )
        )
    parts.append(encoded_values)
    raw = b"".join(parts)
    if n is None:
        raise ValueError("v1 page needs num_values via levels or caller")
    body = codecs.compress(codec, raw, codec_level)
    header = PageHeader(
        type=PageType.DATA_PAGE,
        uncompressed_page_size=len(raw),
        compressed_page_size=len(body),
        data_page_header=DataPageHeader(
            num_values=n,
            encoding=encoding,
            definition_level_encoding=Encoding.RLE,
            repetition_level_encoding=Encoding.RLE,
            statistics=statistics,
        ),
    )
    if with_crc:
        header.crc = _signed_crc(body)
    return EncodedPage(header, body)
