"""LZO page decode via the SYSTEM liblzo2, loaded with ctypes — the same
native-library-behind-a-seam architecture the reference uses for all its
codecs (JNI-wrapped native libs instantiated reflectively,
``ReflectionUtils.java:10-21``; an LZO codec class must likewise be on
its classpath at runtime or the reference fails too).

LZO itself is GPL-licensed upstream, so no implementation is vendored:
when ``liblzo2`` is present on the system this module binds
``lzo1x_decompress_safe`` (and ``lzo1x_1_compress`` for the write side)
and the codec registry routes ``CompressionCodec.LZO`` through it; when
absent, the registry keeps raising ``UnsupportedCodec`` with guidance
(parity with the reference's runtime ClassNotFound behavior).

Framing: parquet-mr's LZO pages use Hadoop's BlockCompressorStream
records — ``[uncompressed_len u32be][compressed_len u32be][raw LZO
block]``, where one record may carry several inner ``[clen][block]``
chunks (the same framing as the legacy LZ4 codec, ``codecs.py``).
"""

from __future__ import annotations

import ctypes
import ctypes.util
from typing import Callable, Optional

from ..errors import checked_alloc_size

_lzo = None
_loaded = False

# lzo1x_1_compress needs a work buffer of LZO1X_1_MEM_COMPRESS bytes
# (16384 * sizeof(void*) on 64-bit = 131072; over-allocate generously)
_WRKMEM = 1 << 18


def _load() -> None:
    global _lzo, _loaded
    if _loaded:
        return
    _loaded = True
    for name in ("lzo2", "liblzo2.so.2", "liblzo2.so"):
        path = ctypes.util.find_library(name) if "." not in name else name
        if path is None:
            continue
        try:
            lib = ctypes.CDLL(path)
            lib.lzo1x_decompress_safe.restype = ctypes.c_int
            lib.lzo1x_decompress_safe.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t,
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_size_t),
                ctypes.c_void_p,
            ]
            lib.lzo1x_1_compress.restype = ctypes.c_int
            lib.lzo1x_1_compress.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t,
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_size_t),
                ctypes.c_char_p,
            ]
        except (OSError, AttributeError):
            continue
        _lzo = lib
        break


def available() -> bool:
    """True when the system liblzo2 loaded."""
    _load()
    return _lzo is not None


def _block_decompress(data: bytes, cap: int) -> bytes:
    """One raw LZO1X block of size ≤ cap (the *_safe* variant takes the
    output CAPACITY and reports the actual decompressed length)."""
    _load()
    if _lzo is None:
        raise RuntimeError("liblzo2 not found")
    bcap = checked_alloc_size(cap, "LZO block output cap")
    out = ctypes.create_string_buffer(max(bcap, 1))
    n = ctypes.c_size_t(bcap)
    rc = _lzo.lzo1x_decompress_safe(
        bytes(data), len(data), out, ctypes.byref(n), None
    )
    if rc != 0:
        raise ValueError(f"invalid LZO block (rc={rc})")
    return out.raw[: n.value]


def _block_compress(data: bytes) -> bytes:
    _load()
    if _lzo is None:
        raise RuntimeError("liblzo2 not found")
    cap = len(data) + len(data) // 16 + 64 + 3  # LZO worst case
    out = ctypes.create_string_buffer(cap)
    n = ctypes.c_size_t(cap)
    wrk = ctypes.create_string_buffer(_WRKMEM)
    rc = _lzo.lzo1x_1_compress(
        bytes(data), len(data), out, ctypes.byref(n), wrk
    )
    if rc != 0:
        raise ValueError(f"lzo1x_1_compress failed (rc={rc})")
    return out.raw[: n.value]


def hadoop_decompress(
    data: bytes, uncompressed_size: Optional[int] = None,
    block_decompress: Optional[Callable[[bytes, int], bytes]] = None,
) -> bytes:
    """Walk Hadoop BlockCompressorStream records and decode every inner
    LZO block.  ``block_decompress`` is injectable so the framing walk is
    testable without liblzo2 on the machine."""
    dec = block_decompress or _block_decompress
    n = len(data)
    out = bytearray()
    pos = 0
    while pos < n:
        if pos + 4 > n:
            raise ValueError("LZO stream truncated in record header")
        ulen = int.from_bytes(data[pos : pos + 4], "big")
        pos += 4
        if ulen > (1 << 31):
            raise ValueError("LZO record claims > 2 GiB")
        # bound the CUMULATIVE output before decoding the record, not
        # just each record's claim: a hostile multi-record page must not
        # allocate past the declared page size before the final length
        # check fires (same amplification bound as the brotli ladder)
        if uncompressed_size is not None and len(out) + ulen > uncompressed_size:
            raise ValueError(
                f"LZO records claim more than the declared "
                f"{uncompressed_size}-byte page"
            )
        if uncompressed_size is None and len(out) + ulen > (1 << 31):
            raise ValueError("LZO stream total claims > 2 GiB")
        produced = 0
        while produced < ulen:
            if pos + 4 > n:
                raise ValueError("LZO stream truncated in block header")
            clen = int.from_bytes(data[pos : pos + 4], "big")
            pos += 4
            if clen <= 0 or pos + clen > n:
                raise ValueError("LZO block overruns the stream")
            block = dec(data[pos : pos + clen], ulen - produced)
            pos += clen
            produced += len(block)
            out += block
            if not block:
                raise ValueError("empty LZO block")
        if produced != ulen:
            raise ValueError(
                f"LZO record produced {produced} bytes, header said {ulen}"
            )
    if uncompressed_size is not None and len(out) != uncompressed_size:
        raise ValueError(
            f"LZO page decoded to {len(out)} bytes, footer said "
            f"{uncompressed_size}"
        )
    return bytes(out)


def hadoop_compress(data: bytes) -> bytes:
    """One Hadoop record: [ulen][clen][block] (write-side convenience,
    mirroring the LZ4 legacy framing's single-record form).  Empty input
    is a bare zero-length record — no inner block, matching the
    decoder's ulen==0 handling (an inner block would be re-read as the
    next record's header)."""
    if not data:
        return (0).to_bytes(4, "big")
    block = _block_compress(data)
    return (
        len(data).to_bytes(4, "big")
        + len(block).to_bytes(4, "big")
        + block
    )
