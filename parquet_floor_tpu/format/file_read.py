"""ParquetFileReader: the from-scratch engine replacing parquet-mr's
``ParquetFileReader.open/getFooter/readNextRowGroup/getRecordCount``
(reference call sites ``ParquetReader.java:114-120,183,221``).

Row-group streaming (one group materialized at a time — parity with the
reference's lazy ``tryAdvance`` pull at ``ParquetReader.java:182-194``), but
each group decodes **columnar**: all pages of a chunk decode into arrays in
one pass instead of per-cell virtual dispatch.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, List, Optional, Set

import numpy as np

from ..batch.columns import ColumnBatch, RowGroupBatch
from ..errors import (
    CorruptFooterError,
    CorruptPageError,
    TruncatedFileError,
    UnsupportedFeatureError,
    checked_alloc_size,
    classified_decode_errors,
)
from ..io.source import FileSource, RetryingSource
from ..utils import trace
from . import pages as pg
from .encodings.plain import ByteArrayColumn
from .metadata import ParquetMetadata, read_footer
from .parquet_thrift import ColumnChunk, ColumnMetaData, PageType, RowGroup
from .schema import ColumnDescriptor
from .thrift import ThriftDecodeError


@dataclass
class ReaderOptions:
    """Read-side configuration — the explicit read twin of
    ``WriterOptions`` (SURVEY.md §5's explicit-config stance).

    * ``verify_crc`` — CRC32-check every page payload against the header
      stamp before decode.  Off by default (parity with parquet-mr's
      default); turn it on for storage you do not trust — it is the only
      way a bit flip inside a compressed payload is *guaranteed* to be
      detected rather than surfacing as a downstream decode error (or,
      for UNCOMPRESSED pages, silent wrong data).
    * ``salvage`` — quarantine corrupt pages/chunks instead of aborting
      the whole file; see :class:`SalvageReport`.  Strict (off) is the
      default and behaves byte-identically to a reader without the flag.
    * ``io_retries`` — bounded retry-with-backoff for *transient*
      ``OSError`` reads (flaky NFS/FUSE/object-store mounts).  0 (off) by
      default; deterministic errors (truncation, parse) never retry.
    * ``io_retry_backoff_s`` — first backoff sleep; doubles per attempt.
    """

    verify_crc: bool = False
    salvage: bool = False
    io_retries: int = 0
    io_retry_backoff_s: float = 0.05

    def __post_init__(self):
        # fail-fast: a bad retry config must error here, not silently
        # become "no retries"
        if self.io_retries < 0:
            raise ValueError(f"io_retries must be >= 0, got {self.io_retries}")
        if self.io_retry_backoff_s < 0:
            raise ValueError(
                f"io_retry_backoff_s must be >= 0, got {self.io_retry_backoff_s}"
            )


@dataclass
class SalvageSkip:
    """One quarantined unit (a page, or a whole column chunk when
    ``page`` is None) recorded by salvage mode."""

    column: str
    row_group: Optional[int]
    page: Optional[int]  # ordinal within the chunk; None = whole chunk
    rows: int            # value slots lost (rows, for flat columns)
    error: str
    path: Optional[str] = None


@dataclass
class SalvageReport:
    """What salvage mode recovered and what it had to give up.

    Counters are in *column-rows* (value slots: one per row per column;
    equal to rows for flat columns).  A page skip nulls the page's rows
    in an OPTIONAL flat column (rows survive as nulls, counted
    quarantined); a chunk quarantine drops that column for the whole row
    group (other columns still decode).  ``first_errors`` maps each
    damaged column to the first error seen on it.
    """

    pages_read: int = 0
    pages_skipped: int = 0
    chunks_quarantined: int = 0
    rows_recovered: int = 0
    rows_quarantined: int = 0
    skips: List[SalvageSkip] = field(default_factory=list)
    # (column, row_group) chunks already accounted — decode is
    # deterministic, so re-decoding a group (restore(), repeated
    # read_row_group) must not double-count its losses or recoveries
    _counted: set = field(default_factory=set, repr=False, compare=False)

    def _first_count(self, column: str, row_group, kind: str) -> bool:
        """True exactly once per (kind, column, row_group); callers skip
        accounting on repeats.  ``kind`` separates successful-decode
        accounting ("ok") from quarantine accounting ("q"): a chunk that
        decoded fine once but fails on a LATER re-read (flaky storage, a
        file changing underneath) must still get its quarantine record —
        every omission has a report entry.  An unknown group (direct
        ``read_column_chunk`` calls with no index) always counts — keys
        from different groups would collide at None, and unreported loss
        is worse than a possible double-count on re-decode."""
        if row_group is None:
            return True
        key = (kind, column, row_group)
        if key in self._counted:
            return False
        self._counted.add(key)
        return True

    @property
    def first_errors(self) -> dict:
        out: dict = {}
        for s in self.skips:
            out.setdefault(s.column, s.error)
        return out

    def summary(self) -> dict:
        return {
            "pages_read": self.pages_read,
            "pages_skipped": self.pages_skipped,
            "chunks_quarantined": self.chunks_quarantined,
            "rows_recovered": self.rows_recovered,
            "rows_quarantined": self.rows_quarantined,
            "first_errors": self.first_errors,
        }


# What salvage mode may quarantine: damaged pages/chunks and reads past
# the physical end.  UnsupportedFeatureError is NOT here on purpose — a
# missing capability is a fact about this engine, not the file, and
# silently dropping such columns would misreport healthy data as damaged.
_SALVAGEABLE = (CorruptPageError, TruncatedFileError, ThriftDecodeError)


def _chunk_byte_range(meta: ColumnMetaData):
    start = meta.data_page_offset
    if meta.dictionary_page_offset is not None and meta.dictionary_page_offset > 0:
        start = min(start, meta.dictionary_page_offset)
    return start, meta.total_compressed_size


def _empty_values(desc: ColumnDescriptor):
    """Typed empty value container for a zero-value chunk."""
    from .parquet_thrift import Type as _T

    pt = desc.physical_type
    if pt == _T.BYTE_ARRAY:
        return ByteArrayColumn(np.zeros(1, np.int64), np.zeros(0, np.uint8))
    if pt == _T.BOOLEAN:
        return np.zeros(0, np.bool_)
    if pt == _T.INT32:
        return np.zeros(0, np.int32)
    if pt == _T.INT64:
        return np.zeros(0, np.int64)
    if pt == _T.FLOAT:
        return np.zeros(0, np.float32)
    if pt == _T.DOUBLE:
        return np.zeros(0, np.float64)
    width = desc.type_length if pt == _T.FIXED_LEN_BYTE_ARRAY else 12
    return np.zeros((0, width), np.uint8)


def _page_num_values(page: "pg.RawPage") -> Optional[int]:
    """The value count a data page's header declares, or None when the
    header lacks it (then the page cannot be null-substituted)."""
    h = page.header
    if page.page_type == PageType.DATA_PAGE and h.data_page_header is not None:
        return h.data_page_header.num_values
    if (
        page.page_type == PageType.DATA_PAGE_V2
        and h.data_page_header_v2 is not None
    ):
        return h.data_page_header_v2.num_values
    return None


def _concat_values(parts):
    if not parts:
        raise ValueError("no pages decoded")
    if len(parts) == 1:
        return parts[0]
    if isinstance(parts[0], ByteArrayColumn):
        pools = [p.data for p in parts]
        offs = [parts[0].offsets]
        base = parts[0].offsets[-1]
        for p in parts[1:]:
            offs.append(p.offsets[1:] + base)
            base = base + p.offsets[-1]
        return ByteArrayColumn(np.concatenate(offs), np.concatenate(pools))
    return np.concatenate(parts)


class ParquetFileReader:
    """Open a parquet file, expose footer + per-row-group columnar decode.

    ``options`` (a :class:`ReaderOptions`) is the full read-side config;
    ``verify_crc``/``salvage`` remain as positional shorthands, and a
    truthy shorthand folds into ``options`` when both are given (asking
    for CRC verification is never silently undone by also passing
    options).  With ``salvage=True`` the reader
    quarantines corrupt pages/row-group chunks instead of aborting (see
    :class:`SalvageReport`, exposed as ``self.salvage_report``); strict
    mode — the default — fails loudly on the first damaged byte.
    """

    def __init__(self, source, verify_crc: bool = False,
                 salvage: bool = False,
                 options: Optional[ReaderOptions] = None,
                 metadata: Optional[ParquetMetadata] = None):
        """``metadata``: a pre-parsed footer for THIS file, reused
        instead of re-reading and re-parsing it — how multi-epoch
        loaders re-open dataset files cheaply (the thrift footer parse
        dominates a warm re-open).  The caller owns the claim that it
        matches the source; nothing re-validates it here."""
        if options is None:
            opts = ReaderOptions(verify_crc=verify_crc, salvage=salvage)
        elif verify_crc or salvage:
            # fold truthy shorthands into the caller's options instead of
            # silently dropping them: verify_crc=True must never be
            # disabled by merely ALSO passing options=ReaderOptions(...)
            from dataclasses import replace

            opts = replace(
                options,
                verify_crc=options.verify_crc or verify_crc,
                salvage=options.salvage or salvage,
            )
        else:
            opts = options
        self.options = opts
        src = source if hasattr(source, "read_at") else FileSource(source)
        owns_source = src is not source
        if opts.io_retries > 0 and not isinstance(src, RetryingSource):
            # isinstance guard: a caller-wrapped RetryingSource must not be
            # wrapped again (attempts would multiply, backoffs compound)
            src = RetryingSource(src, opts.io_retries, opts.io_retry_backoff_s)
        self.source = src
        try:
            self.metadata: ParquetMetadata = (
                metadata if metadata is not None else read_footer(self.source)
            )
        except BaseException:
            if owns_source:
                # corrupt-footer raises are a hot path (directory sniffs,
                # fuzz): the fd/mmap THIS constructor opened must not leak
                self.source.close()
            raise
        self.schema = self.metadata.schema
        self.verify_crc = opts.verify_crc
        self._salvage = opts.salvage
        self.salvage_report: Optional[SalvageReport] = (
            SalvageReport() if opts.salvage else None
        )
        self._closed = False

    # -- parity surface ----------------------------------------------------

    @property
    def record_count(self) -> int:
        """Total rows from the footer (``getRecordCount`` parity,
        ``ParquetReader.java:219-222``)."""
        return self.metadata.num_rows

    @property
    def row_groups(self) -> List[RowGroup]:
        return self.metadata.row_groups

    def close(self) -> None:
        if not self._closed:
            if self.salvage_report is not None and self.salvage_report.skips:
                trace.decision("salvage.report", self.salvage_report.summary())
            self.source.close()
            self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- decode ------------------------------------------------------------

    def _descriptor_for(self, chunk: ColumnChunk) -> ColumnDescriptor:
        path = tuple(chunk.meta_data.path_in_schema)
        return self.schema.column(path)

    def _chunk_ctx(self, desc: ColumnDescriptor,
                   row_group_index: Optional[int]) -> dict:
        return {
            "path": getattr(self.source, "name", None),
            "column": ".".join(desc.path),
            "row_group": row_group_index,
        }

    def read_column_chunk(
        self, chunk: ColumnChunk, row_group_index: Optional[int] = None
    ) -> ColumnBatch:
        """Decode one column chunk.  Every failure carries file/column/
        row-group context; hostile bytes surface as taxonomy
        (:mod:`parquet_floor_tpu.errors`), never a bare crash from deep
        inside an encoding.  In salvage mode, damaged pages of flat
        OPTIONAL columns are substituted with all-null pages (recorded in
        ``self.salvage_report``); unrecoverable damage still raises, and
        :meth:`read_row_group` quarantines the whole chunk."""
        meta = chunk.meta_data
        path = getattr(self.source, "name", None)
        if meta is None:
            raise CorruptFooterError(
                "column chunk without inline metadata",
                path=path, row_group=row_group_index,
            )
        if chunk.file_path:
            raise UnsupportedFeatureError(
                "external column chunk files are not supported",
                path=path, row_group=row_group_index,
            )
        try:
            desc = self._descriptor_for(chunk)
        except (OSError, MemoryError):
            raise  # environmental, not a schema defect
        except Exception as e:
            raise CorruptFooterError(
                f"column chunk names a path missing from the schema: "
                f"{meta.path_in_schema!r}",
                path=path, row_group=row_group_index,
            ) from e
        ctx = self._chunk_ctx(desc, row_group_index)
        # the shared transient-vs-corruption ladder: belt-and-braces so a
        # corruption path no decoder anticipated still lands in the
        # taxonomy, while OSError (flaky mounts) and MemoryError (host
        # pressure) pass through — wrapping either as CorruptPageError
        # would let salvage quarantine healthy data on an environmental
        # blip
        with classified_decode_errors(CorruptPageError,
                                      "column chunk decode failed", ctx):
            batch, skips, pages_decoded = self._decode_chunk(chunk, desc, ctx)
        if self.salvage_report is not None and self.salvage_report._first_count(
            ctx["column"], row_group_index, "ok"
        ):
            rep = self.salvage_report
            rep.pages_read += pages_decoded
            nulled = 0
            for ordinal, n, err in skips:
                rep.pages_skipped += 1
                rep.rows_quarantined += n
                nulled += n
                rep.skips.append(SalvageSkip(
                    column=ctx["column"], row_group=row_group_index,
                    page=ordinal, rows=n, error=str(err), path=path,
                ))
                trace.count("salvage.pages_skipped")
                trace.count("salvage.rows_quarantined", n)
                trace.decision("salvage.skip_page", {
                    "column": ctx["column"], "row_group": row_group_index,
                    "page": ordinal, "rows": n, "error": str(err),
                })
            rep.rows_recovered += int(meta.num_values or 0) - nulled
        return batch

    def _decode_chunk(self, chunk: ColumnChunk, desc: ColumnDescriptor,
                      ctx: dict):
        """Shared chunk decode.  Returns ``(batch, skips, pages_decoded)``
        where ``skips`` lists ``(page_ordinal, rows, error)`` for pages
        salvage replaced with all-null pages (always empty in strict
        mode).  Skips are committed to the report only by the caller,
        after the chunk as a whole succeeds — a chunk that fails later
        anyway is recorded once, as one quarantined chunk."""
        meta = chunk.meta_data
        start, length = _chunk_byte_range(meta)
        raw = self.source.read_at(start, length)
        raw_pages = pg.split_pages(raw, meta.num_values, ctx, offset_base=start)
        dictionary = None
        decoded: List[pg.DecodedPage] = []
        skips: list = []
        pages_decoded = 0
        for i, page in enumerate(raw_pages):
            pctx = {**ctx, "page": i}
            if page.page_type == PageType.DICTIONARY_PAGE:
                if dictionary is not None:
                    raise CorruptPageError(
                        "multiple dictionary pages in one chunk", **pctx
                    )
                dictionary = pg.decode_dictionary_page(
                    page, desc, meta.codec, self.verify_crc, pctx
                )
                pages_decoded += 1
            elif page.page_type in (PageType.DATA_PAGE, PageType.DATA_PAGE_V2):
                try:
                    decoded.append(pg.decode_data_page(
                        page, desc, meta.codec, dictionary, self.verify_crc,
                        pctx,
                    ))
                    pages_decoded += 1
                except CorruptPageError as e:
                    n = _page_num_values(page)
                    # n bounded by the chunk's footer total: a corrupt
                    # header claiming absurd counts must not allocate
                    if not (
                        self._salvage
                        and desc.max_repetition_level == 0
                        and desc.max_definition_level > 0
                        and isinstance(n, int)
                        and 0 <= n <= int(meta.num_values or 0)
                    ):
                        raise
                    # flat optional column: the page's rows survive as
                    # nulls (def level 0 < max), so row alignment across
                    # columns is preserved exactly
                    rows = checked_alloc_size(n, "salvaged null page", **pctx)
                    decoded.append(pg.DecodedPage(
                        n, _empty_values(desc), np.zeros(rows, np.uint32), None
                    ))
                    skips.append((i, n, e))
            elif page.page_type == PageType.INDEX_PAGE:
                continue
            else:
                raise CorruptPageError(
                    f"unknown page type {page.page_type}", **pctx
                )
        total = sum(d.num_values for d in decoded)
        if total != meta.num_values:
            raise CorruptPageError(
                f"chunk decoded {total} values, footer said {meta.num_values}",
                **ctx,
            )
        if not decoded:  # zero-row row group: valid, just empty
            empty_levels = (
                np.zeros(0, np.uint32) if desc.max_definition_level > 0 else None
            )
            return ColumnBatch(
                desc, 0, _empty_values(desc), empty_levels,
                np.zeros(0, np.uint32) if desc.max_repetition_level > 0 else None,
            ), skips, pages_decoded
        values = _concat_values([d.values for d in decoded])
        def_levels = (
            np.concatenate([d.def_levels for d in decoded])
            if decoded and decoded[0].def_levels is not None
            else None
        )
        rep_levels = (
            np.concatenate([d.rep_levels for d in decoded])
            if decoded and decoded[0].rep_levels is not None
            else None
        )
        batch = ColumnBatch(desc, meta.num_values, values, def_levels, rep_levels)
        return batch, skips, pages_decoded

    def read_row_group_ranges(
        self, index: int, row_ranges, column_filter: Optional[Set[str]] = None
    ):
        """Selective decode: only pages whose rows intersect ``row_ranges``
        are **read from disk** and decoded, using each chunk's OffsetIndex
        (I/O-level pruning — the payoff of the page indexes; pair with
        ``Predicate.row_ranges``).

        Returns ``(batch, covered)``: ``covered`` is the list of half-open
        row ranges (page-aligned, a superset of the request) the batch's
        rows actually correspond to, identical across columns.  Chunks
        without an OffsetIndex decode fully; a whole-group request or a
        zero-range request short-circuits.
        """
        from ..batch.predicate import normalize_ranges

        rg = self.row_groups[index]
        n = int(rg.num_rows or 0)
        if not normalize_ranges(row_ranges, n):
            # predicate excluded every row — report that regardless of
            # what (or whether anything) was projected
            return RowGroupBatch([], 0), []
        chunks = [
            c for c in rg.columns or []
            if not column_filter or c.meta_data.path_in_schema[0] in column_filter
        ]
        if not chunks:
            # nothing selected (e.g. misspelled projection): mirror
            # read_row_group's empty-batch-with-rows shape rather than
            # looking like "predicate excluded every row"
            return RowGroupBatch([], n), [(0, n)] if n else []
        covered = self.page_cover(index, row_ranges, chunks)
        if covered == []:
            return RowGroupBatch([], 0), []
        if covered is None or covered == [(0, n)]:
            return (
                self.read_row_group(index, column_filter),
                [(0, n)] if n else [],
            )
        batches = []
        for chunk in chunks:
            batches.append(self._read_chunk_ranges(chunk, covered, n))
        rows = sum(b - a for a, b in covered)
        return RowGroupBatch(batches, rows), covered

    def page_cover(self, index: int, row_ranges, chunks=None):
        """Page-aligned cover of ``row_ranges`` for a row group: the
        smallest union of page spans (over EVERY given chunk) containing
        the request.  Iterated to a fixpoint because page boundaries
        differ per column.  Returns None when any chunk lacks an
        OffsetIndex (caller should decode the full group)."""
        from ..batch.predicate import normalize_ranges

        rg = self.row_groups[index]
        n = int(rg.num_rows or 0)
        covered = normalize_ranges(row_ranges, n)
        if not covered:
            return []
        if chunks is None:
            chunks = list(rg.columns or [])
        chunk_spans = []
        for chunk in chunks:
            oi = self.read_offset_index(chunk)
            if oi is None or not oi.page_locations:
                return None
            firsts = [int(pl.first_row_index or 0) for pl in oi.page_locations]
            chunk_spans.append(list(zip(firsts, firsts[1:] + [n])))
        while True:
            spans = {
                (a, b)
                for cs in chunk_spans
                for a, b in cs
                if any(a < cb and ca < b for ca, cb in covered)
            }
            new = normalize_ranges(spans, n)
            if new == covered:
                return covered
            covered = new

    def _read_raw_page(self, offset: int, max_len: int,
                       ctx: Optional[dict] = None) -> "pg.RawPage":
        """Parse one page (header + payload) from a bounded byte range
        (framing validation shared with the chunk scan: ``parse_page_at``).
        """
        raw = self.source.read_at(int(offset), int(max_len))
        page, _ = pg.parse_page_at(raw, 0, ctx, None, offset_base=int(offset))
        return page

    def read_raw_column_chunk_ranges(self, chunk: ColumnChunk, covered, n: int):
        """Raw pages (dictionary page first, then only the data pages whose
        rows intersect ``covered``) — the ranged sibling of
        ``read_raw_column_chunk``.  None when the chunk has no OffsetIndex.
        """
        meta = chunk.meta_data
        oi = self.read_offset_index(chunk)
        if oi is None or not oi.page_locations:
            return None
        ctx = self._chunk_ctx(self._descriptor_for(chunk), None)
        firsts = [int(pl.first_row_index or 0) for pl in oi.page_locations]
        ends = firsts[1:] + [n]
        pages = []
        if meta.dictionary_page_offset is not None and meta.dictionary_page_offset > 0:
            dict_len = int(oi.page_locations[0].offset) - int(meta.dictionary_page_offset)
            dpage = self._read_raw_page(meta.dictionary_page_offset, dict_len, ctx)
            if dpage.page_type != PageType.DICTIONARY_PAGE:
                raise CorruptPageError(
                    "expected dictionary page before data pages",
                    offset=int(meta.dictionary_page_offset), **ctx,
                )
            pages.append(dpage)
        for pl, a, b in zip(oi.page_locations, firsts, ends):
            if any(a < cb and ca < b for ca, cb in covered):
                pages.append(
                    self._read_raw_page(pl.offset, pl.compressed_page_size, ctx)
                )
        return pages

    def _read_chunk_ranges(self, chunk: ColumnChunk, covered, n: int,
                           raw_pages=None) -> ColumnBatch:
        """Decode only the chunk's pages whose rows fall inside ``covered``
        (page spans of every selected chunk; reads page byte ranges —
        reused when the caller already fetched them)."""
        meta = chunk.meta_data
        desc = self._descriptor_for(chunk)
        ctx = self._chunk_ctx(desc, None)
        if raw_pages is None:
            raw_pages = self.read_raw_column_chunk_ranges(chunk, covered, n)
        dictionary = None
        decoded = []
        for i, page in enumerate(raw_pages):
            pctx = {**ctx, "page": i}
            if page.page_type == PageType.DICTIONARY_PAGE:
                dictionary = pg.decode_dictionary_page(
                    page, desc, meta.codec, self.verify_crc, pctx
                )
                continue
            decoded.append(
                pg.decode_data_page(page, desc, meta.codec, dictionary,
                                    self.verify_crc, pctx)
            )
        total = sum(d.num_values for d in decoded)
        if not decoded:
            empty_levels = (
                np.zeros(0, np.uint32) if desc.max_definition_level > 0 else None
            )
            return ColumnBatch(
                desc, 0, _empty_values(desc), empty_levels,
                np.zeros(0, np.uint32) if desc.max_repetition_level > 0 else None,
            )
        values = _concat_values([d.values for d in decoded])
        def_levels = (
            np.concatenate([d.def_levels for d in decoded])
            if decoded[0].def_levels is not None else None
        )
        rep_levels = (
            np.concatenate([d.rep_levels for d in decoded])
            if decoded[0].rep_levels is not None else None
        )
        return ColumnBatch(desc, total, values, def_levels, rep_levels)

    def read_row_group(
        self, index: int, column_filter: Optional[Set[str]] = None
    ) -> RowGroupBatch:
        """Decode one row group into columnar batches.

        ``column_filter`` projects by **top-level field name** — exactly the
        reference's projection semantics (``ParquetReader.java:126-128``);
        None or empty means all columns (``ParquetReader.java:76``).
        """
        rg = self.row_groups[index]
        batches = []
        for chunk in rg.columns or []:
            meta = chunk.meta_data
            # a nulled/corrupt meta_data falls THROUGH to read_column_chunk,
            # which diagnoses it (CorruptFooterError, with context) — a
            # projection must never silently drop an undiagnosable chunk
            path0 = (
                meta.path_in_schema[0]
                if meta is not None and meta.path_in_schema
                else None
            )
            if column_filter and path0 is not None and path0 not in column_filter:
                continue
            if not self._salvage:
                batches.append(self.read_column_chunk(chunk, index))
                continue
            try:
                batches.append(self.read_column_chunk(chunk, index))
            except _SALVAGEABLE as e:
                self._quarantine_chunk(chunk, index, rg, e)
        return RowGroupBatch(batches, rg.num_rows or 0)

    def _quarantine_chunk(self, chunk: ColumnChunk, index: int,
                          rg: RowGroup, err: Exception) -> None:
        """Salvage mode: drop one unrecoverable column chunk, keep the
        row group's other columns.  The batch simply omits the column;
        the report and a ``trace.decision`` event record exactly what
        was lost."""
        rep = self.salvage_report
        column = ".".join(chunk.meta_data.path_in_schema or ["?"])
        if not rep._first_count(column, index, "q"):
            return  # this chunk's loss is already on the books
        rows = int(chunk.meta_data.num_values or rg.num_rows or 0)
        rep.chunks_quarantined += 1
        rep.rows_quarantined += rows
        rep.skips.append(SalvageSkip(
            column=column, row_group=index, page=None, rows=rows,
            error=str(err), path=getattr(self.source, "name", None),
        ))
        trace.count("salvage.chunks_quarantined")
        trace.count("salvage.rows_quarantined", rows)
        trace.decision("salvage.quarantine_chunk", {
            "column": column, "row_group": index, "rows": rows,
            "error": str(err),
        })

    def iter_row_groups(
        self, column_filter: Optional[Set[str]] = None, predicate=None
    ) -> Iterator[RowGroupBatch]:
        """Decode row groups in order; with ``predicate`` (see
        ``batch.predicate.col``) groups whose statistics prove no row can
        match are skipped without reading a page."""
        indices = (
            predicate.row_groups(self)
            if predicate is not None
            else range(len(self.row_groups))
        )
        for i in indices:
            yield self.read_row_group(i, column_filter)

    def read_raw_column_chunk(self, chunk: ColumnChunk):
        """Raw page payloads + headers for a chunk (TPU engine feedstock)."""
        meta = chunk.meta_data
        start, length = _chunk_byte_range(meta)
        raw = self.source.read_at(start, length)
        return pg.split_pages(
            raw, meta.num_values,
            self._chunk_ctx(self._descriptor_for(chunk), None),
            offset_base=start,
        )

    # -- page indexes ------------------------------------------------------

    def read_column_index(self, chunk: ColumnChunk):
        """The chunk's ColumnIndex (per-page min/max/null stats), or None
        when the writer emitted none.  Parsed once per chunk (cached)."""
        from .parquet_thrift import ColumnIndex

        return self._page_index(
            chunk.column_index_offset, chunk.column_index_length, ColumnIndex
        )

    def read_offset_index(self, chunk: ColumnChunk):
        """The chunk's OffsetIndex (per-page locations/first rows), or None
        when the writer emitted none.  Parsed once per chunk (cached)."""
        from .parquet_thrift import OffsetIndex

        return self._page_index(
            chunk.offset_index_offset, chunk.offset_index_length, OffsetIndex
        )

    def _page_index(self, offset, length, struct_cls):
        if offset is None or not length:
            return None
        cache = getattr(self, "_pgidx_cache", None)
        if cache is None:
            cache = self._pgidx_cache = {}
        key = (offset, length)
        if key not in cache:
            raw = self.source.read_at(offset, length)
            cache[key], _ = struct_cls.from_bytes(raw)
        return cache[key]

    # -- bloom filters -----------------------------------------------------

    def read_bloom_filter(self, chunk: ColumnChunk):
        """The chunk's split-block Bloom filter, or None when the writer
        emitted none.  Parsed once per chunk (cached).  Writers that
        predate ``bloom_filter_length`` (field 15) get a two-step read:
        header first, then exactly ``numBytes`` of bitset."""
        from .bloom import BloomFilterHeader, SplitBlockBloomFilter
        from .thrift import CompactReader

        md = chunk.meta_data
        offset = md.bloom_filter_offset
        if offset is None:
            return None
        cache = getattr(self, "_bloom_cache", None)
        if cache is None:
            cache = self._bloom_cache = {}
        if offset not in cache:
            length = md.bloom_filter_length
            if length:
                raw = self.source.read_at(int(offset), int(length))
                cache[offset] = SplitBlockBloomFilter.from_bytes(raw)
            else:
                # header probe clamped to the file tail: a small foreign
                # file may place the filter within the last 64 bytes
                probe = min(64, self.source.size - int(offset))
                if probe <= 0:
                    raise TruncatedFileError(
                        f"bloom filter offset {offset} outside file of "
                        f"{self.source.size} bytes",
                        path=getattr(self.source, "name", None),
                        offset=int(offset),
                    )
                head = self.source.read_at(int(offset), probe)
                reader = CompactReader(head)
                header = BloomFilterHeader.read(reader)
                total = reader.pos + int(header.numBytes or 0)
                raw = self.source.read_at(int(offset), total)
                cache[offset] = SplitBlockBloomFilter.from_bytes(raw)
        return cache[offset]
