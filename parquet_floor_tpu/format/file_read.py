"""ParquetFileReader: the from-scratch engine replacing parquet-mr's
``ParquetFileReader.open/getFooter/readNextRowGroup/getRecordCount``
(reference call sites ``ParquetReader.java:114-120,183,221``).

Row-group streaming (one group materialized at a time — parity with the
reference's lazy ``tryAdvance`` pull at ``ParquetReader.java:182-194``), but
each group decodes **columnar**: all pages of a chunk decode into arrays in
one pass instead of per-cell virtual dispatch.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Set

import numpy as np

from ..batch.columns import ColumnBatch, RowGroupBatch
from ..io.source import FileSource
from . import pages as pg
from .encodings.plain import ByteArrayColumn
from .metadata import ParquetMetadata, read_footer
from .parquet_thrift import ColumnChunk, ColumnMetaData, PageHeader, PageType, RowGroup
from .schema import ColumnDescriptor
from .thrift import CompactReader


def _chunk_byte_range(meta: ColumnMetaData):
    start = meta.data_page_offset
    if meta.dictionary_page_offset is not None and meta.dictionary_page_offset > 0:
        start = min(start, meta.dictionary_page_offset)
    return start, meta.total_compressed_size


def _empty_values(desc: ColumnDescriptor):
    """Typed empty value container for a zero-value chunk."""
    from .parquet_thrift import Type as _T

    pt = desc.physical_type
    if pt == _T.BYTE_ARRAY:
        return ByteArrayColumn(np.zeros(1, np.int64), np.zeros(0, np.uint8))
    if pt == _T.BOOLEAN:
        return np.zeros(0, np.bool_)
    if pt == _T.INT32:
        return np.zeros(0, np.int32)
    if pt == _T.INT64:
        return np.zeros(0, np.int64)
    if pt == _T.FLOAT:
        return np.zeros(0, np.float32)
    if pt == _T.DOUBLE:
        return np.zeros(0, np.float64)
    width = desc.type_length if pt == _T.FIXED_LEN_BYTE_ARRAY else 12
    return np.zeros((0, width), np.uint8)


def _concat_values(parts):
    if not parts:
        raise ValueError("no pages decoded")
    if len(parts) == 1:
        return parts[0]
    if isinstance(parts[0], ByteArrayColumn):
        pools = [p.data for p in parts]
        offs = [parts[0].offsets]
        base = parts[0].offsets[-1]
        for p in parts[1:]:
            offs.append(p.offsets[1:] + base)
            base = base + p.offsets[-1]
        return ByteArrayColumn(np.concatenate(offs), np.concatenate(pools))
    return np.concatenate(parts)


class ParquetFileReader:
    """Open a parquet file, expose footer + per-row-group columnar decode."""

    def __init__(self, source, verify_crc: bool = False):
        self.source = source if isinstance(source, FileSource) else FileSource(source)
        self.metadata: ParquetMetadata = read_footer(self.source)
        self.schema = self.metadata.schema
        self.verify_crc = verify_crc
        self._closed = False

    # -- parity surface ----------------------------------------------------

    @property
    def record_count(self) -> int:
        """Total rows from the footer (``getRecordCount`` parity,
        ``ParquetReader.java:219-222``)."""
        return self.metadata.num_rows

    @property
    def row_groups(self) -> List[RowGroup]:
        return self.metadata.row_groups

    def close(self) -> None:
        if not self._closed:
            self.source.close()
            self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- decode ------------------------------------------------------------

    def _descriptor_for(self, chunk: ColumnChunk) -> ColumnDescriptor:
        path = tuple(chunk.meta_data.path_in_schema)
        return self.schema.column(path)

    def read_column_chunk(self, chunk: ColumnChunk) -> ColumnBatch:
        meta = chunk.meta_data
        if meta is None:
            raise ValueError("column chunk without inline metadata")
        if chunk.file_path:
            raise ValueError("external column chunk files are not supported")
        desc = self._descriptor_for(chunk)
        start, length = _chunk_byte_range(meta)
        raw = self.source.read_at(start, length)
        raw_pages = pg.split_pages(raw, meta.num_values)
        dictionary = None
        decoded: List[pg.DecodedPage] = []
        for page in raw_pages:
            if page.page_type == PageType.DICTIONARY_PAGE:
                if dictionary is not None:
                    raise ValueError("multiple dictionary pages in one chunk")
                dictionary = pg.decode_dictionary_page(
                    page, desc, meta.codec, self.verify_crc
                )
            elif page.page_type in (PageType.DATA_PAGE, PageType.DATA_PAGE_V2):
                decoded.append(
                    pg.decode_data_page(page, desc, meta.codec, dictionary, self.verify_crc)
                )
            elif page.page_type == PageType.INDEX_PAGE:
                continue
            else:
                raise ValueError(f"unknown page type {page.page_type}")
        total = sum(d.num_values for d in decoded)
        if total != meta.num_values:
            raise ValueError(
                f"chunk decoded {total} values, footer said {meta.num_values}"
            )
        if not decoded:  # zero-row row group: valid, just empty
            empty_levels = (
                np.zeros(0, np.uint32) if desc.max_definition_level > 0 else None
            )
            return ColumnBatch(
                desc, 0, _empty_values(desc), empty_levels,
                np.zeros(0, np.uint32) if desc.max_repetition_level > 0 else None,
            )
        values = _concat_values([d.values for d in decoded])
        def_levels = (
            np.concatenate([d.def_levels for d in decoded])
            if decoded and decoded[0].def_levels is not None
            else None
        )
        rep_levels = (
            np.concatenate([d.rep_levels for d in decoded])
            if decoded and decoded[0].rep_levels is not None
            else None
        )
        return ColumnBatch(desc, meta.num_values, values, def_levels, rep_levels)

    def read_row_group_ranges(
        self, index: int, row_ranges, column_filter: Optional[Set[str]] = None
    ):
        """Selective decode: only pages whose rows intersect ``row_ranges``
        are **read from disk** and decoded, using each chunk's OffsetIndex
        (I/O-level pruning — the payoff of the page indexes; pair with
        ``Predicate.row_ranges``).

        Returns ``(batch, covered)``: ``covered`` is the list of half-open
        row ranges (page-aligned, a superset of the request) the batch's
        rows actually correspond to, identical across columns.  Chunks
        without an OffsetIndex decode fully; a whole-group request or a
        zero-range request short-circuits.
        """
        from ..batch.predicate import normalize_ranges

        rg = self.row_groups[index]
        n = int(rg.num_rows or 0)
        if not normalize_ranges(row_ranges, n):
            # predicate excluded every row — report that regardless of
            # what (or whether anything) was projected
            return RowGroupBatch([], 0), []
        chunks = [
            c for c in rg.columns or []
            if not column_filter or c.meta_data.path_in_schema[0] in column_filter
        ]
        if not chunks:
            # nothing selected (e.g. misspelled projection): mirror
            # read_row_group's empty-batch-with-rows shape rather than
            # looking like "predicate excluded every row"
            return RowGroupBatch([], n), [(0, n)] if n else []
        covered = self.page_cover(index, row_ranges, chunks)
        if covered == []:
            return RowGroupBatch([], 0), []
        if covered is None or covered == [(0, n)]:
            return (
                self.read_row_group(index, column_filter),
                [(0, n)] if n else [],
            )
        batches = []
        for chunk in chunks:
            batches.append(self._read_chunk_ranges(chunk, covered, n))
        rows = sum(b - a for a, b in covered)
        return RowGroupBatch(batches, rows), covered

    def page_cover(self, index: int, row_ranges, chunks=None):
        """Page-aligned cover of ``row_ranges`` for a row group: the
        smallest union of page spans (over EVERY given chunk) containing
        the request.  Iterated to a fixpoint because page boundaries
        differ per column.  Returns None when any chunk lacks an
        OffsetIndex (caller should decode the full group)."""
        from ..batch.predicate import normalize_ranges

        rg = self.row_groups[index]
        n = int(rg.num_rows or 0)
        covered = normalize_ranges(row_ranges, n)
        if not covered:
            return []
        if chunks is None:
            chunks = list(rg.columns or [])
        chunk_spans = []
        for chunk in chunks:
            oi = self.read_offset_index(chunk)
            if oi is None or not oi.page_locations:
                return None
            firsts = [int(pl.first_row_index or 0) for pl in oi.page_locations]
            chunk_spans.append(list(zip(firsts, firsts[1:] + [n])))
        while True:
            spans = {
                (a, b)
                for cs in chunk_spans
                for a, b in cs
                if any(a < cb and ca < b for ca, cb in covered)
            }
            new = normalize_ranges(spans, n)
            if new == covered:
                return covered
            covered = new

    def _read_raw_page(self, offset: int, max_len: int) -> "pg.RawPage":
        """Parse one page (header + payload) from a bounded byte range."""
        raw = self.source.read_at(int(offset), int(max_len))
        reader = CompactReader(raw)
        header = PageHeader.read(reader)
        payload = bytes(raw[reader.pos : reader.pos + header.compressed_page_size])
        if len(payload) != header.compressed_page_size:
            raise ValueError("page payload truncated")
        return pg.RawPage(header, payload)

    def read_raw_column_chunk_ranges(self, chunk: ColumnChunk, covered, n: int):
        """Raw pages (dictionary page first, then only the data pages whose
        rows intersect ``covered``) — the ranged sibling of
        ``read_raw_column_chunk``.  None when the chunk has no OffsetIndex.
        """
        meta = chunk.meta_data
        oi = self.read_offset_index(chunk)
        if oi is None or not oi.page_locations:
            return None
        firsts = [int(pl.first_row_index or 0) for pl in oi.page_locations]
        ends = firsts[1:] + [n]
        pages = []
        if meta.dictionary_page_offset is not None and meta.dictionary_page_offset > 0:
            dict_len = int(oi.page_locations[0].offset) - int(meta.dictionary_page_offset)
            dpage = self._read_raw_page(meta.dictionary_page_offset, dict_len)
            if dpage.page_type != PageType.DICTIONARY_PAGE:
                raise ValueError("expected dictionary page before data pages")
            pages.append(dpage)
        for pl, a, b in zip(oi.page_locations, firsts, ends):
            if any(a < cb and ca < b for ca, cb in covered):
                pages.append(
                    self._read_raw_page(pl.offset, pl.compressed_page_size)
                )
        return pages

    def _read_chunk_ranges(self, chunk: ColumnChunk, covered, n: int,
                           raw_pages=None) -> ColumnBatch:
        """Decode only the chunk's pages whose rows fall inside ``covered``
        (page spans of every selected chunk; reads page byte ranges —
        reused when the caller already fetched them)."""
        meta = chunk.meta_data
        desc = self._descriptor_for(chunk)
        if raw_pages is None:
            raw_pages = self.read_raw_column_chunk_ranges(chunk, covered, n)
        dictionary = None
        decoded = []
        for page in raw_pages:
            if page.page_type == PageType.DICTIONARY_PAGE:
                dictionary = pg.decode_dictionary_page(
                    page, desc, meta.codec, self.verify_crc
                )
                continue
            decoded.append(
                pg.decode_data_page(page, desc, meta.codec, dictionary, self.verify_crc)
            )
        total = sum(d.num_values for d in decoded)
        if not decoded:
            empty_levels = (
                np.zeros(0, np.uint32) if desc.max_definition_level > 0 else None
            )
            return ColumnBatch(
                desc, 0, _empty_values(desc), empty_levels,
                np.zeros(0, np.uint32) if desc.max_repetition_level > 0 else None,
            )
        values = _concat_values([d.values for d in decoded])
        def_levels = (
            np.concatenate([d.def_levels for d in decoded])
            if decoded[0].def_levels is not None else None
        )
        rep_levels = (
            np.concatenate([d.rep_levels for d in decoded])
            if decoded[0].rep_levels is not None else None
        )
        return ColumnBatch(desc, total, values, def_levels, rep_levels)

    def read_row_group(
        self, index: int, column_filter: Optional[Set[str]] = None
    ) -> RowGroupBatch:
        """Decode one row group into columnar batches.

        ``column_filter`` projects by **top-level field name** — exactly the
        reference's projection semantics (``ParquetReader.java:126-128``);
        None or empty means all columns (``ParquetReader.java:76``).
        """
        rg = self.row_groups[index]
        batches = []
        for chunk in rg.columns or []:
            path0 = chunk.meta_data.path_in_schema[0]
            if column_filter and path0 not in column_filter:
                continue
            batches.append(self.read_column_chunk(chunk))
        return RowGroupBatch(batches, rg.num_rows or 0)

    def iter_row_groups(
        self, column_filter: Optional[Set[str]] = None, predicate=None
    ) -> Iterator[RowGroupBatch]:
        """Decode row groups in order; with ``predicate`` (see
        ``batch.predicate.col``) groups whose statistics prove no row can
        match are skipped without reading a page."""
        indices = (
            predicate.row_groups(self)
            if predicate is not None
            else range(len(self.row_groups))
        )
        for i in indices:
            yield self.read_row_group(i, column_filter)

    def read_raw_column_chunk(self, chunk: ColumnChunk):
        """Raw page payloads + headers for a chunk (TPU engine feedstock)."""
        meta = chunk.meta_data
        start, length = _chunk_byte_range(meta)
        raw = self.source.read_at(start, length)
        return pg.split_pages(raw, meta.num_values)

    # -- page indexes ------------------------------------------------------

    def read_column_index(self, chunk: ColumnChunk):
        """The chunk's ColumnIndex (per-page min/max/null stats), or None
        when the writer emitted none.  Parsed once per chunk (cached)."""
        from .parquet_thrift import ColumnIndex

        return self._page_index(
            chunk.column_index_offset, chunk.column_index_length, ColumnIndex
        )

    def read_offset_index(self, chunk: ColumnChunk):
        """The chunk's OffsetIndex (per-page locations/first rows), or None
        when the writer emitted none.  Parsed once per chunk (cached)."""
        from .parquet_thrift import OffsetIndex

        return self._page_index(
            chunk.offset_index_offset, chunk.offset_index_length, OffsetIndex
        )

    def _page_index(self, offset, length, struct_cls):
        if offset is None or not length:
            return None
        cache = getattr(self, "_pgidx_cache", None)
        if cache is None:
            cache = self._pgidx_cache = {}
        key = (offset, length)
        if key not in cache:
            raw = self.source.read_at(offset, length)
            cache[key], _ = struct_cls.from_bytes(raw)
        return cache[key]

    # -- bloom filters -----------------------------------------------------

    def read_bloom_filter(self, chunk: ColumnChunk):
        """The chunk's split-block Bloom filter, or None when the writer
        emitted none.  Parsed once per chunk (cached).  Writers that
        predate ``bloom_filter_length`` (field 15) get a two-step read:
        header first, then exactly ``numBytes`` of bitset."""
        from .bloom import BloomFilterHeader, SplitBlockBloomFilter
        from .thrift import CompactReader

        md = chunk.meta_data
        offset = md.bloom_filter_offset
        if offset is None:
            return None
        cache = getattr(self, "_bloom_cache", None)
        if cache is None:
            cache = self._bloom_cache = {}
        if offset not in cache:
            length = md.bloom_filter_length
            if length:
                raw = self.source.read_at(int(offset), int(length))
                cache[offset] = SplitBlockBloomFilter.from_bytes(raw)
            else:
                # header probe clamped to the file tail: a small foreign
                # file may place the filter within the last 64 bytes
                probe = min(64, self.source.size - int(offset))
                if probe <= 0:
                    raise EOFError(
                        f"bloom filter offset {offset} outside file of "
                        f"{self.source.size} bytes"
                    )
                head = self.source.read_at(int(offset), probe)
                reader = CompactReader(head)
                header = BloomFilterHeader.read(reader)
                total = reader.pos + int(header.numBytes or 0)
                raw = self.source.read_at(int(offset), total)
                cache[offset] = SplitBlockBloomFilter.from_bytes(raw)
        return cache[offset]
