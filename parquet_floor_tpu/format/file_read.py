"""ParquetFileReader: the from-scratch engine replacing parquet-mr's
``ParquetFileReader.open/getFooter/readNextRowGroup/getRecordCount``
(reference call sites ``ParquetReader.java:114-120,183,221``).

Row-group streaming (one group materialized at a time — parity with the
reference's lazy ``tryAdvance`` pull at ``ParquetReader.java:182-194``), but
each group decodes **columnar**: all pages of a chunk decode into arrays in
one pass instead of per-cell virtual dispatch.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from typing import Iterator, List, Optional, Set

import numpy as np

from ..batch.columns import ColumnBatch, RowGroupBatch
from ..errors import (
    CorruptFooterError,
    CorruptPageError,
    TruncatedFileError,
    UnsupportedFeatureError,
    checked_alloc_size,
    classified_decode_errors,
)
from ..io.source import FileSource
from ..utils import trace
from . import pages as pg
from .encodings.plain import ByteArrayColumn
from .metadata import ParquetMetadata, read_footer
from .parquet_thrift import ColumnChunk, ColumnMetaData, PageType, RowGroup
from .schema import ColumnDescriptor
from .thrift import ThriftDecodeError


@dataclass
class ReaderOptions:
    """Read-side configuration — the explicit read twin of
    ``WriterOptions`` (SURVEY.md §5's explicit-config stance).

    * ``verify_crc`` — CRC32-check every page payload against the header
      stamp before decode.  Off by default (parity with parquet-mr's
      default); turn it on for storage you do not trust — it is the only
      way a bit flip inside a compressed payload is *guaranteed* to be
      detected rather than surfacing as a downstream decode error (or,
      for UNCOMPRESSED pages, silent wrong data).
    * ``salvage`` — quarantine corrupt pages/chunks instead of aborting
      the whole file; see :class:`SalvageReport`.  Strict (off) is the
      default and behaves byte-identically to a reader without the flag.
    * ``io_retries`` — bounded retry-with-backoff for *transient*
      ``OSError`` reads (flaky NFS/FUSE/object-store mounts).  0 (off) by
      default; deterministic errors (truncation, parse) never retry.
    * ``io_retry_backoff_s`` — first backoff sleep; doubles per attempt.
    * ``io_retry_deadline_s`` — total wall-clock budget across ALL
      attempts of one read (None = unbounded): a deep retry ladder on a
      dead mount gives up when the deadline would be crossed, surfacing
      ``IoRetryExhaustedError`` (and an ``io.retry_deadline_exceeded``
      trace decision) instead of sleeping through the full exponential
      schedule.
    * ``quarantine_map`` — a
      :class:`~parquet_floor_tpu.quarantine.QuarantineMap` (salvage mode
      only): known-bad units recorded by an earlier scan are replayed
      without re-attempting their decode (page-tier entries with
      recorded byte spans skip the page's BYTES too), and new
      quarantines are recorded back into the map when the reader
      closes.  The map carries its own fingerprint mode — pass
      ``QuarantineMap(path, fingerprint="content")`` here to key on a
      full-content CRC (closing the size+tail fingerprint's in-place
      mid-file-repair blind spot at the price of one full read per
      open).
    """

    verify_crc: bool = False
    salvage: bool = False
    io_retries: int = 0
    io_retry_backoff_s: float = 0.05
    io_retry_deadline_s: Optional[float] = None
    quarantine_map: Optional[object] = None

    def __post_init__(self):
        # fail-fast: a bad retry config must error here, not silently
        # become "no retries"
        if self.io_retries < 0:
            raise ValueError(f"io_retries must be >= 0, got {self.io_retries}")
        if self.io_retry_backoff_s < 0:
            raise ValueError(
                f"io_retry_backoff_s must be >= 0, got {self.io_retry_backoff_s}"
            )
        if self.io_retry_deadline_s is not None and self.io_retry_deadline_s <= 0:
            raise ValueError(
                "io_retry_deadline_s must be > 0 (or None for unbounded), "
                f"got {self.io_retry_deadline_s}"
            )
        if self.quarantine_map is not None and not self.salvage:
            raise ValueError(
                "quarantine_map only makes sense with salvage=True (strict "
                "mode never quarantines; an ignored map would be a silent "
                "misconfiguration)"
            )


@dataclass
class SalvageSkip:
    """One quarantined unit recorded by salvage mode.

    ``kind`` names the salvage tier that absorbed the damage
    (``docs/robustness.md``):

    * ``"page_null"`` — a flat OPTIONAL column's damaged page replaced
      by an all-null page (row geometry preserved);
    * ``"row_mask"`` — a flat REQUIRED column's damaged page dropped its
      row span from the whole row group (``row_span`` is the group-local
      half-open range removed);
    * ``"dict"`` — a damaged dictionary page (recovered via another row
      group's shared dictionary or lost to PLAIN-only decode; the error
      string records which);
    * ``"chunk"`` — the whole column chunk dropped for the row group.
    """

    column: str
    row_group: Optional[int]
    page: Optional[int]  # ordinal within the chunk; None = whole chunk
    rows: int            # value slots lost (rows, for flat columns)
    error: str
    path: Optional[str] = None
    kind: str = "chunk"
    row_span: Optional[tuple] = None  # group-local [start, stop) for row_mask
    # absolute file byte span [start, stop) of a quarantined PAGE —
    # recorded so the quarantine map can replay the loss WITHOUT reading
    # the page's bytes on a later scan (page-tier I/O skip)
    byte_span: Optional[tuple] = None

    def key(self) -> tuple:
        """Identity for cross-face/set comparison and map dedup."""
        return (self.row_group, self.column, self.page, self.kind)

    def as_dict(self) -> dict:
        return {
            "column": self.column,
            "row_group": self.row_group,
            "page": self.page,
            "rows": self.rows,
            "error": self.error,
            "path": self.path,
            "kind": self.kind,
            "row_span": list(self.row_span) if self.row_span else None,
            "byte_span": list(self.byte_span) if self.byte_span else None,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "SalvageSkip":
        return cls(
            column=d["column"],
            row_group=d.get("row_group"),
            page=d.get("page"),
            rows=int(d.get("rows") or 0),
            error=str(d.get("error") or ""),
            path=d.get("path"),
            kind=str(d.get("kind") or "chunk"),
            row_span=(
                tuple(d["row_span"]) if d.get("row_span") else None
            ),
            byte_span=(
                tuple(d["byte_span"]) if d.get("byte_span") else None
            ),
        )


@dataclass
class SalvageReport:
    """What salvage mode recovered and what it had to give up.

    Counters are in *column-rows* (value slots: one per row per column;
    equal to rows for flat columns).  A page skip nulls the page's rows
    in an OPTIONAL flat column (rows survive as nulls, counted
    quarantined); a chunk quarantine drops that column for the whole row
    group (other columns still decode).  ``first_errors`` maps each
    damaged column to the first error seen on it.
    """

    pages_read: int = 0
    pages_skipped: int = 0
    chunks_quarantined: int = 0
    rows_recovered: int = 0
    rows_quarantined: int = 0
    # group-wide row loss from the row-mask tier: rows REMOVED from every
    # column of a row group because a REQUIRED page's span was damaged
    rows_dropped: int = 0
    skips: List[SalvageSkip] = field(default_factory=list)
    # (column, row_group) chunks already accounted — decode is
    # deterministic, so re-decoding a group (restore(), repeated
    # read_row_group) must not double-count its losses or recoveries
    _counted: set = field(default_factory=set, repr=False, compare=False)

    def _first_count(self, column: str, row_group, kind: str) -> bool:
        """True exactly once per (kind, column, row_group); callers skip
        accounting on repeats.  ``kind`` separates successful-decode
        accounting ("ok") from quarantine accounting ("q"): a chunk that
        decoded fine once but fails on a LATER re-read (flaky storage, a
        file changing underneath) must still get its quarantine record —
        every omission has a report entry.  An unknown group (direct
        ``read_column_chunk`` calls with no index) always counts — keys
        from different groups would collide at None, and unreported loss
        is worse than a possible double-count on re-decode."""
        if row_group is None:
            return True
        key = (kind, column, row_group)
        if key in self._counted:
            return False
        self._counted.add(key)
        return True

    @property
    def first_errors(self) -> dict:
        out: dict = {}
        for s in self.skips:
            out.setdefault(s.column, s.error)
        return out

    def summary(self) -> dict:
        return {
            "pages_read": self.pages_read,
            "pages_skipped": self.pages_skipped,
            "chunks_quarantined": self.chunks_quarantined,
            "rows_recovered": self.rows_recovered,
            "rows_quarantined": self.rows_quarantined,
            "rows_dropped": self.rows_dropped,
            "first_errors": self.first_errors,
        }

    # -- the merge protocol (per-unit reports → one report) ----------------

    def merge_in(self, other: "SalvageReport") -> "SalvageReport":
        """Fold ``other`` into this report IN PLACE (counters sum, skips
        concatenate in call order, dedup keys union) and return self.
        The scan faces decode each unit into a fresh per-unit report on
        a worker thread and merge them here, in DELIVERY order, on the
        consumer thread — so the folded report is deterministic no
        matter how the pool scheduled the decodes."""
        self.pages_read += other.pages_read
        self.pages_skipped += other.pages_skipped
        self.chunks_quarantined += other.chunks_quarantined
        self.rows_recovered += other.rows_recovered
        self.rows_quarantined += other.rows_quarantined
        self.rows_dropped += other.rows_dropped
        self.skips.extend(other.skips)
        self._counted |= other._counted
        return self

    @classmethod
    def merge(cls, reports) -> "SalvageReport":
        """A new report folding ``reports`` left-to-right.  Associative:
        grouping does not change the result (counters are sums, skips a
        concatenation), so worker sub-merges compose."""
        out = cls()
        for r in reports:
            out.merge_in(r)
        return out

    # -- geometry queries (what the loader needs) ---------------------------

    def geometry_damaged(self, row_group: Optional[int] = None) -> bool:
        """True when salvage changed the SHAPE of the data — a column
        chunk dropped or rows removed (row-mask tier) — for the given
        row group (or any group when None).  Page-null substitution
        keeps geometry and does NOT count: those rows survive as
        masked nulls."""
        return any(
            s.kind in ("chunk", "row_mask")
            and (row_group is None or s.row_group == row_group)
            for s in self.skips
        )

    def damaged_groups(self) -> set:
        """Row groups with geometry-changing damage (see
        :meth:`geometry_damaged`)."""
        return {
            s.row_group for s in self.skips
            if s.kind in ("chunk", "row_mask")
        }

    def chunk_quarantined(self, row_group, column: str) -> bool:
        """True iff a whole-chunk quarantine is on record for
        ``(row_group, column)`` — THE definition every face's
        missing-column placeholder rule consults (a column missing
        WITHOUT a record is corrupt-footer loss and must raise).  The
        snapshot tolerates a concurrent scan worker appending."""
        return any(
            s.kind == "chunk" and s.row_group == row_group
            and s.column == column
            for s in tuple(self.skips)
        )

    # -- JSON round-trip (checkpoints, sidecars) ----------------------------

    def as_dict(self) -> dict:
        d = self.summary()
        d.pop("first_errors")
        d["skips"] = [s.as_dict() for s in self.skips]
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "SalvageReport":
        out = cls(
            pages_read=int(d.get("pages_read") or 0),
            pages_skipped=int(d.get("pages_skipped") or 0),
            chunks_quarantined=int(d.get("chunks_quarantined") or 0),
            rows_recovered=int(d.get("rows_recovered") or 0),
            rows_quarantined=int(d.get("rows_quarantined") or 0),
            rows_dropped=int(d.get("rows_dropped") or 0),
            skips=[SalvageSkip.from_dict(s) for s in d.get("skips") or []],
        )
        return out


# What salvage mode may quarantine: damaged pages/chunks and reads past
# the physical end.  UnsupportedFeatureError is NOT here on purpose — a
# missing capability is a fact about this engine, not the file, and
# silently dropping such columns would misreport healthy data as damaged.
_SALVAGEABLE = (CorruptPageError, TruncatedFileError, ThriftDecodeError)


class _MapGapPage:
    """Placeholder in a chunk's page list for a known-bad page whose
    BYTES were never read: the quarantine map recorded the page's byte
    span, so the sparse chunk read skipped it and the decode loop
    substitutes the recorded outcome here (``entry`` is the map's
    replay record)."""

    __slots__ = ("entry",)
    page_type = None  # never matches a PageType — handled explicitly

    def __init__(self, entry: dict):
        self.entry = entry


def _page_bspan(chunk_start: int, page) -> Optional[tuple]:
    """Absolute file byte span of one parsed page (None when the parser
    did not track offsets)."""
    if getattr(page, "start", None) is None or page.end is None:
        return None
    return (chunk_start + int(page.start), chunk_start + int(page.end))


def _trace_map_skip(ctx: dict, page: int, rows: int,
                    bytes_skipped: int) -> None:
    """The page-tier quarantine-map replay accounting — ONE spelling of
    the counter + decision pair, shared by the sparse (bytes skipped)
    and in-buffer (decode skipped) replay paths."""
    trace.count("salvage.map_skips")
    trace.decision("salvage.map_skip", {
        "column": ctx.get("column"),
        "row_group": ctx.get("row_group"),
        "page": page, "rows": rows, "bytes_skipped": bytes_skipped,
    })


def page_row_spans(oi, num_rows: int) -> list:
    """Per-page ``(page_location, row_start, row_end)`` of one chunk's
    OffsetIndex (half-open, group-local) — THE one derivation of page
    row geometry, shared by the ranged reader, the predicate's page
    pruning, the scan planner, and the lookup face's page accounting
    (a fix to the span math lands everywhere at once)."""
    firsts = [int(pl.first_row_index or 0) for pl in oi.page_locations]
    return list(zip(oi.page_locations, firsts,
                    firsts[1:] + [int(num_rows)]))


def spans_overlap(a: int, b: int, covered) -> bool:
    """True when ``[a, b)`` intersects any half-open range in
    ``covered`` (the page-vs-cover test paired with
    :func:`page_row_spans`)."""
    return any(a < cb and ca < b for ca, cb in covered)


def _chunk_byte_range(meta: ColumnMetaData):
    start = meta.data_page_offset
    if meta.dictionary_page_offset is not None and meta.dictionary_page_offset > 0:
        start = min(start, meta.dictionary_page_offset)
    return start, meta.total_compressed_size


def _filler_values(desc: ColumnDescriptor, n: int = 0):
    """Typed all-zero value container holding ``n`` values — the empty
    container for a zero-value chunk (``n=0``) and the placeholder the
    row-mask tier substitutes for a damaged REQUIRED page (the rows are
    dropped group-wide before any consumer can see the zeros)."""
    from .parquet_thrift import Type as _T

    # n reaches here from page-header value counts: bless it once so a
    # corrupt count cannot size the placeholder (FL-ALLOC001)
    nv = checked_alloc_size(n, "filler values", column=".".join(desc.path))
    pt = desc.physical_type
    if pt == _T.BYTE_ARRAY:
        return ByteArrayColumn(np.zeros(nv + 1, np.int64), np.zeros(0, np.uint8))
    if pt == _T.BOOLEAN:
        return np.zeros(nv, np.bool_)
    if pt == _T.INT32:
        return np.zeros(nv, np.int32)
    if pt == _T.INT64:
        return np.zeros(nv, np.int64)
    if pt == _T.FLOAT:
        return np.zeros(nv, np.float32)
    if pt == _T.DOUBLE:
        return np.zeros(nv, np.float64)
    width = (
        checked_alloc_size(desc.type_length, "FLBA width",
                           column=".".join(desc.path))
        if pt == _T.FIXED_LEN_BYTE_ARRAY else 12
    )
    return np.zeros((nv, width), np.uint8)


def _empty_values(desc: ColumnDescriptor):
    """Typed empty value container for a zero-value chunk."""
    return _filler_values(desc, 0)


def _page_num_values(page: "pg.RawPage") -> Optional[int]:
    """The value count a data page's header declares, or None when the
    header lacks it (then the page cannot be null-substituted)."""
    h = page.header
    if page.page_type == PageType.DATA_PAGE and h.data_page_header is not None:
        return h.data_page_header.num_values
    if (
        page.page_type == PageType.DATA_PAGE_V2
        and h.data_page_header_v2 is not None
    ):
        return h.data_page_header_v2.num_values
    return None


def _take_values(values, keep: np.ndarray):
    """``values[keep]`` for either value container (NumPy array or
    ``ByteArrayColumn``)."""
    if isinstance(values, ByteArrayColumn):
        starts = values.offsets[:-1][keep]
        ends = values.offsets[1:][keep]
        lens = ends - starts
        offsets = np.zeros(len(lens) + 1, dtype=np.int64)
        np.cumsum(lens, out=offsets[1:])
        if len(starts) and offsets[-1]:
            # vectorized ragged gather (the row drop re-applies on every
            # decode of the group, so no per-row Python loop): for each
            # output byte, its row's source start plus its offset within
            # the row — empty rows contribute nothing and cost nothing
            lens64 = lens.astype(np.int64)
            row_of = np.repeat(np.arange(len(lens64)), lens64)
            within = np.arange(int(offsets[-1]), dtype=np.int64) \
                - np.repeat(offsets[:-1], lens64)
            data = np.asarray(values.data)[
                starts.astype(np.int64)[row_of] + within
            ]
        else:
            data = np.zeros(0, np.uint8)
        return ByteArrayColumn(offsets, np.ascontiguousarray(data, np.uint8))
    return values[keep]


def _mask_batch_rows(batch: ColumnBatch, keep: np.ndarray) -> ColumnBatch:
    """Drop the rows where ``keep`` is False from one FLAT column batch —
    the group-wide application of the row-mask salvage tier (every
    column of the row group drops the same union of damaged spans, so
    row alignment across columns is preserved exactly)."""
    desc = batch.descriptor
    if batch.def_levels is None:
        return ColumnBatch(
            desc, int(keep.sum()), _take_values(batch.values, keep),
            None, None,
        )
    defs = batch.def_levels
    present = defs == desc.max_definition_level
    value_keep = keep[present]  # values hold non-null slots, in row order
    return ColumnBatch(
        desc, int(keep.sum()), _take_values(batch.values, value_keep),
        defs[keep], None,
    )


def _concat_values(parts):
    if not parts:
        raise ValueError("no pages decoded")
    if len(parts) == 1:
        return parts[0]
    if isinstance(parts[0], ByteArrayColumn):
        pools = [p.data for p in parts]
        offs = [parts[0].offsets]
        base = parts[0].offsets[-1]
        for p in parts[1:]:
            offs.append(p.offsets[1:] + base)
            base = base + p.offsets[-1]
        return ByteArrayColumn(np.concatenate(offs), np.concatenate(pools))
    return np.concatenate(parts)


class ParquetFileReader:
    """Open a parquet file, expose footer + per-row-group columnar decode.

    ``options`` (a :class:`ReaderOptions`) is the full read-side config;
    ``verify_crc``/``salvage`` remain as positional shorthands, and a
    truthy shorthand folds into ``options`` when both are given (asking
    for CRC verification is never silently undone by also passing
    options).  With ``salvage=True`` the reader
    quarantines corrupt pages/row-group chunks instead of aborting (see
    :class:`SalvageReport`, exposed as ``self.salvage_report``); strict
    mode — the default — fails loudly on the first damaged byte.
    """

    def __init__(self, source, verify_crc: bool = False,
                 salvage: bool = False,
                 options: Optional[ReaderOptions] = None,
                 metadata: Optional[ParquetMetadata] = None):
        """``metadata``: a pre-parsed footer for THIS file, reused
        instead of re-reading and re-parsing it — how multi-epoch
        loaders re-open dataset files cheaply (the thrift footer parse
        dominates a warm re-open).  The caller owns the claim that it
        matches the source; nothing re-validates it here."""
        if options is None:
            opts = ReaderOptions(verify_crc=verify_crc, salvage=salvage)
        elif verify_crc or salvage:
            # fold truthy shorthands into the caller's options instead of
            # silently dropping them: verify_crc=True must never be
            # disabled by merely ALSO passing options=ReaderOptions(...)
            from dataclasses import replace

            opts = replace(
                options,
                verify_crc=options.verify_crc or verify_crc,
                salvage=options.salvage or salvage,
            )
        else:
            opts = options
        self.options = opts
        src = source if hasattr(source, "read_at") else FileSource(source)
        owns_source = src is not source
        if opts.io_retries > 0:
            # the shared retry/fan-out composition (docs/remote.md):
            # RetryingSource below, ParallelRangeReader above for
            # remote sources; pre-composed chains pass through so
            # attempts never multiply and the fan-out never serializes
            from ..io.remote import compose_retrying

            src = compose_retrying(
                src, opts.io_retries, opts.io_retry_backoff_s,
                deadline_s=opts.io_retry_deadline_s,
            )
        self.source = src
        try:
            self.metadata: ParquetMetadata = (
                metadata if metadata is not None else read_footer(self.source)
            )
        except BaseException:
            if owns_source:
                # corrupt-footer raises are a hot path (directory sniffs,
                # fuzz): the fd/mmap THIS constructor opened must not leak
                self.source.close()
            raise
        self.schema = self.metadata.schema
        self.verify_crc = opts.verify_crc
        self._salvage = opts.salvage
        self.salvage_report: Optional[SalvageReport] = (
            SalvageReport() if opts.salvage else None
        )
        # persistent quarantine map (salvage only): known-bad units of
        # THIS file (keyed by fingerprint) replay without decode
        # attempts; close() records what this reader's report learned
        self._qmap = opts.quarantine_map if opts.salvage else None
        self._qmap_fp: Optional[str] = None
        self._known_bad: dict = {}
        if self._qmap is not None:
            try:
                from ..quarantine import fingerprint as _q_fingerprint

                self._qmap_fp = _q_fingerprint(
                    self.source,
                    mode=getattr(self._qmap, "fingerprint", "tail"),
                )
                self._known_bad = self._qmap.known_bad(self._qmap_fp)
            except BaseException:
                if owns_source:
                    self.source.close()
                raise
        self._closed = False

    # -- parity surface ----------------------------------------------------

    @property
    def record_count(self) -> int:
        """Total rows from the footer (``getRecordCount`` parity,
        ``ParquetReader.java:219-222``)."""
        return self.metadata.num_rows

    @property
    def row_groups(self) -> List[RowGroup]:
        return self.metadata.row_groups

    def close(self) -> None:
        if not self._closed:
            if self.salvage_report is not None and self.salvage_report.skips:
                trace.decision("salvage.report", self.salvage_report.summary())
                if self._qmap is not None and self._qmap_fp is not None:
                    # remember this file's losses so the next scan skips
                    # them without re-tripping the decode errors
                    self._qmap.record(
                        self._qmap_fp, self.salvage_report,
                        path=getattr(self.source, "name", None),
                    )
            self.source.close()
            self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- decode ------------------------------------------------------------

    def _descriptor_for(self, chunk: ColumnChunk) -> ColumnDescriptor:
        path = tuple(chunk.meta_data.path_in_schema)
        return self.schema.column(path)

    def _chunk_ctx(self, desc: ColumnDescriptor,
                   row_group_index: Optional[int]) -> dict:
        return {
            "path": getattr(self.source, "name", None),
            "column": ".".join(desc.path),
            "row_group": row_group_index,
        }

    def _chunk_span(self, chunk: ColumnChunk, row_group_index: int):
        """Per-chunk decode span on the sequential read path.  A child
        of whatever span is already open (the scan executor wraps whole
        groups in "decode"): the tracer's nesting-aware stats charge
        the chunk's wall to ``decode_chunk`` and subtract it from the
        parent's exclusive time, so summing self-times never counts one
        second twice (docs/observability.md)."""
        meta = chunk.meta_data
        column = ".".join(
            (meta.path_in_schema if meta is not None else None) or ["?"]
        )
        nbytes = (
            int(meta.total_uncompressed_size or 0) if meta is not None else 0
        )
        return trace.span("decode_chunk", nbytes, attrs={
            "column": column, "row_group": row_group_index,
        })

    def read_column_chunk(
        self, chunk: ColumnChunk, row_group_index: Optional[int] = None,
        *, report: Optional[SalvageReport] = None,
    ) -> ColumnBatch:
        """Decode one column chunk.  Every failure carries file/column/
        row-group context; hostile bytes surface as taxonomy
        (:mod:`parquet_floor_tpu.errors`), never a bare crash from deep
        inside an encoding.  In salvage mode, damaged pages of flat
        OPTIONAL columns are substituted with all-null pages (recorded in
        ``report``, default ``self.salvage_report``); unrecoverable
        damage still raises, and :meth:`read_row_group` quarantines the
        whole chunk.  The row-mask tier (REQUIRED pages) only activates
        under :meth:`read_row_group`, which coordinates the row drop
        across every column of the group — a lone chunk read cannot, so
        it keeps the raise-then-quarantine contract.

        ``report`` routes the accounting to a caller-owned per-unit
        :class:`SalvageReport` — the scan faces decode units on worker
        threads into fresh reports and merge them in delivery order
        (``SalvageReport.merge``)."""
        batch, _spans = self._read_column_chunk_impl(
            chunk, row_group_index, report=report, row_mask=False
        )
        return batch

    def _read_column_chunk_impl(
        self, chunk: ColumnChunk, row_group_index: Optional[int],
        *, report: Optional[SalvageReport] = None, row_mask: bool = False,
    ):
        """Shared chunk decode + salvage accounting.  Returns
        ``(batch, drop_spans)`` — ``drop_spans`` lists the group-local
        row spans the row-mask tier wants removed (empty unless
        ``row_mask`` and a REQUIRED page was damaged)."""
        meta = chunk.meta_data
        path = getattr(self.source, "name", None)
        if meta is None:
            raise CorruptFooterError(
                "column chunk without inline metadata",
                path=path, row_group=row_group_index,
            )
        if chunk.file_path:
            raise UnsupportedFeatureError(
                "external column chunk files are not supported",
                path=path, row_group=row_group_index,
            )
        try:
            desc = self._descriptor_for(chunk)
        except (OSError, MemoryError):
            raise  # environmental, not a schema defect
        except Exception as e:
            raise CorruptFooterError(
                f"column chunk names a path missing from the schema: "
                f"{meta.path_in_schema!r}",
                path=path, row_group=row_group_index,
            ) from e
        ctx = self._chunk_ctx(desc, row_group_index)
        known = (
            self._known_bad.get((row_group_index, ctx["column"]))
            if self._known_bad else None
        )
        # the shared transient-vs-corruption ladder: belt-and-braces so a
        # corruption path no decoder anticipated still lands in the
        # taxonomy, while OSError (flaky mounts) and MemoryError (host
        # pressure) pass through — wrapping either as CorruptPageError
        # would let salvage quarantine healthy data on an environmental
        # blip
        with classified_decode_errors(CorruptPageError,
                                      "column chunk decode failed", ctx):
            batch, skips, pages_decoded = self._decode_chunk(
                chunk, desc, ctx, row_mask=row_mask, known=known
            )
        rep = report if report is not None else self.salvage_report
        if rep is not None and rep._first_count(
            ctx["column"], row_group_index, "ok"
        ):
            rep.pages_read += pages_decoded
            lost = 0
            for ordinal, n, err, kind, span, bspan in skips:
                rep.rows_quarantined += n
                lost += n
                rep.skips.append(SalvageSkip(
                    column=ctx["column"], row_group=row_group_index,
                    page=ordinal, rows=n, error=str(err), path=path,
                    kind=kind, row_span=span, byte_span=bspan,
                ))
                if kind == "dict":
                    # a dict skip is the recovery EVENT (re-derived or
                    # demoted to PLAIN), not a substituted data page:
                    # it lives in `skips` but never in pages_skipped —
                    # report and trace counter must tell the same story
                    trace.decision("salvage.dict_recovery", {
                        "column": ctx["column"],
                        "row_group": row_group_index,
                        "page": ordinal, "error": str(err),
                    })
                    continue
                rep.pages_skipped += 1
                trace.count("salvage.pages_skipped")
                trace.count("salvage.rows_quarantined", n)
                trace.decision(
                    "salvage.row_mask" if kind == "row_mask"
                    else "salvage.skip_page",
                    {
                        "column": ctx["column"],
                        "row_group": row_group_index,
                        "page": ordinal, "rows": n, "error": str(err),
                    },
                )
            rep.rows_recovered += int(meta.num_values or 0) - lost
        # spans return on EVERY decode (re-reads included): the group-wide
        # row drop is an action, not an accounting entry, and must apply
        # even when _first_count already suppressed the bookkeeping
        return batch, [
            span for _o, _n, _e, kind, span, _b in skips
            if kind == "row_mask" and span is not None
        ]

    def _map_gaps(self, known_pages: dict, start: int, length: int,
                  desc: ColumnDescriptor, row_mask: bool,
                  total_vals: int) -> dict:
        """The quarantine-map entries of this chunk whose bytes can be
        SKIPPED outright: page-tier records carrying a plausible byte
        span AND whose substitution tier applies under the current
        decode (``page_null`` needs a flat OPTIONAL column, ``row_mask``
        a flat column under a group-coordinated read).  Returns
        ``{abs_start: (abs_stop, entry)}``; empty means read the whole
        chunk (entries without spans still replay from the buffer).
        Overlapping or out-of-range spans disqualify the whole set —
        a map that mis-tiles the chunk must not corrupt the parse."""
        if not known_pages or not self._salvage:
            return {}
        flat = desc.max_repetition_level == 0
        spans = []
        for e in known_pages.values():
            bs = e.get("byte_span")
            rows = e.get("rows")
            if not bs or len(bs) != 2:
                continue
            a, b = int(bs[0]), int(bs[1])
            if not (start <= a < b <= start + length):
                continue
            if not isinstance(rows, int) or not 0 <= rows <= total_vals:
                continue
            if e.get("kind") == "page_null":
                if not (flat and desc.max_definition_level > 0):
                    continue
            elif e.get("kind") == "row_mask":
                if not (flat and row_mask):
                    continue
            else:
                continue
            spans.append((a, b, e))
        spans.sort(key=lambda s: s[0])
        for (a1, b1, _), (a2, _b2, _) in zip(spans, spans[1:]):
            if a2 < b1:
                return {}  # overlapping records: distrust the whole set
        return {a: (b, e) for a, b, e in spans}

    def _split_pages_sparse(self, start: int, length: int, total_vals: int,
                            ctx: dict, gaps: dict) -> list:
        """Chunk page scan that never reads the known-bad spans in
        ``gaps``: the complement ranges fetch as one vectored read, each
        segment parses sequentially, and every gap contributes a
        :class:`_MapGapPage` in ordinal position.  A map whose spans do
        not tile page boundaries surfaces as a framing
        ``CorruptPageError`` (the chunk then quarantines) — stale
        replay is visible loss, never silent corruption."""
        end = start + length
        segments = []  # (abs_offset, byte_length)
        cur = start
        for a in sorted(gaps):
            b, _e = gaps[a]
            if a > cur:
                segments.append((cur, a - cur))
            cur = max(cur, b)
        if cur < end:
            segments.append((cur, end - cur))
        read_many = getattr(self.source, "read_many", None)
        if read_many is not None:
            bufs = read_many(segments)
        else:
            bufs = [self.source.read_at(o, n) for o, n in segments]
        seg_by_start = {o: buf for (o, _n), buf in zip(segments, bufs)}
        pages: list = []
        pos = start
        seen = 0
        seg_off = None
        seg_buf = None
        while seen < total_vals and pos < end:
            hit = gaps.get(pos)
            if hit is not None:
                b, e = hit
                pages.append(_MapGapPage(e))
                seen += int(e.get("rows") or 0)
                pos = b
                seg_off = seg_buf = None
                continue
            if seg_buf is None:
                seg_buf = seg_by_start.get(pos)
                seg_off = pos
                if seg_buf is None:
                    raise CorruptPageError(
                        "quarantine-map byte spans do not tile the chunk "
                        "(stale sidecar?)",
                        offset=pos, **ctx,
                    )
            page, rel_end = pg.parse_page_at(
                seg_buf, pos - seg_off, ctx, len(pages), offset_base=seg_off
            )
            # re-anchor the span chunk-relative (the parse was
            # segment-relative)
            page.start = pos - start
            page.end = (seg_off + rel_end) - start
            pages.append(page)
            pos = seg_off + rel_end
            if pos - seg_off >= len(seg_buf):
                seg_off = seg_buf = None
            if page.page_type in (PageType.DATA_PAGE, PageType.DATA_PAGE_V2):
                n = _page_num_values(page)
                if n is None:
                    raise CorruptPageError(
                        "data page header is missing its num_values",
                        page=len(pages) - 1, offset=pos, **ctx,
                    )
                seen += n
        return pages

    def _decode_chunk(self, chunk: ColumnChunk, desc: ColumnDescriptor,
                      ctx: dict, row_mask: bool = False,
                      known: Optional[dict] = None):
        """Shared chunk decode.  Returns ``(batch, skips, pages_decoded)``
        where ``skips`` lists ``(page_ordinal, rows, error, kind,
        row_span)`` for units salvage absorbed (always empty in strict
        mode).  Skips are committed to the report only by the caller,
        after the chunk as a whole succeeds — a chunk that fails later
        anyway is recorded once, as one quarantined chunk.

        ``row_mask`` enables the REQUIRED-page tier (only
        :meth:`read_row_group` may set it — the row drop must apply to
        every column of the group).  ``known`` is the quarantine map's
        replay index for this chunk: listed data pages substitute their
        recorded outcome without re-attempting the decode — and, when
        the entry recorded the page's byte span, without READING the
        page's bytes either (the chunk reads as a vectored complement
        around the known-bad spans)."""
        meta = chunk.meta_data
        start, length = _chunk_byte_range(meta)
        known_pages = (known or {}).get("pages") or {}
        gaps = self._map_gaps(known_pages, start, length, desc, row_mask,
                              int(meta.num_values or 0))
        if gaps:
            raw_pages = self._split_pages_sparse(
                start, length, int(meta.num_values or 0), ctx, gaps
            )
        else:
            raw = self.source.read_at(start, length)
            raw_pages = pg.split_pages(
                raw, meta.num_values, ctx, offset_base=start
            )
        dictionary = None
        dict_seen = False
        decoded: List[pg.DecodedPage] = []
        skips: list = []
        pages_decoded = 0
        row_cursor = 0  # values before this page == rows, for flat columns
        known_pages = (known or {}).get("pages") or {}
        total_vals = int(meta.num_values or 0)
        for i, page in enumerate(raw_pages):
            pctx = {**ctx, "page": i}
            if isinstance(page, _MapGapPage):
                # page-tier map replay WITHOUT I/O: the bytes were never
                # read; substitute the recorded outcome (record fields
                # identical to a fresh scan's, byte span included)
                e = page.entry
                n = int(e.get("rows") or 0)
                rows = checked_alloc_size(n, "map-replayed page", **pctx)
                bspan = tuple(e["byte_span"])
                if e["kind"] == "page_null":
                    decoded.append(pg.DecodedPage(
                        n, _empty_values(desc),
                        np.zeros(rows, np.uint32), None,
                    ))
                    skips.append((i, n, e["error"], "page_null", None, bspan))
                else:  # row_mask (the only other kind _map_gaps admits)
                    decoded.append(pg.DecodedPage(
                        n, _filler_values(desc, rows), None, None
                    ))
                    skips.append((
                        i, n, e["error"], "row_mask",
                        (row_cursor, row_cursor + n), bspan,
                    ))
                _trace_map_skip(ctx, i, n, bspan[1] - bspan[0])
                row_cursor += n
                continue
            if page.page_type == PageType.DICTIONARY_PAGE:
                if dict_seen:
                    raise CorruptPageError(
                        "multiple dictionary pages in one chunk", **pctx
                    )
                dict_seen = True
                try:
                    dictionary = pg.decode_dictionary_page(
                        page, desc, meta.codec, self.verify_crc, pctx
                    )
                    pages_decoded += 1
                except CorruptPageError as e:
                    if not self._salvage:
                        raise
                    # dictionary tier: try to borrow a shared dictionary
                    # from another row group's chunk of the same column;
                    # failing that, fall back to PLAIN-only decode (the
                    # chunk's PLAIN pages still decode; dict-encoded
                    # pages land in the page tiers below)
                    dictionary, action = self._recover_dictionary(
                        chunk, desc, ctx, page, e
                    )
                    skips.append((i, 0, f"{action}: {e}", "dict", None, None))
            elif page.page_type in (PageType.DATA_PAGE, PageType.DATA_PAGE_V2):
                n = _page_num_values(page)
                ok_n = (
                    isinstance(n, int) and 0 <= n <= total_vals
                )
                flat = desc.max_repetition_level == 0
                kn = known_pages.get(i)
                if (
                    kn is not None and self._salvage and ok_n
                    and int(kn.get("rows") or -1) == n
                ):
                    # quarantine-map replay: substitute the recorded
                    # outcome without re-attempting the decode; the skip
                    # record (recorded error string included) is
                    # byte-identical to the one a fresh scan produces
                    if kn["kind"] == "page_null" and flat and \
                            desc.max_definition_level > 0:
                        rows = checked_alloc_size(
                            n, "salvaged null page", **pctx
                        )
                        decoded.append(pg.DecodedPage(
                            n, _empty_values(desc),
                            np.zeros(rows, np.uint32), None,
                        ))
                        skips.append((i, n, kn["error"], "page_null", None,
                                      _page_bspan(start, page)))
                        _trace_map_skip(ctx, i, n, 0)
                        row_cursor += n
                        continue
                    if kn["kind"] == "row_mask" and flat and row_mask:
                        rows = checked_alloc_size(
                            n, "row-masked page", **pctx
                        )
                        decoded.append(pg.DecodedPage(
                            n, _filler_values(desc, rows), None, None
                        ))
                        skips.append((
                            i, n, kn["error"], "row_mask",
                            (row_cursor, row_cursor + n),
                            _page_bspan(start, page),
                        ))
                        _trace_map_skip(ctx, i, n, 0)
                        row_cursor += n
                        continue
                    # stale or inapplicable entry: fall through and let
                    # the decode re-establish the truth
                try:
                    decoded.append(pg.decode_data_page(
                        page, desc, meta.codec, dictionary, self.verify_crc,
                        pctx,
                    ))
                    pages_decoded += 1
                except CorruptPageError as e:
                    # n bounded by the chunk's footer total: a corrupt
                    # header claiming absurd counts must not allocate
                    if (
                        self._salvage and ok_n and flat
                        and desc.max_definition_level > 0
                    ):
                        # flat optional column: the page's rows survive
                        # as nulls (def level 0 < max), so row alignment
                        # across columns is preserved exactly
                        rows = checked_alloc_size(
                            n, "salvaged null page", **pctx
                        )
                        decoded.append(pg.DecodedPage(
                            n, _empty_values(desc),
                            np.zeros(rows, np.uint32), None,
                        ))
                        skips.append((i, n, e, "page_null", None,
                                      _page_bspan(start, page)))
                    elif self._salvage and ok_n and flat and row_mask:
                        # flat REQUIRED column: nulls cannot stand in,
                        # but the page's ROW SPAN is known (values ==
                        # rows for flat columns) — substitute a
                        # placeholder and drop the span from the whole
                        # group (read_row_group applies the union)
                        rows = checked_alloc_size(
                            n, "row-masked page", **pctx
                        )
                        decoded.append(pg.DecodedPage(
                            n, _filler_values(desc, rows), None, None
                        ))
                        skips.append((
                            i, n, e, "row_mask",
                            (row_cursor, row_cursor + n),
                            _page_bspan(start, page),
                        ))
                    else:
                        raise
                if isinstance(n, int) and n > 0:
                    row_cursor += n
            elif page.page_type == PageType.INDEX_PAGE:
                continue
            else:
                raise CorruptPageError(
                    f"unknown page type {page.page_type}", **pctx
                )
        total = sum(d.num_values for d in decoded)
        if total != meta.num_values:
            raise CorruptPageError(
                f"chunk decoded {total} values, footer said {meta.num_values}",
                **ctx,
            )
        if not decoded:  # zero-row row group: valid, just empty
            empty_levels = (
                np.zeros(0, np.uint32) if desc.max_definition_level > 0 else None
            )
            return ColumnBatch(
                desc, 0, _empty_values(desc), empty_levels,
                np.zeros(0, np.uint32) if desc.max_repetition_level > 0 else None,
            ), skips, pages_decoded
        values = _concat_values([d.values for d in decoded])
        def_levels = (
            np.concatenate([d.def_levels for d in decoded])
            if decoded and decoded[0].def_levels is not None
            else None
        )
        rep_levels = (
            np.concatenate([d.rep_levels for d in decoded])
            if decoded and decoded[0].rep_levels is not None
            else None
        )
        batch = ColumnBatch(desc, meta.num_values, values, def_levels, rep_levels)
        return batch, skips, pages_decoded

    def _recover_dictionary(self, chunk: ColumnChunk, desc: ColumnDescriptor,
                            ctx: dict, page: "pg.RawPage", err: Exception):
        """Dictionary-page damage recovery: borrow the dictionary from
        another row group's chunk of the SAME column when the sibling's
        payload is PROVABLY the bytes the damaged page used to hold.
        Returns ``(dictionary_or_None, action)``.

        Writers commonly emit identical per-chunk dictionaries when the
        value set repeats across row groups.  But "same value count and
        size" is NOT identity — two chunks over the same value set in
        different first-occurrence order pass both and would decode
        indices through the wrong table, which is silent wrong data.
        The borrow therefore demands a byte proof: the damaged page's
        header (readable by precondition) carries the CRC32 of its
        original payload, and a sibling qualifies only when its own
        payload hashes to exactly that value.  No recorded CRC, no
        borrow — the dictionary is declared lost and only
        PLAIN(-fallback) pages survive."""
        dh = page.header.dictionary_page_header
        declared = dh.num_values if dh is not None else None
        declared_usize = page.header.uncompressed_page_size
        want_crc = page.header.crc
        rg_idx = ctx.get("row_group")
        my_path = tuple(chunk.meta_data.path_in_schema or ())
        if declared is None or declared_usize is None:
            return None, "dictionary lost (damaged header declares no shape)"
        if want_crc is None:
            return None, (
                "dictionary lost (no page CRC recorded — a borrowed "
                "dictionary cannot be proven byte-identical); PLAIN "
                "pages still decode"
            )
        for j, rg in enumerate(self.row_groups):
            if j == rg_idx:
                continue
            for other in rg.columns or []:
                om = other.meta_data
                if om is None or \
                        tuple(om.path_in_schema or ()) != my_path:
                    continue
                off = om.dictionary_page_offset
                if off is None or off <= 0:
                    continue
                end = om.data_page_offset
                max_len = (
                    int(end) - int(off)
                    if end is not None and end > off
                    else int(om.total_compressed_size or 0)
                )
                if max_len <= 0:
                    continue
                try:
                    opage = self._read_raw_page(
                        off, max_len, {**ctx, "row_group": j}
                    )
                    oh = opage.header.dictionary_page_header
                    if (
                        opage.page_type != PageType.DICTIONARY_PAGE
                        or oh is None
                        or oh.num_values != declared
                        or opage.header.uncompressed_page_size
                        != declared_usize
                        or (zlib.crc32(bytes(opage.payload)) & 0xFFFFFFFF)
                        != (want_crc & 0xFFFFFFFF)
                    ):
                        continue
                    foreign = pg.decode_dictionary_page(
                        opage, desc, om.codec, self.verify_crc,
                        {**ctx, "row_group": j, "page": 0},
                    )
                except (OSError, MemoryError):
                    raise  # environmental, never part of recovery search
                except Exception:
                    continue  # this sibling is damaged too; keep looking
                return foreign, (
                    f"dictionary re-derived from row group {j} "
                    f"({declared} values, payload CRC match)"
                )
        return None, (
            "dictionary lost (no sibling chunk proves the payload "
            "bytes); PLAIN pages still decode"
        )

    def read_row_group_ranges(
        self, index: int, row_ranges, column_filter: Optional[Set[str]] = None,
        *, report: Optional[SalvageReport] = None,
    ):
        """Selective decode: only pages whose rows intersect ``row_ranges``
        are **read from disk** and decoded, using each chunk's OffsetIndex
        (I/O-level pruning — the payoff of the page indexes; pair with
        ``Predicate.row_ranges``).

        Returns ``(batch, covered)``: ``covered`` is the list of half-open
        row ranges (page-aligned, a superset of the request) the batch's
        rows actually correspond to, identical across columns.  Chunks
        without an OffsetIndex decode fully; a whole-group request or a
        zero-range request short-circuits.

        **Salvage mode keeps the I/O pruning for CLEAN chunks.**  Each
        selected chunk first decodes only its covered pages; a chunk
        whose pruned decode trips a salvageable error WIDENS to the
        whole-chunk salvage ladder (page-null, row-mask, quarantine —
        the exact tiers :meth:`read_row_group` runs), so the quarantine
        record for damage INSIDE the cover is identical to the
        whole-group path's by construction.  Damage entirely OUTSIDE
        the cover is never decoded and therefore never discovered —
        the same contract the non-salvage pruned read has always had
        (docs/robustness.md).  Chunks lacking an OffsetIndex, or a
        cover that is the whole group, fall back to the group-wide
        delegation.  ``report`` routes per-unit accounting exactly as
        in :meth:`read_row_group`.
        """
        from ..batch.predicate import normalize_ranges

        rg = self.row_groups[index]
        n = int(rg.num_rows or 0)
        if self._salvage:
            return self._read_row_group_ranges_salvage(
                index, row_ranges, column_filter, report=report,
            )
        if not normalize_ranges(row_ranges, n):
            # predicate excluded every row — report that regardless of
            # what (or whether anything) was projected
            return RowGroupBatch([], 0), []
        chunks = [
            c for c in rg.columns or []
            if not column_filter or c.meta_data.path_in_schema[0] in column_filter
        ]
        if not chunks:
            # nothing selected (e.g. misspelled projection): mirror
            # read_row_group's empty-batch-with-rows shape rather than
            # looking like "predicate excluded every row"
            return RowGroupBatch([], n), [(0, n)] if n else []
        covered = self.page_cover(index, row_ranges, chunks)
        if covered == []:
            return RowGroupBatch([], 0), []
        if covered is None or covered == [(0, n)]:
            return (
                self.read_row_group(index, column_filter),
                [(0, n)] if n else [],
            )
        batches = []
        for chunk in chunks:
            batches.append(self._read_chunk_ranges(chunk, covered, n))
        rows = sum(b - a for a, b in covered)
        return RowGroupBatch(batches, rows), covered

    def page_cover(self, index: int, row_ranges, chunks=None):
        """Page-aligned cover of ``row_ranges`` for a row group: the
        smallest union of page spans (over EVERY given chunk) containing
        the request.  Iterated to a fixpoint because page boundaries
        differ per column.  Returns None when any chunk lacks an
        OffsetIndex (caller should decode the full group)."""
        from ..batch.predicate import normalize_ranges

        rg = self.row_groups[index]
        n = int(rg.num_rows or 0)
        covered = normalize_ranges(row_ranges, n)
        if not covered:
            return []
        if chunks is None:
            chunks = list(rg.columns or [])
        chunk_spans = []
        for chunk in chunks:
            oi = self.read_offset_index(chunk)
            if oi is None or not oi.page_locations:
                return None
            chunk_spans.append(
                [(a, b) for _pl, a, b in page_row_spans(oi, n)]
            )
        while True:
            spans = {
                (a, b)
                for cs in chunk_spans
                for a, b in cs
                if any(a < cb and ca < b for ca, cb in covered)
            }
            new = normalize_ranges(spans, n)
            if new == covered:
                return covered
            covered = new

    def _read_raw_page(self, offset: int, max_len: int,
                       ctx: Optional[dict] = None) -> "pg.RawPage":
        """Parse one page (header + payload) from a bounded byte range
        (framing validation shared with the chunk scan: ``parse_page_at``).
        """
        raw = self.source.read_at(int(offset), int(max_len))
        page, _ = pg.parse_page_at(raw, 0, ctx, None, offset_base=int(offset))
        return page

    def read_raw_column_chunk_ranges(self, chunk: ColumnChunk, covered, n: int):
        """Raw pages (dictionary page first, then only the data pages whose
        rows intersect ``covered``) — the ranged sibling of
        ``read_raw_column_chunk``.  None when the chunk has no OffsetIndex.
        """
        meta = chunk.meta_data
        oi = self.read_offset_index(chunk)
        if oi is None or not oi.page_locations:
            return None
        ctx = self._chunk_ctx(self._descriptor_for(chunk), None)
        pages = []
        if meta.dictionary_page_offset is not None and meta.dictionary_page_offset > 0:
            dict_len = int(oi.page_locations[0].offset) - int(meta.dictionary_page_offset)
            dpage = self._read_raw_page(meta.dictionary_page_offset, dict_len, ctx)
            if dpage.page_type != PageType.DICTIONARY_PAGE:
                raise CorruptPageError(
                    "expected dictionary page before data pages",
                    offset=int(meta.dictionary_page_offset), **ctx,
                )
            pages.append(dpage)
        for pl, a, b in page_row_spans(oi, n):
            if spans_overlap(a, b, covered):
                pages.append(
                    self._read_raw_page(pl.offset, pl.compressed_page_size, ctx)
                )
        return pages

    def _read_chunk_ranges(self, chunk: ColumnChunk, covered, n: int,
                           raw_pages=None) -> ColumnBatch:
        """Decode only the chunk's pages whose rows fall inside ``covered``
        (page spans of every selected chunk; reads page byte ranges —
        reused when the caller already fetched them)."""
        meta = chunk.meta_data
        desc = self._descriptor_for(chunk)
        ctx = self._chunk_ctx(desc, None)
        if raw_pages is None:
            raw_pages = self.read_raw_column_chunk_ranges(chunk, covered, n)
        dictionary = None
        decoded = []
        for i, page in enumerate(raw_pages):
            pctx = {**ctx, "page": i}
            if page.page_type == PageType.DICTIONARY_PAGE:
                dictionary = pg.decode_dictionary_page(
                    page, desc, meta.codec, self.verify_crc, pctx
                )
                continue
            decoded.append(
                pg.decode_data_page(page, desc, meta.codec, dictionary,
                                    self.verify_crc, pctx)
            )
        total = sum(d.num_values for d in decoded)
        if not decoded:
            empty_levels = (
                np.zeros(0, np.uint32) if desc.max_definition_level > 0 else None
            )
            return ColumnBatch(
                desc, 0, _empty_values(desc), empty_levels,
                np.zeros(0, np.uint32) if desc.max_repetition_level > 0 else None,
            )
        values = _concat_values([d.values for d in decoded])
        def_levels = (
            np.concatenate([d.def_levels for d in decoded])
            if decoded[0].def_levels is not None else None
        )
        rep_levels = (
            np.concatenate([d.rep_levels for d in decoded])
            if decoded[0].rep_levels is not None else None
        )
        return ColumnBatch(desc, total, values, def_levels, rep_levels)

    def read_row_group(
        self, index: int, column_filter: Optional[Set[str]] = None,
        *, report: Optional[SalvageReport] = None,
    ) -> RowGroupBatch:
        """Decode one row group into columnar batches.

        ``column_filter`` projects by **top-level field name** — exactly the
        reference's projection semantics (``ParquetReader.java:126-128``);
        None or empty means all columns (``ParquetReader.java:76``).

        ``report`` (salvage mode) routes accounting to a caller-owned
        per-unit :class:`SalvageReport` instead of the reader's shared
        one — the scan faces' merge protocol.
        """
        rg = self.row_groups[index]
        selected = []
        for chunk in rg.columns or []:
            meta = chunk.meta_data
            # a nulled/corrupt meta_data falls THROUGH to read_column_chunk,
            # which diagnoses it (CorruptFooterError, with context) — a
            # projection must never silently drop an undiagnosable chunk
            path0 = (
                meta.path_in_schema[0]
                if meta is not None and meta.path_in_schema
                else None
            )
            if column_filter and path0 is not None and path0 not in column_filter:
                continue
            selected.append(chunk)
        if not self._salvage:
            batches = []
            for c in selected:
                # per-chunk decode attribution on the sequential reader;
                # stats stay nesting-aware (StageStat.self_seconds), so
                # under the scan executor's per-group "decode" span these
                # child spans refine, never double-count, the totals
                with self._chunk_span(c, index):
                    batches.append(self.read_column_chunk(c, index))
            return RowGroupBatch(batches, rg.num_rows or 0)
        rep = report if report is not None else self.salvage_report
        # the row-mask tier needs every selected column FLAT: dropping a
        # row span from a repeated leaf would need record boundaries the
        # damaged page no longer provides — groups with repeated columns
        # keep the chunk-quarantine tier for REQUIRED damage
        allow_mask = True
        for c in selected:
            try:
                d = self._descriptor_for(c)
            except (OSError, MemoryError):
                raise
            except Exception:
                allow_mask = False
                break
            if d.max_repetition_level > 0:
                allow_mask = False
                break
        batches = []
        drops: list = []
        for chunk in selected:
            meta = chunk.meta_data
            column = ".".join(
                (meta.path_in_schema if meta is not None else None) or ["?"]
            )
            kn = self._known_bad.get((index, column))
            if kn is not None and kn.get("chunk") is not None:
                # quarantine-map short-circuit: the chunk is known
                # unrecoverable — skip its bytes entirely and replay the
                # recorded quarantine (identical record, zero decode cost)
                e = kn["chunk"]
                self._quarantine_chunk(
                    chunk, index, rg, e["error"], rep, via_map=True,
                    rows=int(e.get("rows") or 0),
                )
                continue
            try:
                with self._chunk_span(chunk, index):
                    batch, spans = self._read_column_chunk_impl(
                        chunk, index, report=rep, row_mask=allow_mask
                    )
                batches.append(batch)
                drops.extend(spans)
            except _SALVAGEABLE as e:
                self._quarantine_chunk(chunk, index, rg, e, rep)
        n_rows = int(rg.num_rows or 0)
        if not drops:
            return RowGroupBatch(batches, n_rows)
        # group-wide row mask: the union of damaged REQUIRED spans drops
        # from EVERY column, so cross-column row alignment is exact
        # (nr is the blessed footer row count — it sizes the mask)
        nr = checked_alloc_size(n_rows, "row-mask group rows",
                                row_group=index)
        keep = np.ones(nr, dtype=bool)
        for a, b in drops:
            keep[max(0, int(a)):max(0, min(nr, int(b)))] = False
        dropped = int(nr - keep.sum())
        if dropped and rep is not None and rep._first_count("*", index, "rm"):
            rep.rows_dropped += dropped
            trace.count("salvage.rows_dropped", dropped)
        batches = [_mask_batch_rows(b, keep) for b in batches]
        return RowGroupBatch(batches, int(keep.sum()))

    def _read_row_group_ranges_salvage(
        self, index: int, row_ranges,
        column_filter: Optional[Set[str]] = None,
        *, report: Optional[SalvageReport] = None,
    ):
        """Ranged read under salvage: clean chunks keep the I/O pruning
        (only covered pages are read and decoded); a chunk whose pruned
        decode trips a salvageable error WIDENS to the whole-chunk
        salvage ladder — ``_read_column_chunk_impl`` with the row-mask
        tier, then chunk quarantine — so quarantine records for damage
        inside the cover match the whole-group path's exactly
        (``SalvageReport._first_count`` dedupes across the retry).
        Widened chunks decode the full group and are sliced back to the
        covered rows; when the group holds REPEATED columns that slice
        is not expressible (``_mask_batch_rows`` is flat-only), so the
        first widen there restarts through :meth:`read_row_group` —
        correctness over pruning.  ``rows_dropped`` counts only rows
        dropped INSIDE the cover (rows outside it were never decoded).
        """
        from ..batch.predicate import normalize_ranges

        rg = self.row_groups[index]
        n = int(rg.num_rows or 0)
        if not normalize_ranges(row_ranges, n):
            return RowGroupBatch([], 0), []
        selected = []
        for chunk in rg.columns or []:
            meta = chunk.meta_data
            # nulled/corrupt meta falls THROUGH (read_row_group's rule):
            # the chunk ladder diagnoses it, projection never hides it
            path0 = (
                meta.path_in_schema[0]
                if meta is not None and meta.path_in_schema
                else None
            )
            if column_filter and path0 is not None \
                    and path0 not in column_filter:
                continue
            selected.append(chunk)
        if not selected:
            return RowGroupBatch([], n), [(0, n)] if n else []
        whole = ([(0, n)] if n else [])
        try:
            covered = self.page_cover(index, row_ranges, selected)
        except (OSError, MemoryError):
            raise
        except Exception:
            # a damaged OffsetIndex must not fail the read — the
            # group-wide ladder still decodes; the cover just falls away
            covered = None
        if covered == []:
            return RowGroupBatch([], 0), []
        if covered is None or covered == [(0, n)]:
            return (
                self.read_row_group(index, column_filter, report=report),
                whole,
            )
        rep = report if report is not None else self.salvage_report
        # same flat-columns gate as read_row_group: it bounds BOTH the
        # row-mask tier and our ability to slice a widened full-chunk
        # batch back down to the covered rows
        allow_mask = True
        for c in selected:
            try:
                d = self._descriptor_for(c)
            except (OSError, MemoryError):
                raise
            except Exception:
                allow_mask = False
                break
            if d.max_repetition_level > 0:
                allow_mask = False
                break
        nr = checked_alloc_size(n, "ranged row-mask group rows",
                                row_group=index)
        cov_mask = np.zeros(nr, dtype=bool)
        for a, b in covered:
            cov_mask[max(0, int(a)):max(0, min(nr, int(b)))] = True
        cov_rows = int(cov_mask.sum())
        batches: list = []   # (ColumnBatch, pruned: bool)
        drops: list = []
        for chunk in selected:
            meta = chunk.meta_data
            column = ".".join(
                (meta.path_in_schema if meta is not None else None) or ["?"]
            )
            kn = self._known_bad.get((index, column))
            if kn is not None and kn.get("chunk") is not None:
                e = kn["chunk"]
                self._quarantine_chunk(
                    chunk, index, rg, e["error"], rep, via_map=True,
                    rows=int(e.get("rows") or 0),
                )
                continue
            try:
                with self._chunk_span(chunk, index):
                    pruned_batch = self._read_chunk_ranges(
                        chunk, covered, n
                    )
                batches.append((pruned_batch, True))
                continue
            except (OSError, MemoryError):
                raise
            except _SALVAGEABLE:
                pass  # widen: the chunk ladder below owns the diagnosis
            trace.count("salvage.ranged_widens")
            if not allow_mask:
                # a repeated (or undiagnosable) column cannot be sliced
                # back to the cover — restart group-wide; _first_count
                # keeps the report's records identical across the retry
                return (
                    self.read_row_group(index, column_filter,
                                        report=report),
                    whole,
                )
            try:
                with self._chunk_span(chunk, index):
                    batch, spans = self._read_column_chunk_impl(
                        chunk, index, report=rep, row_mask=True
                    )
                batches.append((batch, False))
                drops.extend(spans)
            except _SALVAGEABLE as e:
                self._quarantine_chunk(chunk, index, rg, e, rep)
        keep = np.ones(nr, dtype=bool)
        for a, b in drops:
            keep[max(0, int(a)):max(0, min(nr, int(b)))] = False
        keep_cov = keep & cov_mask
        dropped = int(cov_rows - keep_cov.sum())
        if dropped and rep is not None and rep._first_count("*", index, "rm"):
            rep.rows_dropped += dropped
            trace.count("salvage.rows_dropped", dropped)
        out = []
        for batch, pruned in batches:
            if pruned:
                if dropped:
                    out.append(_mask_batch_rows(batch, keep[cov_mask]))
                else:
                    out.append(batch)
            else:
                out.append(_mask_batch_rows(batch, keep_cov))
        return RowGroupBatch(out, int(keep_cov.sum())), covered

    def _quarantine_chunk(self, chunk: ColumnChunk, index: int,
                          rg: RowGroup, err, report=None,
                          via_map: bool = False,
                          rows: Optional[int] = None) -> None:
        """Salvage mode: drop one unrecoverable column chunk, keep the
        row group's other columns.  The batch simply omits the column;
        the report and a ``trace.decision`` event record exactly what
        was lost.  ``via_map`` marks a quarantine replayed from the
        persistent map (no decode was attempted; the record is
        identical either way)."""
        rep = report if report is not None else self.salvage_report
        column = ".".join(chunk.meta_data.path_in_schema or ["?"])
        if not rep._first_count(column, index, "q"):
            return  # this chunk's loss is already on the books
        if not rows:
            rows = int(chunk.meta_data.num_values or rg.num_rows or 0)
        rep.chunks_quarantined += 1
        rep.rows_quarantined += rows
        rep.skips.append(SalvageSkip(
            column=column, row_group=index, page=None, rows=rows,
            error=str(err), path=getattr(self.source, "name", None),
            kind="chunk",
        ))
        trace.count("salvage.chunks_quarantined")
        trace.count("salvage.rows_quarantined", rows)
        if via_map:
            trace.count("salvage.map_skips")
            trace.decision("salvage.map_skip", {
                "column": column, "row_group": index, "rows": rows,
            })
            return
        trace.decision("salvage.quarantine_chunk", {
            "column": column, "row_group": index, "rows": rows,
            "error": str(err),
        })

    def iter_row_groups(
        self, column_filter: Optional[Set[str]] = None, predicate=None
    ) -> Iterator[RowGroupBatch]:
        """Decode row groups in order; with ``predicate`` (see
        ``batch.predicate.col``) groups whose statistics prove no row can
        match are skipped without reading a page."""
        indices = (
            predicate.row_groups(self)
            if predicate is not None
            else range(len(self.row_groups))
        )
        for i in indices:
            yield self.read_row_group(i, column_filter)

    def read_raw_column_chunk(self, chunk: ColumnChunk):
        """Raw page payloads + headers for a chunk (TPU engine feedstock)."""
        meta = chunk.meta_data
        start, length = _chunk_byte_range(meta)
        raw = self.source.read_at(start, length)
        return pg.split_pages(
            raw, meta.num_values,
            self._chunk_ctx(self._descriptor_for(chunk), None),
            offset_base=start,
        )

    # -- page indexes ------------------------------------------------------

    def read_column_index(self, chunk: ColumnChunk):
        """The chunk's ColumnIndex (per-page min/max/null stats), or None
        when the writer emitted none.  Parsed once per chunk (cached)."""
        from .parquet_thrift import ColumnIndex

        return self._page_index(
            chunk.column_index_offset, chunk.column_index_length, ColumnIndex
        )

    def read_offset_index(self, chunk: ColumnChunk):
        """The chunk's OffsetIndex (per-page locations/first rows), or None
        when the writer emitted none.  Parsed once per chunk (cached)."""
        from .parquet_thrift import OffsetIndex

        return self._page_index(
            chunk.offset_index_offset, chunk.offset_index_length, OffsetIndex
        )

    def _page_index(self, offset, length, struct_cls):
        if offset is None or not length:
            return None
        cache = getattr(self, "_pgidx_cache", None)
        if cache is None:
            cache = self._pgidx_cache = {}
        key = (offset, length)
        if key not in cache:
            raw = self.source.read_at(offset, length)
            cache[key], _ = struct_cls.from_bytes(raw)
        return cache[key]

    # -- bloom filters -----------------------------------------------------

    def read_bloom_filter(self, chunk: ColumnChunk):
        """The chunk's split-block Bloom filter, or None when the writer
        emitted none.  Parsed once per chunk (cached).  Writers that
        predate ``bloom_filter_length`` (field 15) get a two-step read:
        header first, then exactly ``numBytes`` of bitset."""
        from .bloom import BloomFilterHeader, SplitBlockBloomFilter
        from .thrift import CompactReader

        md = chunk.meta_data
        offset = md.bloom_filter_offset
        if offset is None:
            return None
        cache = getattr(self, "_bloom_cache", None)
        if cache is None:
            cache = self._bloom_cache = {}
        if offset not in cache:
            length = md.bloom_filter_length
            if length:
                raw = self.source.read_at(int(offset), int(length))
                cache[offset] = SplitBlockBloomFilter.from_bytes(raw)
            else:
                # header probe clamped to the file tail: a small foreign
                # file may place the filter within the last 64 bytes
                probe = min(64, self.source.size - int(offset))
                if probe <= 0:
                    raise TruncatedFileError(
                        f"bloom filter offset {offset} outside file of "
                        f"{self.source.size} bytes",
                        path=getattr(self.source, "name", None),
                        offset=int(offset),
                    )
                head = self.source.read_at(int(offset), probe)
                reader = CompactReader(head)
                header = BloomFilterHeader.read(reader)
                total = reader.pos + int(header.numBytes or 0)
                raw = self.source.read_at(int(offset), total)
                cache[offset] = SplitBlockBloomFilter.from_bytes(raw)
        return cache[offset]
