"""Schema tree: the TPU-native equivalent of parquet-mr's ``MessageType`` /
``Types`` DSL / ``ColumnDescriptor`` surface that the reference leaks into its
API (reference ``ParquetReader.java:59``, ``HydratorSupplier.java:3,15``,
``ParquetWriter.java:26``, DSL use at ``ParquetReadWriteTest.java:32-35``).

A schema is a tree of :class:`GroupType`/:class:`PrimitiveType` nodes rooted at
a :class:`MessageType`.  Leaves flatten into :class:`ColumnDescriptor`s with
Dremel max definition/repetition levels.  The ``types`` builder namespace
mirrors the reference's fluent DSL (``Types.required(INT64).named("id")``)
in idiomatic Python.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from .parquet_thrift import (
    ConvertedType,
    FieldRepetitionType,
    LogicalType,
    SchemaElement,
    Type,
)
from . import parquet_thrift as pt

REQUIRED = FieldRepetitionType.REQUIRED
OPTIONAL = FieldRepetitionType.OPTIONAL
REPEATED = FieldRepetitionType.REPEATED


# ---------------------------------------------------------------------------
# Logical type annotations (user-facing, mapped to thrift LogicalType +
# legacy ConvertedType on serialization)
# ---------------------------------------------------------------------------

class LogicalAnnotation:
    """User-facing logical type annotation.

    ``kind`` is one of STRING/ENUM/JSON/BSON/UUID/DECIMAL/DATE/TIME/TIMESTAMP/
    INTEGER/MAP/LIST/UNKNOWN/FLOAT16 with optional params.
    """

    __slots__ = ("kind", "params")

    def __init__(self, kind: str, **params):
        self.kind = kind
        self.params = params

    def __repr__(self):
        if self.params:
            inner = ", ".join(f"{k}={v}" for k, v in self.params.items())
            return f"{self.kind}({inner})"
        return self.kind

    def __eq__(self, other):
        return (
            isinstance(other, LogicalAnnotation)
            and self.kind == other.kind
            and self.params == other.params
        )

    def __hash__(self):
        return hash((self.kind, tuple(sorted(self.params.items()))))

    # --- thrift conversion -------------------------------------------------

    def to_thrift(self) -> Optional[LogicalType]:
        """Thrift LogicalType for this annotation — or None for
        INTERVAL, which exists only as a legacy ConvertedType (callers
        must treat the logicalType field as absent and rely on
        ``to_converted``)."""
        lt = LogicalType()
        k, p = self.kind, self.params
        if k == "STRING":
            lt.STRING = pt.StringType()
        elif k == "MAP":
            lt.MAP = pt.MapType()
        elif k == "LIST":
            lt.LIST = pt.ListType()
        elif k == "ENUM":
            lt.ENUM = pt.EnumType()
        elif k == "DECIMAL":
            lt.DECIMAL = pt.DecimalType(scale=p.get("scale", 0), precision=p["precision"])
        elif k == "DATE":
            lt.DATE = pt.DateType()
        elif k == "TIME":
            lt.TIME = pt.TimeType(
                isAdjustedToUTC=p.get("utc", True), unit=_time_unit(p.get("unit", "MICROS"))
            )
        elif k == "TIMESTAMP":
            lt.TIMESTAMP = pt.TimestampType(
                isAdjustedToUTC=p.get("utc", True), unit=_time_unit(p.get("unit", "MICROS"))
            )
        elif k == "INTEGER":
            lt.INTEGER = pt.IntType(
                bitWidth=p.get("bit_width", 32), isSigned=p.get("signed", True)
            )
        elif k == "UNKNOWN":
            lt.UNKNOWN = pt.NullType()
        elif k == "JSON":
            lt.JSON = pt.JsonType()
        elif k == "BSON":
            lt.BSON = pt.BsonType()
        elif k == "UUID":
            lt.UUID = pt.UUIDType()
        elif k == "FLOAT16":
            lt.FLOAT16 = pt.Float16Type()
        elif k == "INTERVAL":
            # legacy-only annotation: the thrift LogicalType union never
            # gained INTERVAL — it rides ConvertedType alone
            return None
        else:
            raise ValueError(f"unknown logical annotation {k}")
        return lt

    @classmethod
    def from_thrift(cls, lt: Optional[LogicalType]) -> Optional["LogicalAnnotation"]:
        if lt is None:
            return None
        name, v = lt.set_member()
        if name is None:
            return None
        if name == "DECIMAL":
            return cls("DECIMAL", scale=v.scale or 0, precision=v.precision)
        if name in ("TIME", "TIMESTAMP"):
            unit = "MICROS"
            if v.unit is not None:
                uname, _ = v.unit.set_member()
                unit = uname or "MICROS"
            return cls(name, utc=bool(v.isAdjustedToUTC), unit=unit)
        if name == "INTEGER":
            return cls("INTEGER", bit_width=v.bitWidth, signed=bool(v.isSigned))
        return cls(name)

    @classmethod
    def from_converted(cls, ct: Optional[int], scale=None, precision=None):
        """Map legacy ConvertedType to an annotation (for old files)."""
        if ct is None:
            return None
        m = {
            ConvertedType.UTF8: cls("STRING"),
            ConvertedType.ENUM: cls("ENUM"),
            ConvertedType.JSON: cls("JSON"),
            ConvertedType.BSON: cls("BSON"),
            ConvertedType.DATE: cls("DATE"),
            ConvertedType.MAP: cls("MAP"),
            ConvertedType.LIST: cls("LIST"),
            # INTERVAL exists only as a legacy ConvertedType (the thrift
            # LogicalType union never gained it) — parquet-mr files carry
            # it on FLBA(12) columns
            ConvertedType.INTERVAL: cls("INTERVAL"),
            ConvertedType.TIME_MILLIS: cls("TIME", utc=True, unit="MILLIS"),
            ConvertedType.TIME_MICROS: cls("TIME", utc=True, unit="MICROS"),
            ConvertedType.TIMESTAMP_MILLIS: cls("TIMESTAMP", utc=True, unit="MILLIS"),
            ConvertedType.TIMESTAMP_MICROS: cls("TIMESTAMP", utc=True, unit="MICROS"),
            ConvertedType.INT_8: cls("INTEGER", bit_width=8, signed=True),
            ConvertedType.INT_16: cls("INTEGER", bit_width=16, signed=True),
            ConvertedType.INT_32: cls("INTEGER", bit_width=32, signed=True),
            ConvertedType.INT_64: cls("INTEGER", bit_width=64, signed=True),
            ConvertedType.UINT_8: cls("INTEGER", bit_width=8, signed=False),
            ConvertedType.UINT_16: cls("INTEGER", bit_width=16, signed=False),
            ConvertedType.UINT_32: cls("INTEGER", bit_width=32, signed=False),
            ConvertedType.UINT_64: cls("INTEGER", bit_width=64, signed=False),
        }
        if ct == ConvertedType.DECIMAL:
            return cls("DECIMAL", scale=scale or 0, precision=precision or 0)
        return m.get(ct)

    def to_converted(self) -> Optional[int]:
        k, p = self.kind, self.params
        m = {
            "STRING": ConvertedType.UTF8,
            "ENUM": ConvertedType.ENUM,
            "JSON": ConvertedType.JSON,
            "BSON": ConvertedType.BSON,
            "DATE": ConvertedType.DATE,
            "MAP": ConvertedType.MAP,
            "LIST": ConvertedType.LIST,
            "DECIMAL": ConvertedType.DECIMAL,
            "INTERVAL": ConvertedType.INTERVAL,
        }
        if k in m:
            return m[k]
        if k == "TIME":
            return (
                ConvertedType.TIME_MILLIS
                if p.get("unit") == "MILLIS"
                else ConvertedType.TIME_MICROS if p.get("unit") == "MICROS" else None
            )
        if k == "TIMESTAMP":
            return (
                ConvertedType.TIMESTAMP_MILLIS
                if p.get("unit") == "MILLIS"
                else ConvertedType.TIMESTAMP_MICROS if p.get("unit") == "MICROS" else None
            )
        if k == "INTEGER":
            signed = p.get("signed", True)
            bw = p.get("bit_width", 32)
            table = {
                (8, True): ConvertedType.INT_8, (16, True): ConvertedType.INT_16,
                (32, True): ConvertedType.INT_32, (64, True): ConvertedType.INT_64,
                (8, False): ConvertedType.UINT_8, (16, False): ConvertedType.UINT_16,
                (32, False): ConvertedType.UINT_32, (64, False): ConvertedType.UINT_64,
            }
            return table.get((bw, signed))
        return None


def _time_unit(unit: str) -> pt.TimeUnit:
    tu = pt.TimeUnit()
    if unit == "MILLIS":
        tu.MILLIS = pt.MilliSeconds()
    elif unit == "MICROS":
        tu.MICROS = pt.MicroSeconds()
    elif unit == "NANOS":
        tu.NANOS = pt.NanoSeconds()
    else:
        raise ValueError(f"unknown time unit {unit}")
    return tu


string_type = lambda: LogicalAnnotation("STRING")  # noqa: E731  (DSL parity helper)


# ---------------------------------------------------------------------------
# Schema nodes
# ---------------------------------------------------------------------------

class SchemaNode:
    __slots__ = ("name", "repetition", "logical_type", "field_id")

    def __init__(self, name, repetition, logical_type=None, field_id=None):
        self.name = name
        self.repetition = repetition
        self.logical_type = logical_type
        self.field_id = field_id

    @property
    def is_primitive(self) -> bool:
        raise NotImplementedError

    @property
    def is_optional(self):
        return self.repetition == OPTIONAL

    @property
    def is_repeated(self):
        return self.repetition == REPEATED


class PrimitiveType(SchemaNode):
    __slots__ = ("physical_type", "type_length")

    def __init__(self, name, physical_type, repetition=REQUIRED, logical_type=None,
                 type_length=None, field_id=None):
        super().__init__(name, repetition, logical_type, field_id)
        self.physical_type = physical_type
        self.type_length = type_length
        if physical_type == Type.FIXED_LEN_BYTE_ARRAY and not type_length:
            raise ValueError("FIXED_LEN_BYTE_ARRAY requires type_length")

    @property
    def is_primitive(self):
        return True

    def __repr__(self):
        lt = f" ({self.logical_type})" if self.logical_type else ""
        return (
            f"{FieldRepetitionType.name(self.repetition).lower()} "
            f"{Type.name(self.physical_type).lower()} {self.name}{lt}"
        )

    def __eq__(self, other):
        return (
            isinstance(other, PrimitiveType)
            and self.name == other.name
            and self.physical_type == other.physical_type
            and self.repetition == other.repetition
            and self.logical_type == other.logical_type
            and self.type_length == other.type_length
        )

    def __hash__(self):
        return hash((self.name, self.physical_type, self.repetition))

    def stringify(self, value) -> str:
        """Debug stringifier; parity with the per-type ``stringifier()``
        used at reference ``ParquetReader.java:147-163``.  Like
        parquet-mr's ``PrimitiveStringifier`` family, rendering is
        logical-type aware: DECIMAL scales the unscaled integer, DATE and
        TIME/TIMESTAMP render ISO forms at their annotated unit, UUID is
        canonical 8-4-4-4-12, INTERVAL decomposes its (months, days,
        millis) triple; annotated strings decode UTF-8 and raw binary
        renders ``0x`` hex."""
        if value is None:
            return "null"
        lt = self.logical_type
        k = lt.kind if lt is not None else None
        if k == "DECIMAL":
            from decimal import Decimal

            unscaled = (
                int.from_bytes(value, "big", signed=True)
                if isinstance(value, bytes)
                else int(value)
            )
            # exact construction from (sign, digits, exponent): context
            # arithmetic (scaleb/division) would round past 28 digits
            digits = tuple(int(c) for c in str(abs(unscaled)))
            return str(Decimal((
                int(unscaled < 0), digits, -int(lt.params.get("scale", 0))
            )))
        if k == "DATE" and not isinstance(value, bytes):
            from datetime import date, timedelta

            return (date(1970, 1, 1) + timedelta(days=int(value))).isoformat()
        if k == "TIME" and not isinstance(value, bytes):
            v = int(value)
            unit = lt.params.get("unit", "MICROS")
            per_s = {"MILLIS": 10**3, "MICROS": 10**6, "NANOS": 10**9}[unit]
            digits = {"MILLIS": 3, "MICROS": 6, "NANOS": 9}[unit]
            s, frac = divmod(v, per_s)
            h, s = divmod(s, 3600)
            m, s = divmod(s, 60)
            return f"{h:02d}:{m:02d}:{s:02d}.{frac:0{digits}d}"
        if k == "TIMESTAMP" and not isinstance(value, bytes):
            from datetime import datetime, timedelta

            v = int(value)
            unit = lt.params.get("unit", "MICROS")
            if unit == "NANOS":
                micro, nano_rem = divmod(v, 1000)
                dt = datetime(1970, 1, 1) + timedelta(microseconds=micro)
                return dt.isoformat(timespec="microseconds") + f"{nano_rem:03d}"
            micros = v * 1000 if unit == "MILLIS" else v
            dt = datetime(1970, 1, 1) + timedelta(microseconds=micros)
            return dt.isoformat(
                timespec="milliseconds" if unit == "MILLIS" else "microseconds"
            )
        if k == "UUID" and isinstance(value, bytes) and len(value) == 16:
            import uuid as _uuid

            return str(_uuid.UUID(bytes=value))
        if k == "INTERVAL" and isinstance(value, bytes) and len(value) == 12:
            months, days, millis = (
                int.from_bytes(value[i : i + 4], "little") for i in (0, 4, 8)
            )
            return f"interval({months} months, {days} days, {millis} millis)"
        if self.physical_type in (Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY):
            if isinstance(value, bytes):
                if k in ("STRING", "ENUM", "JSON"):
                    return value.decode("utf-8", errors="replace")
                return "0x" + value.hex().upper()
            return str(value)
        if self.physical_type == Type.INT96:
            if isinstance(value, bytes):
                return "0x" + value.hex().upper()
            return str(value)
        if self.physical_type == Type.BOOLEAN:
            return "true" if value else "false"
        return str(value)


def dataset_schema_key(columns) -> list:
    """The schema facts a multi-file dataset must agree on, per column:
    path, physical type, type length, Dremel levels, and the logical
    annotation (which drives stringify/decimal-scale semantics).  Used
    by every dataset entry point so the contract is one definition."""
    return [
        (
            c.path, c.physical_type, c.type_length or 0,
            c.max_definition_level, c.max_repetition_level,
            c.primitive.logical_type,
        )
        for c in columns
    ]


class GroupType(SchemaNode):
    __slots__ = ("fields", "_index")

    def __init__(self, name, fields: Sequence[SchemaNode], repetition=REQUIRED,
                 logical_type=None, field_id=None):
        super().__init__(name, repetition, logical_type, field_id)
        self.fields = list(fields)
        names = [f.name for f in self.fields]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate field names in group {name!r}")
        self._index = {f.name: i for i, f in enumerate(self.fields)}

    @property
    def is_primitive(self):
        return False

    def field_index(self, name: str) -> int:
        """Name→index lookup (parity: ``schema.getFieldIndex`` used per write
        at reference ``ParquetWriter.java:143``)."""
        try:
            return self._index[name]
        except KeyError:
            raise KeyError(f"field {name!r} not found in group {self.name!r}") from None

    def field(self, name: str) -> SchemaNode:
        return self.fields[self.field_index(name)]

    def __contains__(self, name):
        return name in self._index

    def __repr__(self):
        inner = "; ".join(repr(f) for f in self.fields)
        return (
            f"{FieldRepetitionType.name(self.repetition).lower()} group "
            f"{self.name} {{ {inner} }}"
        )

    def __eq__(self, other):
        return (
            isinstance(other, GroupType)
            and self.name == other.name
            and self.repetition == other.repetition
            and self.logical_type == other.logical_type
            and self.fields == other.fields
        )

    def __hash__(self):
        return hash((self.name, self.repetition, len(self.fields)))


class ColumnDescriptor:
    """A flattened leaf: dotted path + Dremel levels + primitive type.

    Parity with parquet-mr's ``ColumnDescriptor`` that the reference hands to
    ``HydratorSupplier.get`` (reference ``HydratorSupplier.java:10-15``) and
    uses for projection by ``path[0]`` (``ParquetReader.java:126-128``).
    """

    __slots__ = ("path", "primitive", "max_definition_level", "max_repetition_level")

    def __init__(self, path: Tuple[str, ...], primitive: PrimitiveType,
                 max_definition_level: int, max_repetition_level: int):
        self.path = tuple(path)
        self.primitive = primitive
        self.max_definition_level = max_definition_level
        self.max_repetition_level = max_repetition_level

    @property
    def physical_type(self):
        return self.primitive.physical_type

    @property
    def type_length(self):
        return self.primitive.type_length

    @property
    def logical_type(self):
        return self.primitive.logical_type

    def __repr__(self):
        return (
            f"ColumnDescriptor({'.'.join(self.path)}: "
            f"{Type.name(self.primitive.physical_type)}, "
            f"d={self.max_definition_level}, r={self.max_repetition_level})"
        )

    def __eq__(self, other):
        return (
            isinstance(other, ColumnDescriptor)
            and self.path == other.path
            and self.primitive == other.primitive
            and self.max_definition_level == other.max_definition_level
            and self.max_repetition_level == other.max_repetition_level
        )

    def __hash__(self):
        return hash(self.path)


class MessageType(GroupType):
    """Root of a schema tree."""

    __slots__ = ("_columns", "_by_path")

    def __init__(self, name: str, fields: Sequence[SchemaNode]):
        super().__init__(name, fields, repetition=REQUIRED)
        self._columns = None
        self._by_path = None

    @property
    def columns(self) -> List[ColumnDescriptor]:
        if self._columns is None:
            cols = []

            def walk(node: SchemaNode, path, max_def, max_rep):
                if node.is_optional:
                    max_def += 1
                elif node.is_repeated:
                    max_def += 1
                    max_rep += 1
                if node.is_primitive:
                    cols.append(
                        ColumnDescriptor(path + (node.name,), node, max_def, max_rep)
                    )
                else:
                    for f in node.fields:
                        walk(f, path + (node.name,), max_def, max_rep)

            for f in self.fields:
                walk(f, (), 0, 0)
            self._columns = cols
        return self._columns

    def column(self, path) -> ColumnDescriptor:
        if isinstance(path, str):
            path = tuple(path.split("."))
        if self._by_path is None:
            self._by_path = {c.path: c for c in self.columns}
        try:
            return self._by_path[tuple(path)]
        except KeyError:
            raise KeyError(
                f"no column {path!r} in schema {self.name!r}"
            ) from None

    @property
    def is_flat(self) -> bool:
        """True when all fields are non-repeated primitives (the only shape
        the reference facade accepts — ``ParquetReader.java:200-202``)."""
        return all(f.is_primitive and not f.is_repeated for f in self.fields)

    def __repr__(self):
        inner = "; ".join(repr(f) for f in self.fields)
        return f"message {self.name} {{ {inner} }}"

    # --- thrift (de)serialization -----------------------------------------

    def to_thrift(self) -> List[SchemaElement]:
        out = [SchemaElement(name=self.name, num_children=len(self.fields))]

        def emit(node: SchemaNode):
            el = SchemaElement(name=node.name, repetition_type=node.repetition)
            if node.field_id is not None:
                el.field_id = node.field_id
            if node.logical_type is not None:
                el.logicalType = node.logical_type.to_thrift()
                el.converted_type = node.logical_type.to_converted()
                if node.logical_type.kind == "DECIMAL":
                    el.scale = node.logical_type.params.get("scale", 0)
                    el.precision = node.logical_type.params.get("precision", 0)
            if node.is_primitive:
                el.type = node.physical_type
                if node.type_length:
                    el.type_length = node.type_length
                out.append(el)
            else:
                el.num_children = len(node.fields)
                out.append(el)
                for f in node.fields:
                    emit(f)

        for f in self.fields:
            emit(f)
        return out

    @classmethod
    def from_thrift(cls, elements: Sequence[SchemaElement]) -> "MessageType":
        if not elements:
            raise ValueError("empty schema element list")
        pos = [1]

        def parse_node() -> SchemaNode:
            el = elements[pos[0]]
            pos[0] += 1
            lt = LogicalAnnotation.from_thrift(el.logicalType)
            if lt is None:
                lt = LogicalAnnotation.from_converted(el.converted_type, el.scale, el.precision)
            rep = el.repetition_type if el.repetition_type is not None else REQUIRED
            if el.num_children:
                children = [parse_node() for _ in range(el.num_children)]
                return GroupType(el.name, children, repetition=rep, logical_type=lt,
                                 field_id=el.field_id)
            return PrimitiveType(
                el.name, el.type, repetition=rep, logical_type=lt,
                type_length=el.type_length, field_id=el.field_id,
            )

        root = elements[0]
        fields = [parse_node() for _ in range(root.num_children or 0)]
        if pos[0] != len(elements):
            raise ValueError("trailing schema elements after root tree")
        return cls(root.name or "schema", fields)


# ---------------------------------------------------------------------------
# Builder DSL — parity with parquet-mr's Types DSL used by the reference test
# (reference ParquetReadWriteTest.java:32-35):
#
#   schema = types.message("msg",
#       types.required(INT64).named("id"),
#       types.required(BYTE_ARRAY).as_(types.string()).named("email"))
# ---------------------------------------------------------------------------

class _FieldBuilder:
    __slots__ = ("_ptype", "_rep", "_lt", "_tl", "_fid")

    def __init__(self, ptype, rep):
        self._ptype = ptype
        self._rep = rep
        self._lt = None
        self._tl = None
        self._fid = None

    def as_(self, annotation: LogicalAnnotation) -> "_FieldBuilder":
        self._lt = annotation
        return self

    def length(self, n: int) -> "_FieldBuilder":
        self._tl = n
        return self

    def id(self, fid: int) -> "_FieldBuilder":
        self._fid = fid
        return self

    def named(self, name: str) -> PrimitiveType:
        return PrimitiveType(
            name, self._ptype, repetition=self._rep, logical_type=self._lt,
            type_length=self._tl, field_id=self._fid,
        )


class _GroupBuilder:
    __slots__ = ("_rep", "_fields", "_lt")

    def __init__(self, rep, fields):
        self._rep = rep
        self._fields = fields
        self._lt = None

    def as_(self, annotation: LogicalAnnotation) -> "_GroupBuilder":
        self._lt = annotation
        return self

    def named(self, name: str) -> GroupType:
        return GroupType(name, self._fields, repetition=self._rep, logical_type=self._lt)


class types:
    """Fluent builder namespace (``types.required(...)`` etc.)."""

    BOOLEAN = Type.BOOLEAN
    INT32 = Type.INT32
    INT64 = Type.INT64
    INT96 = Type.INT96
    FLOAT = Type.FLOAT
    DOUBLE = Type.DOUBLE
    BYTE_ARRAY = Type.BYTE_ARRAY
    FIXED_LEN_BYTE_ARRAY = Type.FIXED_LEN_BYTE_ARRAY

    @staticmethod
    def required(ptype: int) -> _FieldBuilder:
        return _FieldBuilder(ptype, REQUIRED)

    @staticmethod
    def optional(ptype: int) -> _FieldBuilder:
        return _FieldBuilder(ptype, OPTIONAL)

    @staticmethod
    def repeated(ptype: int) -> _FieldBuilder:
        return _FieldBuilder(ptype, REPEATED)

    @staticmethod
    def required_group(*fields: SchemaNode) -> _GroupBuilder:
        return _GroupBuilder(REQUIRED, list(fields))

    @staticmethod
    def optional_group(*fields: SchemaNode) -> _GroupBuilder:
        return _GroupBuilder(OPTIONAL, list(fields))

    @staticmethod
    def repeated_group(*fields: SchemaNode) -> _GroupBuilder:
        return _GroupBuilder(REPEATED, list(fields))

    @staticmethod
    def list_of(element: SchemaNode, name: str, optional: bool = False) -> GroupType:
        """Standard 3-level LIST structure."""
        rep_group = GroupType("list", [element], repetition=REPEATED)
        return GroupType(
            name, [rep_group],
            repetition=OPTIONAL if optional else REQUIRED,
            logical_type=LogicalAnnotation("LIST"),
        )

    @staticmethod
    def map_of(key: SchemaNode, value: SchemaNode, name: str,
               optional: bool = False) -> GroupType:
        """Standard MAP structure: (optional) group MAP > repeated group
        key_value > [required key, value]."""
        kv = GroupType("key_value", [key, value], repetition=REPEATED)
        return GroupType(
            name, [kv],
            repetition=OPTIONAL if optional else REQUIRED,
            logical_type=LogicalAnnotation("MAP"),
        )

    @staticmethod
    def message(name: str, *fields: SchemaNode) -> MessageType:
        return MessageType(name, list(fields))

    @staticmethod
    def string() -> LogicalAnnotation:
        return LogicalAnnotation("STRING")

    @staticmethod
    def decimal(precision: int, scale: int = 0) -> LogicalAnnotation:
        return LogicalAnnotation("DECIMAL", precision=precision, scale=scale)

    @staticmethod
    def date() -> LogicalAnnotation:
        return LogicalAnnotation("DATE")

    @staticmethod
    def timestamp(unit: str = "MICROS", utc: bool = True) -> LogicalAnnotation:
        return LogicalAnnotation("TIMESTAMP", unit=unit, utc=utc)

    @staticmethod
    def time(unit: str = "MICROS", utc: bool = True) -> LogicalAnnotation:
        return LogicalAnnotation("TIME", unit=unit, utc=utc)

    @staticmethod
    def int_(bit_width: int, signed: bool = True) -> LogicalAnnotation:
        return LogicalAnnotation("INTEGER", bit_width=bit_width, signed=signed)

    @staticmethod
    def uuid() -> LogicalAnnotation:
        return LogicalAnnotation("UUID")

    @staticmethod
    def json() -> LogicalAnnotation:
        return LogicalAnnotation("JSON")

    @staticmethod
    def enum() -> LogicalAnnotation:
        return LogicalAnnotation("ENUM")
