"""BROTLI codec via ctypes over the system Brotli shared libraries.

The reference reads any footer-named codec by instantiating its class
through the reflection seam (``ReflectionUtils.java:10-21``), and those
codec classes are thin JNI wrappers over native libraries (snappy-java →
libsnappy, zstd-jni → libzstd).  This module is the same architecture for
Brotli: a direct binding to ``libbrotlidec``/``libbrotlienc`` (RFC 7932
reference implementation, present on any dpkg/rpm system with the
``brotli`` runtime), loaded lazily and degrading to the
``register_codec`` guidance when absent.

One-shot API only: Parquet page headers carry the exact uncompressed
size, so streaming decode buys nothing here.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import threading
from typing import Optional

from ..errors import checked_alloc_size

_dec = None
_enc = None
_tried = False
_load_lock = threading.Lock()

# BrotliDecoderResult
_DECODER_SUCCESS = 1


def _load() -> None:
    global _dec, _enc, _tried
    if _tried:
        return
    with _load_lock:
        if _tried:
            return
        _load_locked()
        _tried = True  # set last: concurrent fast-path readers must not
        #                observe _tried before _dec/_enc are assigned


def _load_locked() -> None:
    global _dec, _enc
    for name in (
        "brotlidec",            # ctypes.util resolution
        "libbrotlidec.so.1",    # common soname (no -dev package needed)
        "libbrotlidec.so",
    ):
        path = ctypes.util.find_library(name) if "." not in name else name
        if not path:
            continue
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            continue
        try:
            fn = lib.BrotliDecoderDecompress
        except AttributeError:
            continue
        fn.restype = ctypes.c_int
        fn.argtypes = [
            ctypes.c_size_t,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_size_t),
            ctypes.c_char_p,
        ]
        _dec = lib
        break
    for name in ("brotlienc", "libbrotlienc.so.1", "libbrotlienc.so"):
        path = ctypes.util.find_library(name) if "." not in name else name
        if not path:
            continue
        try:
            lib = ctypes.CDLL(path)
            cfn = lib.BrotliEncoderCompress
        except (OSError, AttributeError):
            continue
        cfn.restype = ctypes.c_int
        cfn.argtypes = [
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_size_t,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_size_t),
            ctypes.c_char_p,
        ]
        mx = lib.BrotliEncoderMaxCompressedSize
        mx.restype = ctypes.c_size_t
        mx.argtypes = [ctypes.c_size_t]
        _enc = lib
        break


def available() -> bool:
    """True when the system decode library loaded (read-side support)."""
    _load()
    return _dec is not None


def encoder_available() -> bool:
    _load()
    return _enc is not None


def decompress(data: bytes, uncompressed_size: Optional[int] = None,
               max_output: int = 1 << 28) -> bytes:
    """One-shot Brotli decode.  With ``uncompressed_size`` (the Parquet
    page header's value) the output buffer is exact; without it the
    buffer doubles until the stream fits, up to ``max_output``.

    The no-hint ladder is capped (default 256 MiB) because the one-shot
    decoder cannot distinguish "buffer too small" from "corrupt", so a
    hostile stream would otherwise cost allocations up to the full 2 GiB.
    The page-read path always passes the header's exact size; direct
    callers with legitimately larger hint-less streams raise
    ``max_output``."""
    _load()
    if _dec is None:
        raise RuntimeError("libbrotlidec not found")
    data = bytes(data)
    cap = (
        # a caller-held header field: cap it to the format's i32 range
        # before it becomes a buffer (FL-ALLOC001 at the ctypes boundary)
        checked_alloc_size(uncompressed_size, "brotli uncompressed")
        if uncompressed_size
        # the cap bounds the FIRST allocation too: a huge hostile input
        # must not force 4*len(data) bytes before the ladder even starts
        else min(max(4 * len(data), 1 << 14), max_output)
    )
    while True:
        out = ctypes.create_string_buffer(cap or 1)
        n = ctypes.c_size_t(cap)
        rc = _dec.BrotliDecoderDecompress(len(data), data, ctypes.byref(n), out)
        if rc == _DECODER_SUCCESS:
            return out.raw[: n.value]
        if uncompressed_size is not None or cap >= max_output:
            raise ValueError(
                "invalid brotli stream (or wrong size hint)"
                if uncompressed_size is not None
                else "invalid brotli stream (or output larger than "
                f"max_output={max_output} — pass uncompressed_size or "
                "raise max_output)"
            )
        cap = min(cap * 2, max_output)


def compress(data: bytes, quality: int = 5, lgwin: int = 22) -> bytes:
    _load()
    if _enc is None:
        raise RuntimeError("libbrotlienc not found")
    data = bytes(data)
    cap = int(_enc.BrotliEncoderMaxCompressedSize(len(data))) or \
        len(data) + 1024
    out = ctypes.create_string_buffer(cap)
    n = ctypes.c_size_t(cap)
    rc = _enc.BrotliEncoderCompress(
        quality, lgwin, 0, len(data), data, ctypes.byref(n), out
    )
    if rc != 1:
        raise ValueError("brotli compression failed")
    return out.raw[: n.value]
