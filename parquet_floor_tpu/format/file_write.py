"""ParquetFileWriter: from-scratch file writer (replaces the parquet-mr
writer stack behind the reference's Builder at ``ParquetWriter.java:79-106``).

Defaults pinned for parity with the reference: SNAPPY compression and v2
data pages (``ParquetWriter.java:65-66``), dictionary encoding on with
PLAIN fallback, page-level statistics, CRCs.

Write model is columnar: callers hand whole column arrays per row group
(the row-based Dehydrator API in ``api/writer.py`` buffers rows and flushes
through this).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from ..io.source import FileSink
from . import pages as pg
from .encodings import plain as e_plain
from .encodings import delta as e_delta
from .encodings import byte_stream_split as e_bss
from .encodings.dictionary import build_dictionary, encode_dict_indices
from .encodings.plain import ByteArrayColumn
from .metadata import MAGIC, serialize_footer
from .parquet_thrift import (
    ColumnChunk,
    ColumnIndex,
    ColumnMetaData,
    ColumnOrder,
    CompressionCodec,
    Encoding,
    FileMetaData,
    KeyValue,
    OffsetIndex,
    PageEncodingStats,
    PageLocation,
    PageType,
    RowGroup,
    SortingColumn,
    Statistics,
    Type,
    TypeDefinedOrder,
)
from .schema import ColumnDescriptor, MessageType

from .._version import __version__ as _pkg_version

CREATED_BY = f"parquet-floor-tpu version {_pkg_version}"

_NUMPY_DTYPE = {
    Type.INT32: np.dtype("<i4"),
    Type.INT64: np.dtype("<i8"),
    Type.FLOAT: np.dtype("<f4"),
    Type.DOUBLE: np.dtype("<f8"),
}


@dataclass
class WriterOptions:
    """The explicit config dataclass SURVEY.md §5 calls for (replacing the
    reference's deliberately-inert ``Configuration`` shim)."""

    codec: int = CompressionCodec.SNAPPY          # parity: ParquetWriter.java:65
    page_version: int = 2                         # parity: PARQUET_2_0, :66
    data_page_values: int = 20_000
    row_group_rows: int = 1 << 20
    # Byte-based thresholds, mirroring parquet-mr's size tunables (its
    # 1 MiB page / 128 MiB block defaults are what the reference's inert
    # Configuration pins).  When set they compose with the count limits:
    # a page closes at whichever bound is hit first (from a per-chunk
    # average-value-size estimate); the row-at-a-time API writer flushes
    # a row group when its buffered estimate reaches row_group_bytes.
    data_page_bytes: Optional[int] = None
    row_group_bytes: Optional[int] = None
    enable_dictionary: bool = True
    dictionary_max_fraction: float = 0.67  # fall back to PLAIN past this
    dictionary_max_bytes: int = 1 << 20
    write_statistics: bool = True
    write_crc: bool = True
    delta_integers: bool = False  # use DELTA_BINARY_PACKED for int cols
    byte_stream_split_floats: bool = False
    delta_strings: bool = False   # v2: DELTA_BYTE_ARRAY for non-dict strings
    # Split-block Bloom filters per top-level column name: True sizes from
    # the chunk's distinct count at fpp 1%, or pass {"ndv": N, "fpp": p}.
    # parquet-mr 1.12 surface (ColumnMetaData fields 14/15).
    bloom_filter_columns: Optional[Dict[str, object]] = None
    # Compression level for level-aware codecs (parquet-mr's
    # compression-level config): ZSTD 1..22, GZIP 1..9, BROTLI quality
    # 0..11; None = each codec's default.  Level-less codecs ignore it.
    codec_level: Optional[int] = None
    # Binary min/max truncation for long BYTE_ARRAY values, parquet-mr
    # semantics: min truncates to a prefix (still a lower bound); max
    # truncates-and-increments the last non-0xFF byte (still an upper
    # bound) or stays whole when every byte is 0xFF.  The ColumnIndex
    # truncates at 64 by default (parquet-mr's
    # DEFAULT_COLUMN_INDEX_TRUNCATE_LENGTH); chunk Statistics are
    # untruncated by default (1.12 behavior) — set
    # statistics_truncate_length to bound them too.
    column_index_truncate_length: int = 64
    statistics_truncate_length: Optional[int] = None
    # Per-column value-encoding overrides by top-level name (parquet-mr's
    # withByteStreamSplitEncoding/builder per-path config; pyarrow's
    # column_encoding): "PLAIN" | "DELTA_BINARY_PACKED" |
    # "BYTE_STREAM_SPLIT" | "DELTA_BYTE_ARRAY" (or the Encoding int).
    # Naming a column here disables its dictionary attempt, like pyarrow.
    column_encodings: Optional[Dict[str, object]] = None
    # Per-column dictionary enable, overriding enable_dictionary
    # (parquet-mr's withDictionaryEncoding(path, bool)).
    column_dictionary: Optional[Dict[str, bool]] = None
    # Declared sort order of the data, recorded in every row group's
    # metadata (parquet-mr's withSortingColumns — the writer does NOT
    # sort; the caller asserts the order).  Entries are a column name
    # or (name, descending, nulls_first).
    sorting_columns: Optional[List[object]] = None
    # Encode engine (docs/write.md): "host" keeps the numpy encoders;
    # "tpu" routes flat numeric columns through the fused device encode
    # programs (``write.DeviceFileWriter``), host-encoding the rest;
    # "auto" picks tpu when a usable jax backend is up.  The engine
    # selection lives in ``parquet_floor_tpu.write`` — this dataclass
    # only carries the knob so the api facade and the compactor share
    # one options surface.
    engine: str = "host"
    # DeviceFileWriter pipeline: how many row groups may be in flight
    # (device-encoded, compressing) before write_row_group blocks, and
    # the compression pool width (None = min(4, cpu)).
    write_pipeline_depth: int = 2
    compress_threads: Optional[int] = None


@dataclass
class ColumnData:
    """One column's row-group payload handed to the writer."""

    descriptor: ColumnDescriptor
    values: Union[np.ndarray, ByteArrayColumn]  # non-null values only
    def_levels: Optional[np.ndarray] = None
    rep_levels: Optional[np.ndarray] = None

    @property
    def num_values(self) -> int:
        if self.def_levels is not None:
            return len(self.def_levels)
        if isinstance(self.values, ByteArrayColumn):
            return len(self.values)
        return len(self.values)


def _lex_min_max_bytearray(col: ByteArrayColumn) -> tuple:
    """Lexicographic (min, max) of a ByteArrayColumn without
    materializing n Python bytes objects OR a padded matrix: narrow
    the candidate set one byte position at a time, gathering only the
    candidates' byte at that position (values past their length read
    as 0 — same zero-pad semantics as ``padded_matrix``), breaking
    padded ties by length (among padded-equal values the shorter is a
    strict prefix, hence the smaller).  Typically the candidate set
    collapses to a handful after 2-3 positions (~O(n) total); a low-
    cardinality column whose candidates never shrink degrades to
    O(n * max_len) gathers — which is why the caller gates this path
    to short values."""
    n = len(col)
    lengths = col.lengths()
    max_len = int(lengths.max()) if n else 0
    if max_len == 0:
        return b"", b""

    def pick(reduce_fn, tie_fn):
        cand = np.arange(n)
        for j in range(max_len):
            lens_c = lengths[cand]
            vals_j = np.zeros(len(cand), dtype=np.uint8)
            alive = lens_c > j
            if not alive.any():
                break
            vals_j[alive] = col.data[col.offsets[cand[alive]] + j]
            t = reduce_fn(vals_j)
            cand = cand[vals_j == t]
            if len(cand) == 1:
                break
        i = int(cand[tie_fn(lengths[cand])])
        return col.data[col.offsets[i] : col.offsets[i + 1]].tobytes()

    return pick(np.min, np.argmin), pick(np.max, np.argmax)


def _min_max_bytes(descriptor: ColumnDescriptor, values) -> Optional[tuple]:
    """(min_bytes, max_bytes) per the column's sort order, or None."""
    pt = descriptor.physical_type
    n = len(values)
    if n == 0:
        return None
    if isinstance(values, ByteArrayColumn):
        lengths = values.lengths()
        if n and int(lengths.max()) <= 256:
            # short values (the common string-column case): the lazy
            # narrowing scan's O(n * max_len) WORST case (constant
            # columns never shrink the candidate set) stays bounded
            return _lex_min_max_bytearray(values)
        # long values: per-value Python cost amortizes over the bytes
        lst = values.to_list()
        return min(lst), max(lst)
    if pt in _NUMPY_DTYPE:
        arr = np.asarray(values)
        if arr.dtype.kind == "f":
            finite = arr[~np.isnan(arr)]
            if len(finite) == 0:
                return None
            mn, mx = finite.min(), finite.max()
        else:
            mn, mx = arr.min(), arr.max()
        dt = _NUMPY_DTYPE[pt]
        return (
            np.asarray(mn, dtype=dt).tobytes(),
            np.asarray(mx, dtype=dt).tobytes(),
        )
    if pt == Type.BOOLEAN:
        arr = np.asarray(values, dtype=np.bool_)
        return (bytes([int(arr.min())]), bytes([int(arr.max())]))
    if pt == Type.FIXED_LEN_BYTE_ARRAY:
        rows = [bytes(r) for r in np.asarray(values)]
        return min(rows), max(rows)
    return None  # INT96: no defined order


# Per-column override surface: name → Encoding, with the physical types
# each override legally applies to (spec §Encodings; BOOLEAN only PLAIN).
_OVERRIDE_ENCODINGS = {
    "PLAIN": Encoding.PLAIN,
    "DELTA_BINARY_PACKED": Encoding.DELTA_BINARY_PACKED,
    "BYTE_STREAM_SPLIT": Encoding.BYTE_STREAM_SPLIT,
    "DELTA_BYTE_ARRAY": Encoding.DELTA_BYTE_ARRAY,
}
_OVERRIDE_TYPES = {
    Encoding.DELTA_BINARY_PACKED: {Type.INT32, Type.INT64},
    Encoding.BYTE_STREAM_SPLIT: {
        Type.FLOAT, Type.DOUBLE, Type.INT32, Type.INT64,
    },
    Encoding.DELTA_BYTE_ARRAY: {Type.BYTE_ARRAY},
}


def _normalize_encoding(sel) -> int:
    """A column_encodings value (name string or Encoding int) → int."""
    if isinstance(sel, str):
        enc = _OVERRIDE_ENCODINGS.get(sel.upper())
        if enc is None:
            raise ValueError(
                f"column_encodings: unknown encoding {sel!r} (expected one "
                f"of {sorted(_OVERRIDE_ENCODINGS)})"
            )
        return enc
    if sel in _OVERRIDE_ENCODINGS.values():
        return int(sel)
    raise ValueError(f"column_encodings: unsupported encoding {sel!r}")


def _boundary_order(desc, null_pages, mins, maxs) -> int:
    """ColumnIndex boundary_order (parquet-mr computes it so readers can
    binary-search the page bounds): 1 = ASCENDING when every non-null
    page's [min, max] is ordered against the next, 2 = DESCENDING
    symmetric, else 0 = UNORDERED (always valid).  Comparison is by the
    column's SORT ORDER, not the raw stat bytes (little-endian numeric
    encodings do not byte-compare).  Logical types that CHANGE the sort
    order away from the physical default — unsigned INTEGER (unsigned
    compare over a signed physical int), DECIMAL (signed compare over
    unsigned-lex binary), FLOAT16 — report UNORDERED, which is always
    valid; so do types with no defined order (INT96)."""
    pt = desc.physical_type
    lt = desc.primitive.logical_type
    if lt is not None:
        if lt.kind in ("DECIMAL", "FLOAT16", "UNKNOWN", "INTERVAL"):
            return 0
        if lt.kind == "INTEGER" and not lt.params.get("signed", True):
            return 0
    if pt in (Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY, Type.BOOLEAN):
        def key(b):
            return b  # unsigned-lex == stats byte order
    elif pt in _NUMPY_DTYPE:
        dt = _NUMPY_DTYPE[pt]

        def key(b):
            return np.frombuffer(b, dtype=dt)[0]
    else:
        return 0  # INT96 etc.: no defined order
    live = [
        (key(mins[i]), key(maxs[i]))
        for i in range(len(mins))
        if not null_pages[i]
    ]
    if len(live) < 2:
        return 1  # trivially ascending (parquet-mr reports ASCENDING)
    asc = all(
        live[i][0] <= live[i + 1][0] and live[i][1] <= live[i + 1][1]
        for i in range(len(live) - 1)
    )
    if asc:
        return 1
    desc_ = all(
        live[i][0] >= live[i + 1][0] and live[i][1] >= live[i + 1][1]
        for i in range(len(live) - 1)
    )
    return 2 if desc_ else 0


def _truncate_min_max(desc, mm, limit: Optional[int]):
    """Bound long BYTE_ARRAY min/max at ``limit`` bytes, keeping them
    valid bounds (parquet-mr BinaryTruncator): min → prefix; max →
    prefix with its last non-0xFF byte incremented (an all-0xFF prefix
    cannot be incremented, so the full value stays)."""
    if (
        mm is None
        or not limit
        or desc.physical_type != Type.BYTE_ARRAY
    ):
        return mm
    mn, mx = mm
    if len(mn) > limit:
        mn = mn[:limit]
    if len(mx) > limit:
        t = bytearray(mx[:limit])
        for i in range(len(t) - 1, -1, -1):
            if t[i] != 0xFF:
                t[i] += 1
                mx = bytes(t[: i + 1])
                break
        # else: every prefix byte is 0xFF — keep the full value
    return mn, mx


@dataclass
class PrecomputedPages:
    """A device-encoded column's handoff into
    :meth:`_ColumnChunkWriter.prepare` (built by ``write/encode.py``):
    the chosen value encoding, the level-position page boundaries the
    payloads were cut at, one encoded value stream per page, and — for
    the dictionary path — the host-side dictionary values the PLAIN
    dictionary page is encoded from.  Statistics, levels, page headers,
    compression, CRCs, and the page indexes all still run through the
    one host pagination path, so device-encoded chunks share every
    metadata behavior with host-encoded ones."""

    value_encoding: int
    positions: List[tuple]
    page_payloads: List[bytes]
    dictionary: object = None


@dataclass
class _PreparedChunk:
    """One column chunk, fully encoded and compressed but not yet
    written: :meth:`_ColumnChunkWriter.emit` turns it into sink bytes +
    a ``ColumnChunk`` once the row group's position is known.  Page
    payloads (``EncodedPage``) are offset-free by construction, which is
    what lets preparation run concurrently while emission stays
    strictly ordered."""

    desc: ColumnDescriptor
    value_encoding: int
    num_values: int
    dict_page: Optional[object]            # EncodedPage | None
    pages: List[object]                    # EncodedPage per data page
    page_rows: List[int]                   # num_rows per data page
    total_uncompressed: int
    total_compressed: int
    statistics: Optional[Statistics]
    # (null_pages, mins, maxs, null_counts, index_ok) or None
    index: Optional[tuple]
    data: Optional[ColumnData] = None      # kept for the bloom pass


class _ColumnChunkWriter:
    """Encodes one column's pages for one row group and tracks metadata.

    Split into :meth:`prepare` (encode + paginate + compress — no sink,
    safe to run on a worker thread) and :meth:`emit` (sequential sink
    writes + offset bookkeeping); :meth:`write` composes them for the
    plain synchronous path."""

    def __init__(self, options: WriterOptions, descriptor: ColumnDescriptor):
        self.options = options
        self.desc = descriptor

    def _choose_value_encoding(self, values) -> int:
        opt, pt = self.options, self.desc.physical_type
        override = (opt.column_encodings or {}).get(self.desc.path[0])
        if override is not None:
            return _normalize_encoding(override)
        if opt.delta_integers and pt in (Type.INT32, Type.INT64):
            return Encoding.DELTA_BINARY_PACKED
        if opt.byte_stream_split_floats and pt in (Type.FLOAT, Type.DOUBLE):
            return Encoding.BYTE_STREAM_SPLIT
        if (
            opt.delta_strings
            and opt.page_version == 2
            and pt == Type.BYTE_ARRAY
        ):
            # parquet-mr's PARQUET_2_0 writer emits DELTA_BYTE_ARRAY for
            # non-dictionary string columns (the reference pins v2)
            return Encoding.DELTA_BYTE_ARRAY
        return Encoding.PLAIN

    def _encode_values(self, values, encoding: int) -> bytes:
        pt = self.desc.physical_type
        if encoding == Encoding.PLAIN:
            return e_plain.encode_plain(values, pt, self.desc.type_length)
        if encoding == Encoding.DELTA_BINARY_PACKED:
            return e_delta.encode_delta_binary_packed(
                np.asarray(values), bit_width=32 if pt == Type.INT32 else 64
            )
        if encoding == Encoding.BYTE_STREAM_SPLIT:
            dt = _NUMPY_DTYPE[pt]
            return e_bss.encode_byte_stream_split(np.asarray(values, dtype=dt))
        if encoding == Encoding.DELTA_BYTE_ARRAY:
            col = (
                values if isinstance(values, ByteArrayColumn)
                else ByteArrayColumn.from_list([bytes(v) for v in values])
            )
            return e_delta.encode_delta_byte_array(col)
        raise ValueError(f"unsupported write encoding {Encoding.name(encoding)}")

    def _slice_values(self, values, lo: int, hi: int):
        if isinstance(values, ByteArrayColumn):
            off = values.offsets
            return ByteArrayColumn(
                off[lo : hi + 1] - off[lo],
                values.data[off[lo] : off[hi]],
            )
        return values[lo:hi]

    def write(self, sink: FileSink, data: ColumnData) -> ColumnChunk:
        return self.emit(sink, self.prepare(data))

    def prepare(self, data: ColumnData,
                pre: Optional[PrecomputedPages] = None) -> _PreparedChunk:
        opt = self.options
        desc = self.desc
        values = data.values
        n_leaf = len(values)
        num_values = data.num_values
        codec = opt.codec

        # --- choose encoding: try dictionary first -------------------------
        dictionary = None
        indices = None
        if pre is None:
            dict_enable = opt.enable_dictionary
            if opt.column_dictionary is not None:
                dict_enable = opt.column_dictionary.get(
                    desc.path[0], dict_enable
                )
            if opt.column_encodings and desc.path[0] in opt.column_encodings:
                # an explicit per-column encoding bypasses the dictionary
                # attempt entirely (pyarrow column_encoding semantics)
                dict_enable = False
            use_dict = (
                dict_enable
                and desc.physical_type != Type.BOOLEAN
                and n_leaf > 0
            )
            if use_dict:
                dictionary, indices = build_dictionary(
                    values, desc.physical_type
                )
                dict_len = len(dictionary)
                dict_bytes = (
                    int(dictionary.offsets[-1]) + 4 * dict_len
                    if isinstance(dictionary, ByteArrayColumn)
                    else dictionary.nbytes
                )
                if dict_len > max(
                    1, int(n_leaf * opt.dictionary_max_fraction)
                ) or (dict_bytes > opt.dictionary_max_bytes):
                    dictionary, indices = None, None
            value_encoding = (
                Encoding.RLE_DICTIONARY if dictionary is not None
                else self._choose_value_encoding(values)
            )
        else:
            dictionary = pre.dictionary
            value_encoding = pre.value_encoding

        dict_page = None
        total_uncompressed = 0
        total_compressed = 0

        if dictionary is not None:
            dict_page = pg.encode_dictionary_page(
                dictionary, desc, codec, opt.write_crc, opt.codec_level
            )
            hlen = len(dict_page.header_bytes())
            total_uncompressed += (
                hlen + dict_page.header.uncompressed_page_size
            )
            total_compressed += hlen + len(dict_page.body)

        # --- paginate ------------------------------------------------------
        null_count_total = 0
        # Chunk-level min/max computed over the whole value array (encoded
        # bytes are little-endian and must not be compared lexicographically).
        chunk_mm = _min_max_bytes(desc, values) if opt.write_statistics else None
        per_page = max(1, opt.data_page_values)
        if opt.data_page_bytes:
            # compose the byte bound with the count bound: estimate this
            # chunk's bytes per level slot and close pages at whichever
            # limit is hit first (parquet-mr keeps both tunables too)
            n_slots = max(data.num_values, 1)
            if dictionary is not None:
                per_val = max(len(dictionary).bit_length(), 1) / 8
            elif isinstance(values, ByteArrayColumn):
                # content size from offsets, not the backing pool: the
                # column may reference a subrange of a larger shared pool
                content = int(values.offsets[-1] - values.offsets[0])
                per_val = (content + 4 * max(len(values), 1)) / max(
                    len(values), 1
                )
            elif isinstance(values, np.ndarray):
                per_val = values.nbytes / max(values.shape[0], 1)
            else:
                per_val = 8
            per_slot = per_val * (len(values) / n_slots) + (
                0.25 if desc.max_definition_level else 0
            )
            per_page = max(1, min(per_page, int(opt.data_page_bytes / max(per_slot, 0.125))))
        max_def, max_rep = desc.max_definition_level, desc.max_repetition_level

        # Page boundaries are in *level* positions; for rep>0 keep whole rows
        # together by splitting only where rep_level == 0.
        positions = (
            pre.positions if pre is not None
            else self._page_boundaries(data, per_page)
        )
        vi = 0  # running non-null value index
        index_ok = True
        pages: List[pg.EncodedPage] = []
        page_rows: List[int] = []
        idx_null_pages: List[bool] = []
        idx_mins: List[bytes] = []
        idx_maxs: List[bytes] = []
        idx_nulls: List[int] = []
        for pi, (lo, hi) in enumerate(positions):
            dl = data.def_levels[lo:hi] if data.def_levels is not None else None
            rl = data.rep_levels[lo:hi] if data.rep_levels is not None else None
            if dl is not None:
                present = int(np.count_nonzero(dl == max_def))
            else:
                present = hi - lo
            page_vals = (
                self._slice_values(values, vi, vi + present)
                if pre is None or opt.write_statistics
                else None
            )
            idx_vals = indices[vi : vi + present] if indices is not None else None
            vi += present
            if rl is not None:
                num_rows = int(np.count_nonzero(rl == 0))
            else:
                num_rows = hi - lo

            if pre is not None:
                encoded = pre.page_payloads[pi]
            elif dictionary is not None:
                encoded = encode_dict_indices(idx_vals, len(dictionary))
            else:
                encoded = self._encode_values(page_vals, value_encoding)

            stats = None
            mm = None
            if opt.write_statistics:
                nulls = (hi - lo) - present
                null_count_total += nulls
                mm = _min_max_bytes(desc, page_vals)
                stats = Statistics(null_count=nulls)
                page_mm = _truncate_min_max(
                    desc, mm, opt.statistics_truncate_length
                )
                if page_mm is not None:
                    stats.min_value, stats.max_value = page_mm

            if opt.page_version == 2:
                ep = pg.encode_data_page_v2(
                    desc, codec, num_rows, value_encoding, encoded, dl, rl,
                    stats, opt.write_crc, opt.codec_level,
                )
            else:
                ep = pg.encode_data_page_v1(
                    desc, codec, value_encoding, encoded, dl, rl, stats,
                    opt.write_crc, num_values=hi - lo,
                    codec_level=opt.codec_level,
                )
            hlen = len(ep.header_bytes())
            total_uncompressed += hlen + ep.header.uncompressed_page_size
            total_compressed += hlen + len(ep.body)
            pages.append(ep)
            page_rows.append(num_rows)
            if opt.write_statistics:
                idx_null_pages.append(present == 0)
                if present > 0 and mm is None:
                    # e.g. an all-NaN float page: the spec requires valid
                    # bounds on every non-null page, so this chunk cannot
                    # carry a ColumnIndex at all
                    index_ok = False
                idx_mm = _truncate_min_max(
                    desc, mm, opt.column_index_truncate_length
                )
                idx_mins.append(idx_mm[0] if idx_mm is not None else b"")
                idx_maxs.append(idx_mm[1] if idx_mm is not None else b"")
                idx_nulls.append((hi - lo) - present)

        statistics = None
        if opt.write_statistics:
            statistics = Statistics(null_count=null_count_total)
            chunk_mm_t = _truncate_min_max(
                desc, chunk_mm, opt.statistics_truncate_length
            )
            if chunk_mm_t is not None:
                statistics.min_value, statistics.max_value = chunk_mm_t
        return _PreparedChunk(
            desc=desc,
            value_encoding=value_encoding,
            num_values=num_values,
            dict_page=dict_page,
            pages=pages,
            page_rows=page_rows,
            total_uncompressed=total_uncompressed,
            total_compressed=total_compressed,
            statistics=statistics,
            index=(
                (idx_null_pages, idx_mins, idx_maxs, idx_nulls, index_ok)
                if opt.write_statistics and pages
                else None
            ),
            # the decoded values are only needed past prepare() when a
            # bloom filter hashes them at emit time — dropping them
            # otherwise frees each in-flight group's dominant buffer as
            # soon as encoding finishes (the pipeline holds
            # write_pipeline_depth groups)
            data=(
                data
                if (opt.bloom_filter_columns or {}).get(desc.path[0])
                else None
            ),
        )

    def emit(self, sink: FileSink, prepared: _PreparedChunk) -> ColumnChunk:
        opt = self.options
        desc = self.desc
        first_offset = sink.pos
        dict_page_offset = None
        encoding_stats: List[PageEncodingStats] = []
        if prepared.dict_page is not None:
            dict_page_offset = sink.pos
            sink.write(prepared.dict_page.header_bytes())
            sink.write(prepared.dict_page.body)
            encoding_stats.append(
                PageEncodingStats(
                    page_type=PageType.DICTIONARY_PAGE, encoding=Encoding.PLAIN, count=1
                )
            )
        data_page_offset = None
        row_cursor = 0
        idx_loc: List[PageLocation] = []
        for ep, num_rows in zip(prepared.pages, prepared.page_rows):
            if data_page_offset is None:
                data_page_offset = sink.pos
            page_off = sink.pos
            hdr = ep.header_bytes()
            sink.write(hdr)
            sink.write(ep.body)
            if prepared.index is not None:
                idx_loc.append(PageLocation(
                    offset=page_off,
                    compressed_page_size=len(hdr) + len(ep.body),
                    first_row_index=row_cursor,
                ))
            row_cursor += num_rows
        page_type = (
            PageType.DATA_PAGE_V2 if opt.page_version == 2
            else PageType.DATA_PAGE
        )
        encoding_stats.append(
            PageEncodingStats(
                page_type=page_type, encoding=prepared.value_encoding,
                count=len(prepared.pages),
            )
        )

        max_def, max_rep = desc.max_definition_level, desc.max_repetition_level
        encodings = sorted(
            {prepared.value_encoding}
            | ({Encoding.RLE} if (max_def or max_rep or opt.page_version == 2) else set())
            | ({Encoding.PLAIN} if prepared.dict_page is not None else set())
        )
        meta = ColumnMetaData(
            type=desc.physical_type,
            encodings=list(encodings),
            path_in_schema=list(desc.path),
            codec=opt.codec,
            num_values=prepared.num_values,
            total_uncompressed_size=prepared.total_uncompressed,
            total_compressed_size=prepared.total_compressed,
            data_page_offset=data_page_offset,
            dictionary_page_offset=dict_page_offset,
            encoding_stats=encoding_stats,
        )
        if prepared.statistics is not None:
            meta.statistics = prepared.statistics
        chunk = ColumnChunk(file_offset=first_offset, meta_data=meta)
        if prepared.index is not None and idx_loc:
            # stashed for ParquetFileWriter.close(), which serializes the
            # page indexes between the last row group and the footer and
            # patches the offsets into this chunk (parquet-mr layout).
            # ColumnIndex is dropped when some non-null page has no valid
            # bounds (all-NaN pages); the OffsetIndex alone remains valid.
            idx_null_pages, idx_mins, idx_maxs, idx_nulls, index_ok = (
                prepared.index
            )
            ci = (
                ColumnIndex(
                    null_pages=idx_null_pages,
                    min_values=idx_mins,
                    max_values=idx_maxs,
                    boundary_order=_boundary_order(
                        desc, idx_null_pages, idx_mins, idx_maxs
                    ),
                    null_counts=idx_nulls,
                )
                if index_ok
                else None
            )
            chunk._pftpu_page_index = (ci, OffsetIndex(page_locations=idx_loc))
        return chunk

    def _page_boundaries(self, data: ColumnData, per_page: int):
        n = data.num_values
        if data.rep_levels is None:
            return [(i, min(i + per_page, n)) for i in range(0, n, per_page)] or [(0, 0)]
        # split only at row starts (rep == 0)
        row_starts = np.flatnonzero(np.asarray(data.rep_levels) == 0)
        bounds = []
        lo = 0
        while lo < n:
            target = lo + per_page
            nxt = row_starts[row_starts >= target]
            hi = int(nxt[0]) if len(nxt) else n
            bounds.append((lo, hi))
            lo = hi
        return bounds or [(0, 0)]


class ParquetFileWriter:
    """Writes a complete parquet file: magic, row groups, footer."""

    def __init__(self, dest, schema: MessageType, options: Optional[WriterOptions] = None,
                 key_value_metadata: Optional[Dict[str, str]] = None):
        self.sink = dest if isinstance(dest, FileSink) else FileSink(dest)
        try:
            self._init_validated(schema, options, key_value_metadata)
        except BaseException:
            # a failed construction must not leak the sink fd (the
            # option validation below raises BEFORE any byte is owned)
            self.sink.close()
            raise

    def _init_validated(self, schema: MessageType,
                        options: Optional[WriterOptions],
                        key_value_metadata: Optional[Dict[str, str]]):
        self.schema = schema
        self.options = options or WriterOptions()
        # Validate Bloom selections up front: _maybe_build_bloom runs after
        # the chunk bytes hit the sink, so a bad selection discovered there
        # would abort write_row_group mid-group with a partial file.
        for name, sel in (self.options.bloom_filter_columns or {}).items():
            if not sel:
                continue
            descs = [c for c in schema.columns if c.path[0] == name]
            if not descs:
                raise ValueError(
                    f"bloom_filter_columns: no column named {name!r}"
                )
            for d in descs:
                if d.physical_type == Type.BOOLEAN:
                    raise ValueError(
                        "bloom_filter_columns: BOOLEAN column "
                        f"{name!r} is not supported (1-bit domain; "
                        "parquet-mr refuses it too)"
                    )
        # Codec level validates up front too (an out-of-range level
        # would otherwise raise mid-write, leaving a partial file).
        from . import codecs as _codecs

        _codecs.validate_level(self.options.codec, self.options.codec_level)
        # Declared sort order resolves to leaf column indexes once.
        self._sorting: Optional[List[SortingColumn]] = None
        if self.options.sorting_columns:
            by_name = {
                ".".join(c.path): i for i, c in enumerate(schema.columns)
            }
            self._sorting = []
            for sel in self.options.sorting_columns:
                name, descending, nulls_first = (
                    (sel, False, False) if isinstance(sel, str) else sel
                )
                if name not in by_name:
                    raise ValueError(
                        f"sorting_columns: no column named {name!r}"
                    )
                self._sorting.append(SortingColumn(
                    column_idx=by_name[name],
                    descending=bool(descending),
                    nulls_first=bool(nulls_first),
                ))
        # Per-column encoding/dictionary overrides validate up front too
        # (fail before any bytes hit the sink, same as blooms).
        for sel_map, label in (
            (self.options.column_encodings, "column_encodings"),
            (self.options.column_dictionary, "column_dictionary"),
        ):
            for name in (sel_map or {}):
                if not any(c.path[0] == name for c in schema.columns):
                    raise ValueError(f"{label}: no column named {name!r}")
        for name, sel in (self.options.column_encodings or {}).items():
            enc = _normalize_encoding(sel)
            for d in schema.columns:
                if d.path[0] != name:
                    continue
                allowed = _OVERRIDE_TYPES.get(enc)
                if allowed is not None and d.physical_type not in allowed:
                    raise ValueError(
                        f"column_encodings: {Encoding.name(enc)} does not "
                        f"apply to {Type.name(d.physical_type)} column "
                        f"{name!r}"
                    )
                if d.physical_type == Type.BOOLEAN and enc != Encoding.PLAIN:
                    raise ValueError(
                        f"column_encodings: BOOLEAN column {name!r} "
                        "supports only PLAIN"
                    )
        self._row_groups: List[RowGroup] = []
        self._num_rows = 0
        self._kv = key_value_metadata or {}
        self._closed = False
        self._file_meta: Optional[FileMetaData] = None
        self.sink.write(MAGIC)

    def write_row_group(self, columns: Sequence[ColumnData]) -> None:
        if self._closed:
            raise ValueError("writer is closed")
        expected = self.schema.columns
        if len(columns) != len(expected):
            raise ValueError(
                f"row group has {len(columns)} columns, schema has {len(expected)}"
            )
        rg_start = self.sink.pos
        chunks: List[ColumnChunk] = []
        num_rows = None
        total_bytes = 0
        total_comp = 0
        for cd, desc in zip(columns, expected):
            if cd.descriptor.path != desc.path:
                raise ValueError(
                    f"column order mismatch: got {cd.descriptor.path}, want {desc.path}"
                )
            rows = (
                int(np.count_nonzero(np.asarray(cd.rep_levels) == 0))
                if cd.rep_levels is not None
                else cd.num_values
            )
            if num_rows is None:
                num_rows = rows
            elif rows != num_rows:
                raise ValueError(f"column {desc.path}: {rows} rows != {num_rows}")
            chunk = _ColumnChunkWriter(self.options, desc).write(self.sink, cd)
            self._maybe_build_bloom(chunk, desc, cd)
            total_bytes += chunk.meta_data.total_uncompressed_size
            total_comp += chunk.meta_data.total_compressed_size
            chunks.append(chunk)
        self._row_groups.append(
            RowGroup(
                columns=chunks,
                total_byte_size=total_bytes,
                num_rows=num_rows or 0,
                sorting_columns=self._sorting,
                file_offset=rg_start,
                total_compressed_size=total_comp,
                ordinal=len(self._row_groups),
            )
        )
        self._num_rows += num_rows or 0

    def write_prepared_group(self, prepared: Sequence[_PreparedChunk],
                             num_rows: int) -> None:
        """Emit one row group from already-prepared chunks (the device
        write engine's entry point — ``write/encode.py`` validates the
        columns and runs :meth:`_ColumnChunkWriter.prepare` off-thread;
        this method only does the strictly-ordered sink writes +
        metadata bookkeeping that :meth:`write_row_group` would)."""
        if self._closed:
            raise ValueError("writer is closed")
        expected = self.schema.columns
        if len(prepared) != len(expected):
            raise ValueError(
                f"row group has {len(prepared)} columns, schema has "
                f"{len(expected)}"
            )
        rg_start = self.sink.pos
        chunks: List[ColumnChunk] = []
        total_bytes = 0
        total_comp = 0
        for pc, desc in zip(prepared, expected):
            if pc.desc.path != desc.path:
                raise ValueError(
                    f"column order mismatch: got {pc.desc.path}, "
                    f"want {desc.path}"
                )
            chunk = _ColumnChunkWriter(self.options, desc).emit(self.sink, pc)
            if pc.data is not None:
                self._maybe_build_bloom(chunk, desc, pc.data)
            total_bytes += chunk.meta_data.total_uncompressed_size
            total_comp += chunk.meta_data.total_compressed_size
            chunks.append(chunk)
        self._row_groups.append(
            RowGroup(
                columns=chunks,
                total_byte_size=total_bytes,
                num_rows=num_rows,
                sorting_columns=self._sorting,
                file_offset=rg_start,
                total_compressed_size=total_comp,
                ordinal=len(self._row_groups),
            )
        )
        self._num_rows += num_rows

    def write_columns(self, columns: Dict[str, object]) -> None:
        """Convenience: dict of top-level-name → array/list (None = null).

        Repeated (nested) leaves accept per-record nested lists and are
        Dremel-shredded; a ``None`` inside maps to the *outermost* optional
        node at that position — pass an explicit ``ColumnData`` with levels
        for finer control.  Leaves under a group are keyed by dotted path.
        """
        from ..batch.nested import shred_nested

        leaves_per_top: Dict[str, int] = {}
        for d in self.schema.columns:
            leaves_per_top[d.path[0]] = leaves_per_top.get(d.path[0], 0) + 1
        cds = []
        for desc in self.schema.columns:
            key = desc.path[0] if len(desc.path) == 1 else ".".join(desc.path)
            if key not in columns:
                # a bare top-level key can only stand in for a group with
                # exactly one leaf — with several leaves the nested rows
                # would be ambiguous per leaf
                if desc.path[0] in columns and leaves_per_top[desc.path[0]] == 1:
                    key = desc.path[0]
                else:
                    raise KeyError(
                        f"write_columns: missing column {key!r} (leaves "
                        "under multi-leaf groups must be keyed by dotted "
                        "path)"
                    )
            data = columns[key]
            if isinstance(data, ColumnData):
                cds.append(data)
            elif desc.max_repetition_level > 0 or len(desc.path) > 1:
                vals, defs, reps = shred_nested(self.schema, desc, data)
                cds.append(
                    ColumnData(
                        desc,
                        _coerce_values(desc, vals),
                        def_levels=defs if desc.max_definition_level else None,
                        rep_levels=reps if desc.max_repetition_level else None,
                    )
                )
            else:
                cds.append(make_column_data(desc, data))
        self.write_row_group(cds)

    def _maybe_build_bloom(self, chunk, desc, cd: ColumnData) -> None:
        """Hash the chunk's non-null values into a split-block Bloom
        filter when the column is selected; serialized at close()."""
        sel = (self.options.bloom_filter_columns or {}).get(desc.path[0])
        if not sel:
            return
        from .bloom import (
            SplitBlockBloomFilter, hash_values, optimal_num_bytes,
            zero_variant_hashes,
        )
        from .encodings.plain import ByteArrayColumn

        values = cd.values
        if isinstance(values, ByteArrayColumn) or (
            isinstance(values, np.ndarray) and values.dtype.kind in "OSU"
        ) or isinstance(values, (list, tuple)):
            # duplicate inserts add nothing: hash each DISTINCT byte
            # string once instead of per row (the per-item Python XXH64
            # is the write path's only scalar loop)
            items = (
                values.to_list()
                if isinstance(values, ByteArrayColumn)
                else list(values)
            )
            values = list({
                v.encode("utf-8") if isinstance(v, str) else bytes(v)
                for v in items
            })
        hashes = hash_values(desc.physical_type, values)
        zv = zero_variant_hashes(desc.physical_type, values)
        if zv is not None:
            hashes = np.concatenate([hashes, zv])
        if isinstance(sel, dict):
            ndv = int(sel.get("ndv", 0)) or len(np.unique(hashes))
            fpp = float(sel.get("fpp", 0.01))
        else:
            ndv = len(np.unique(hashes))
            fpp = 0.01
        bf = SplitBlockBloomFilter(optimal_num_bytes(ndv, fpp))
        bf.insert_hashes(hashes)
        chunk._pftpu_bloom = bf

    def close(self) -> FileMetaData:
        if self._closed:
            return self._file_meta
        # bloom filters first, then page indexes — all between the last
        # row group and the footer (parquet-mr layout); offsets patch
        # into each ColumnChunk's metadata
        for rg in self._row_groups:
            for chunk in rg.columns or []:
                bf = getattr(chunk, "_pftpu_bloom", None)
                if bf is None:
                    continue
                data = bf.to_bytes()
                chunk.meta_data.bloom_filter_offset = self.sink.pos
                chunk.meta_data.bloom_filter_length = len(data)
                self.sink.write(data)
                del chunk._pftpu_bloom
        # page indexes: all ColumnIndex structs, then all OffsetIndex
        # structs, between the last row group and the footer (parquet-mr
        # layout); offsets patch into each ColumnChunk
        indexed = [
            chunk
            for rg in self._row_groups
            for chunk in (rg.columns or [])
            if getattr(chunk, "_pftpu_page_index", None) is not None
        ]
        for chunk in indexed:
            ci, _ = chunk._pftpu_page_index
            if ci is None:
                continue
            data = ci.to_bytes()
            chunk.column_index_offset = self.sink.pos
            chunk.column_index_length = len(data)
            self.sink.write(data)
        for chunk in indexed:
            _, oi = chunk._pftpu_page_index
            data = oi.to_bytes()
            chunk.offset_index_offset = self.sink.pos
            chunk.offset_index_length = len(data)
            self.sink.write(data)
            del chunk._pftpu_page_index
        fm = FileMetaData(
            version=2,
            schema=self.schema.to_thrift(),
            num_rows=self._num_rows,
            row_groups=self._row_groups,
            created_by=CREATED_BY,
            column_orders=[
                ColumnOrder(TYPE_ORDER=TypeDefinedOrder()) for _ in self.schema.columns
            ],
        )
        if self._kv:
            fm.key_value_metadata = [
                KeyValue(key=k, value=v) for k, v in self._kv.items()
            ]
        self.sink.write(serialize_footer(fm))
        self.sink.close()
        self._closed = True
        self._file_meta = fm
        return fm

    def abort(self) -> None:
        """Close the sink without finalizing the footer (error path)."""
        if not self._closed:
            self._closed = True
            self.sink.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if exc[0] is None:
            self.close()
        else:
            self.abort()


def make_column_data(desc: ColumnDescriptor, data) -> ColumnData:
    """Build ColumnData from a user array/list; None entries become nulls."""
    pt = desc.physical_type
    if desc.max_repetition_level > 0:
        raise ValueError("make_column_data handles flat columns only")
    if isinstance(data, ColumnData):
        return data
    if isinstance(data, ByteArrayColumn):
        return ColumnData(desc, data)
    items = list(data) if not isinstance(data, np.ndarray) else data
    if desc.max_definition_level > 0:
        if isinstance(items, np.ndarray):
            mask = np.zeros(len(items), dtype=bool)
            present = items
        else:
            mask = np.array([v is None for v in items], dtype=bool)
            present = [v for v in items if v is not None]
        def_levels = np.where(
            mask, desc.max_definition_level - 1, desc.max_definition_level
        ).astype(np.uint32)
        values = _coerce_values(desc, present)
        return ColumnData(desc, values, def_levels=def_levels)
    # required column: the None check is only needed on THIS branch
    # (nullable columns derive it from the mask above).  C-speed
    # membership scan (identity shortcut per element); an exotic
    # element whose __eq__ raises falls back to the identity-only
    # generator
    if not isinstance(items, np.ndarray):
        try:
            has_none = None in items
        except Exception:
            has_none = any(v is None for v in items)
        if has_none:
            raise ValueError(f"required column {desc.path} contains None")
    return ColumnData(desc, _coerce_values(desc, items))


def _coerce_values(desc: ColumnDescriptor, items):
    pt = desc.physical_type
    if pt in _NUMPY_DTYPE:
        return np.asarray(items, dtype=_NUMPY_DTYPE[pt])
    if pt == Type.BOOLEAN:
        return np.asarray(items, dtype=np.bool_)
    if pt == Type.BYTE_ARRAY:
        if isinstance(items, ByteArrayColumn):
            return items
        if type(items) is list and items and type(items[0]) is str:
            # all-str fast path: one C-level join+encode instead of n
            # encode calls.  Pure-ASCII pools have per-value byte
            # lengths equal to the str lengths (one cheap len() each);
            # a multibyte pool (isascii scan, no wasted encode) or a
            # mixed str/bytes list (join raises) falls through to the
            # loop
            try:
                joined = "".join(items)
            except TypeError:
                joined = None
            if joined is not None and joined.isascii():
                lengths = np.fromiter(
                    map(len, items), dtype=np.int64, count=len(items)
                )
                return ByteArrayColumn.from_pool(
                    lengths,
                    np.frombuffer(joined.encode(), dtype=np.uint8),
                )
        enc = [
            v.encode("utf-8") if isinstance(v, str) else bytes(v) for v in items
        ]
        return ByteArrayColumn.from_list(enc)
    if pt in (Type.FIXED_LEN_BYTE_ARRAY, Type.INT96):
        width = desc.type_length if pt == Type.FIXED_LEN_BYTE_ARRAY else 12
        if isinstance(items, np.ndarray) and items.ndim == 2:
            return np.asarray(items, dtype=np.uint8)
        rows = [bytes(v) for v in items]
        if any(len(r) != width for r in rows):
            raise ValueError(f"fixed-width column {desc.path} expects {width} bytes")
        return (
            np.frombuffer(b"".join(rows), dtype=np.uint8).reshape(-1, width).copy()
            if rows
            else np.zeros((0, width), dtype=np.uint8)
        )
    raise ValueError(f"unsupported physical type {Type.name(pt)}")
