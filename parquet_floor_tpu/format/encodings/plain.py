"""PLAIN encoding (encode + decode) for every Parquet physical type.

Vectorized NumPy reference implementation.  This is the CPU ground truth the
Pallas kernels in :mod:`parquet_floor_tpu.tpu.kernels` are tested against.

Capability parity: parquet-mr's PLAIN ValuesReader/Writer, exercised through
the reference's typed getters at ``ParquetReader.java:141-168`` and
``recordConsumer.add*`` at ``ParquetWriter.java:142-164``.

Wire format (Parquet spec):
  * BOOLEAN            — bit-packed LSB-first, one bit per value
  * INT32/INT64        — little-endian fixed width
  * FLOAT/DOUBLE       — IEEE little-endian
  * INT96              — 12 little-endian bytes (legacy timestamps)
  * BYTE_ARRAY         — 4-byte LE length prefix + bytes, back to back
  * FIXED_LEN_BYTE_ARRAY — raw bytes, ``type_length`` each
"""

from __future__ import annotations

import numpy as np

from ...errors import checked_alloc_size
from ..parquet_thrift import Type

try:  # native length-chain scanner (optional fast path)
    from ...native import binding as _native
except Exception:  # pragma: no cover
    _native = None

_FIXED_DTYPES = {
    Type.INT32: np.dtype("<i4"),
    Type.INT64: np.dtype("<i8"),
    Type.FLOAT: np.dtype("<f4"),
    Type.DOUBLE: np.dtype("<f8"),
}


class ByteArrayColumn:
    """Variable-length binary column as offsets + contiguous pool.

    TPU-friendly representation: ``data`` is a flat uint8 pool and
    ``offsets`` (int64, len n+1) delimits value *i* as
    ``data[offsets[i]:offsets[i+1]]``.  This is what ships to HBM instead of
    per-value Python objects.
    """

    __slots__ = ("offsets", "data")

    def __init__(self, offsets: np.ndarray, data: np.ndarray):
        self.offsets = np.asarray(offsets, dtype=np.int64)
        self.data = np.asarray(data, dtype=np.uint8)

    def __len__(self):
        return len(self.offsets) - 1

    def __getitem__(self, i) -> bytes:
        return self.data[self.offsets[i] : self.offsets[i + 1]].tobytes()

    def to_list(self):
        data = self.data.tobytes()
        off = self.offsets
        return [data[off[i] : off[i + 1]] for i in range(len(self))]

    def lengths(self) -> np.ndarray:
        return np.diff(self.offsets)

    def padded_matrix(self) -> np.ndarray:
        """``(n, max_len)`` uint8 matrix, each row the value zero-padded
        on the right.  Built by a ragged scatter over only the real
        content bytes — O(total bytes) work and memory, no dense
        (n, max_len) index intermediates (callers bound max_len, so the
        OUTPUT matrix is small; the inputs may not be)."""
        n = len(self)
        lengths = self.lengths()
        max_len = (checked_alloc_size(int(lengths.max()), "padded matrix width")
                   if n else 0)
        out = np.zeros((n, max_len), dtype=np.uint8)
        total = int(self.offsets[-1]) if n else 0
        if total:
            rows = np.repeat(np.arange(n), lengths)
            pos = np.arange(total) - np.repeat(self.offsets[:-1], lengths)
            out[rows, pos] = self.data[:total]
        return out

    @classmethod
    def from_list(cls, values) -> "ByteArrayColumn":
        lengths = np.fromiter((len(v) for v in values), dtype=np.int64, count=len(values))
        pool = (
            np.frombuffer(b"".join(values), dtype=np.uint8)
            if len(values)
            else np.zeros(0, np.uint8)
        )
        return cls.from_pool(lengths, pool)

    @classmethod
    def from_pool(cls, lengths: np.ndarray, pool: np.ndarray) -> "ByteArrayColumn":
        """Build from per-value byte lengths + the already-concatenated
        pool (offsets derived here, the one place that owns them)."""
        offsets = np.zeros(len(lengths) + 1, dtype=np.int64)
        np.cumsum(lengths, out=offsets[1:])
        return cls(offsets, pool)

    def take(self, idx: np.ndarray) -> "ByteArrayColumn":
        """Gather value rows by index — vectorized (the CPU shape of the
        TPU dictionary-gather kernel): one ragged source-index build over
        only the selected bytes."""
        idx = np.asarray(idx, dtype=np.int64)
        out_lengths = self.lengths()[idx]
        offsets = np.zeros(len(idx) + 1, dtype=np.int64)
        np.cumsum(out_lengths, out=offsets[1:])
        total = int(offsets[-1])
        if total == 0:
            return ByteArrayColumn(offsets, np.zeros(0, np.uint8))
        starts = self.offsets[:-1][idx]
        src = np.repeat(starts - offsets[:-1], out_lengths) + np.arange(total)
        return ByteArrayColumn(offsets, self.data[src])

    @classmethod
    def concat(cls, cols: "list[ByteArrayColumn]") -> "ByteArrayColumn":
        """Concatenate columns into one pool (the compactor's carry
        buffer flush)."""
        if not cols:
            return cls(np.zeros(1, np.int64), np.zeros(0, np.uint8))
        lengths = np.concatenate([c.lengths() for c in cols])
        pool = np.concatenate([
            c.data[c.offsets[0] : c.offsets[-1]] for c in cols
        ]) if lengths.sum() else np.zeros(0, np.uint8)
        return cls.from_pool(lengths, pool)

    def __eq__(self, other):
        if isinstance(other, ByteArrayColumn):
            return (
                np.array_equal(self.offsets, other.offsets)
                and np.array_equal(self.data, other.data)
            )
        return NotImplemented


def encode_plain(values, physical_type: int, type_length=None) -> bytes:
    """Encode values (ndarray / ByteArrayColumn / list of bytes) to PLAIN."""
    if physical_type == Type.BOOLEAN:
        bits = np.asarray(values, dtype=np.uint8)
        return np.packbits(bits, bitorder="little").tobytes()
    if physical_type in _FIXED_DTYPES:
        return np.ascontiguousarray(values, dtype=_FIXED_DTYPES[physical_type]).tobytes()
    if physical_type == Type.INT96:
        arr = np.asarray(values, dtype=np.uint8)
        if arr.ndim == 1:
            arr = arr.reshape(-1, 12)
        if arr.shape[-1] != 12:
            raise ValueError("INT96 values must be 12 bytes each")
        return arr.tobytes()
    if physical_type == Type.FIXED_LEN_BYTE_ARRAY:
        if isinstance(values, ByteArrayColumn):
            return values.data.tobytes()
        if isinstance(values, np.ndarray):
            return np.ascontiguousarray(values, dtype=np.uint8).tobytes()
        return b"".join(values)
    if physical_type == Type.BYTE_ARRAY:
        if isinstance(values, ByteArrayColumn):
            lengths = values.lengths().astype("<u4")
            n = len(values)
            total = int(values.offsets[-1]) + 4 * n
            # write side: the sizes are the caller's in-memory data, not a
            # parsed file field, so an unwritable page is API misuse
            # (ValueError), NOT corruption taxonomy — hence no
            # checked_alloc_size here, just the same i32 framing bound
            if total >= 1 << 31:
                raise ValueError(
                    f"PLAIN BYTE_ARRAY page would be {total} bytes; "
                    "pages are i32-framed — split the column into more "
                    "pages/row groups"
                )
            out = np.empty(total, dtype=np.uint8)  # floorlint: disable=FL-ALLOC001
            # interleave 4-byte lengths and payloads
            pos = 0
            data = values.data
            off = values.offsets
            lb = lengths.view(np.uint8).reshape(n, 4)
            for i in range(n):
                out[pos : pos + 4] = lb[i]
                pos += 4
                ln = off[i + 1] - off[i]
                out[pos : pos + ln] = data[off[i] : off[i + 1]]
                pos += ln
            return out.tobytes()
        parts = []
        for v in values:
            parts.append(len(v).to_bytes(4, "little"))
            parts.append(bytes(v))
        return b"".join(parts)
    raise ValueError(f"cannot PLAIN-encode physical type {Type.name(physical_type)}")


def decode_plain(data, num_values: int, physical_type: int, type_length=None, offset: int = 0):
    """Decode ``num_values`` PLAIN values; returns (values, bytes_consumed).

    ``values`` is an ndarray for fixed-width types, a :class:`ByteArrayColumn`
    for BYTE_ARRAY, an ``(n, type_length)`` uint8 ndarray for FLBA, and an
    ``(n, 12)`` uint8 ndarray for INT96.
    """
    buf = memoryview(data)[offset:]

    def _need(nbytes: int) -> None:
        if len(buf) < nbytes:
            raise ValueError(
                f"PLAIN page truncated: need {nbytes} bytes for "
                f"{num_values} values, have {len(buf)}"
            )

    if physical_type == Type.BOOLEAN:
        nbytes = (num_values + 7) // 8
        _need(nbytes)
        bits = np.unpackbits(
            np.frombuffer(buf[:nbytes], dtype=np.uint8), bitorder="little"
        )[:num_values]
        return bits.astype(np.bool_), nbytes
    if physical_type in _FIXED_DTYPES:
        dt = _FIXED_DTYPES[physical_type]
        nbytes = num_values * dt.itemsize
        _need(nbytes)
        return np.frombuffer(buf[:nbytes], dtype=dt).copy(), nbytes
    if physical_type == Type.INT96:
        nbytes = num_values * 12
        _need(nbytes)
        return (
            np.frombuffer(buf[:nbytes], dtype=np.uint8).reshape(num_values, 12).copy(),
            nbytes,
        )
    if physical_type == Type.FIXED_LEN_BYTE_ARRAY:
        if not type_length:
            raise ValueError("FIXED_LEN_BYTE_ARRAY requires type_length")
        nbytes = num_values * type_length
        _need(nbytes)
        return (
            np.frombuffer(buf[:nbytes], dtype=np.uint8)
            .reshape(num_values, type_length)
            .copy(),
            nbytes,
        )
    if physical_type == Type.BYTE_ARRAY:
        return _decode_plain_byte_array(buf, num_values)
    raise ValueError(f"cannot PLAIN-decode physical type {Type.name(physical_type)}")


def _decode_plain_byte_array(buf: memoryview, num_values: int):
    """Vectorized split of the interleaved length/payload stream.

    Strategy: lengths are data-dependent, so walk the length chain first
    (one u32 read per value — native C++ when built, Python otherwise),
    then gather payloads with one fancy index — no per-value Python bytes.
    """
    raw = np.frombuffer(buf, dtype=np.uint8)
    # num_values is a page-header field: cap it before it sizes anything
    # (nv is the checked value; the raw name stays for error messages)
    nv = checked_alloc_size(num_values, "PLAIN BYTE_ARRAY num_values")
    if _native is not None and _native.available() and nv > 64:
        starts, lengths = _native.plain_ba_scan(buf, nv)
        if len(starts) != nv:
            raise ValueError(
                f"PLAIN BYTE_ARRAY stream ended after {len(starts)} of "
                f"{num_values} values"
            )
        pos = int(starts[-1] + lengths[-1]) if nv else 0
    else:
        starts = np.empty(nv, dtype=np.int64)
        lengths = np.empty(nv, dtype=np.int64)
        pos = 0
        b = buf
        end = len(buf)
        for i in range(nv):
            if pos + 4 > end:
                raise ValueError("PLAIN BYTE_ARRAY stream truncated")
            ln = int.from_bytes(b[pos : pos + 4], "little")
            pos += 4
            if pos + ln > end:
                raise ValueError("PLAIN BYTE_ARRAY stream truncated")
            starts[i] = pos
            lengths[i] = ln
            pos += ln
    offsets = np.zeros(nv + 1, dtype=np.int64)
    np.cumsum(lengths, out=offsets[1:])
    total = checked_alloc_size(int(offsets[-1]), "PLAIN BYTE_ARRAY pool")
    pool = np.empty(total, dtype=np.uint8)
    # gather payload spans
    if nv:
        idx = np.repeat(starts - offsets[:-1], lengths) + np.arange(total)
        pool = raw[idx]
    return ByteArrayColumn(offsets, pool), pos
