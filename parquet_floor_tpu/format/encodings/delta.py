"""DELTA_BINARY_PACKED / DELTA_LENGTH_BYTE_ARRAY / DELTA_BYTE_ARRAY.

The v2 integer/binary encodings that PARQUET_2_0 writers (the reference pins
v2 at ``ParquetWriter.java:66``) may emit and every reader must handle.
NumPy reference implementation.  Delta arithmetic wraps at the **column's
physical width**: uint64 for INT64 columns (full int64 delta range
round-trips bit-exactly) and uint32 for INT32 columns (miniblock widths
must stay ≤32 — arrow's DeltaBitPackDecoder rejects wider).

Wire format (Parquet spec "Delta encoding")::

    header  := block_size varint | miniblocks_per_block varint
             | total_count varint | first_value zigzag
    block   := min_delta zigzag | bit_width byte * miniblocks
             | miniblock-packed deltas (delta - min_delta, LSB-first)

Standard geometry (also what we write): block 128, 4 miniblocks × 32 values.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from ...errors import checked_alloc_size
from .plain import ByteArrayColumn
from .rle_hybrid import bit_pack, bit_unpack, _read_varint, _write_varint

_BLOCK = 128
_MINIBLOCKS = 4
_PER_MINIBLOCK = _BLOCK // _MINIBLOCKS


def _read_zigzag(buf, pos):
    v, pos = _read_varint(buf, pos)
    return (v >> 1) ^ -(v & 1), pos


def _write_zigzag(out, n):
    _write_varint(out, ((n << 1) ^ (n >> 63)) & 0xFFFFFFFFFFFFFFFF if n < 0 else n << 1)


def decode_delta_binary_packed(data, pos: int = 0, out_dtype=np.int64):
    """Decode one DELTA_BINARY_PACKED stream; returns (values, end_pos)."""
    block_size, pos = _read_varint(data, pos)
    n_mini, pos = _read_varint(data, pos)
    raw_total, pos = _read_varint(data, pos)
    first, pos = _read_zigzag(data, pos)
    # total_count came off the wire: cap it before it drives allocation
    total = checked_alloc_size(raw_total, "DELTA_BINARY_PACKED total_count")
    if total == 0:
        return np.zeros(0, dtype=out_dtype), pos
    if n_mini == 0 or block_size % n_mini:
        raise ValueError("bad DELTA_BINARY_PACKED geometry")
    per_mini = block_size // n_mini

    n_deltas = total - 1
    deltas = np.empty(n_deltas, dtype=np.uint64)
    got = 0
    buf = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    while got < n_deltas:
        min_delta, pos = _read_zigzag(data, pos)
        widths = bytes(data[pos : pos + n_mini])
        pos += n_mini
        md = np.uint64(min_delta & 0xFFFFFFFFFFFFFFFF)
        for m in range(n_mini):
            if got >= n_deltas:
                break
            bw = widths[m]
            nbytes = per_mini * bw // 8
            take = min(per_mini, n_deltas - got)
            if bw == 0:
                vals = np.zeros(take, dtype=np.uint64)
            else:
                vals = bit_unpack(buf[pos : pos + nbytes], bw, per_mini)[:take]
            deltas[got : got + take] = vals + md  # wraps in uint64
            got += take
            pos += nbytes

    acc = np.empty(total, dtype=np.uint64)
    acc[0] = np.uint64(first & 0xFFFFFFFFFFFFFFFF)
    if n_deltas:
        np.cumsum(deltas, out=acc[1:])
        acc[1:] += acc[0]
    signed = acc.view(np.int64)
    if out_dtype == np.int32:
        return (acc & np.uint64(0xFFFFFFFF)).astype(np.uint32).view(np.int32), pos
    return signed.copy(), pos


def encode_delta_binary_packed(values: np.ndarray, bit_width: int = 0) -> bytes:
    """Encode int32/int64 values with standard 128/4 geometry.

    ``bit_width`` is the column's physical width (32 or 64); delta
    arithmetic wraps there (spec): 32-bit columns must produce ≤32-bit
    miniblock widths — 64-bit deltas on an int32 column make widths >32
    that other readers (arrow's DeltaBitPackDecoder) reject.  When 0,
    inferred from the array dtype (callers with the column descriptor in
    hand should pass it explicitly).
    """
    v = np.asarray(values)
    if bit_width not in (0, 32, 64):
        raise ValueError(f"bit_width must be 32 or 64, got {bit_width}")
    if bit_width:
        narrow = bit_width == 32
    else:
        narrow = v.dtype.itemsize <= 4 and np.issubdtype(v.dtype, np.integer)
    if narrow:
        vu = v.astype(np.int32, copy=False).view(np.uint32)
    else:
        vu = v.astype(np.int64, copy=False).view(np.uint64)
    n = len(vu)
    out = bytearray()
    _write_varint(out, _BLOCK)
    _write_varint(out, _MINIBLOCKS)
    _write_varint(out, n)
    if narrow:
        _write_zigzag(out, int(vu[0].view(np.int32)) if n else 0)
    else:
        _write_zigzag(out, int(vu[0].view(np.int64)) if n else 0)
    if n <= 1:
        return bytes(out)
    deltas = (vu[1:] - vu[:-1]).astype(np.uint64)  # wraparound at width
    if narrow:
        # reinterpret each 32-bit wrapped delta as signed, pick min there
        sdeltas = deltas.astype(np.uint32).view(np.int32).astype(np.int64)
    else:
        sdeltas = deltas.view(np.int64)
    n_deltas = len(deltas)
    mask = np.uint64(0xFFFFFFFF) if narrow else np.uint64(0xFFFFFFFFFFFFFFFF)
    for b0 in range(0, n_deltas, _BLOCK):
        block = deltas[b0 : b0 + _BLOCK]
        sblock = sdeltas[b0 : b0 + _BLOCK]
        min_delta = int(sblock.min())
        _write_zigzag(out, min_delta)
        adj = (block - np.uint64(min_delta & int(mask))) & mask
        widths = []
        packed_parts = []
        for m in range(_MINIBLOCKS):
            mb = adj[m * _PER_MINIBLOCK : (m + 1) * _PER_MINIBLOCK]
            if len(mb) == 0:
                widths.append(0)
                packed_parts.append(b"")
                continue
            maxv = int(mb.max())
            bw = maxv.bit_length()
            widths.append(bw)
            if bw == 0:
                packed_parts.append(b"")
                continue
            full = np.zeros(_PER_MINIBLOCK, dtype=np.uint64)
            full[: len(mb)] = mb
            packed_parts.append(bit_pack(full, bw))
        out.extend(bytes(widths))
        for p in packed_parts:
            out.extend(p)
    return bytes(out)


def decode_delta_length_byte_array(data, pos: int = 0) -> Tuple[ByteArrayColumn, int]:
    lengths, pos = decode_delta_binary_packed(data, pos)
    lengths = lengths.astype(np.int64)
    n = len(lengths)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(lengths, out=offsets[1:])
    # the lengths are parsed data: a corrupt (negative/huge) sum must not
    # reach np.frombuffer as its count
    total = checked_alloc_size(int(offsets[-1]), "DELTA_LENGTH_BYTE_ARRAY pool")
    pool = (
        np.frombuffer(data, dtype=np.uint8, count=total, offset=pos).copy()
        if total
        else np.zeros(0, np.uint8)
    )
    return ByteArrayColumn(offsets, pool), pos + total


def encode_delta_length_byte_array(col: ByteArrayColumn) -> bytes:
    lengths = col.lengths().astype(np.int32)
    return encode_delta_binary_packed(lengths) + col.data.tobytes()


def decode_delta_byte_array(data, pos: int = 0) -> Tuple[ByteArrayColumn, int]:
    """Incremental (front-coded) binary: shared prefix lengths + suffixes."""
    prefix_lens, pos = decode_delta_binary_packed(data, pos)
    suffixes, pos = decode_delta_length_byte_array(data, pos)
    n = len(prefix_lens)
    if n != len(suffixes):
        raise ValueError("DELTA_BYTE_ARRAY prefix/suffix count mismatch")
    values = []
    prev = b""
    sdata = suffixes.data.tobytes()
    soff = suffixes.offsets
    for i in range(n):
        cur = prev[: prefix_lens[i]] + sdata[soff[i] : soff[i + 1]]
        values.append(cur)
        prev = cur
    return ByteArrayColumn.from_list(values), pos


def encode_delta_byte_array(col: ByteArrayColumn) -> bytes:
    values = col.to_list()
    n = len(values)
    prefix_lens = np.zeros(n, dtype=np.int32)
    suffixes = []
    prev = b""
    for i, cur in enumerate(values):
        k = 0
        m = min(len(prev), len(cur))
        while k < m and prev[k] == cur[k]:
            k += 1
        prefix_lens[i] = k
        suffixes.append(cur[k:])
        prev = cur
    return encode_delta_binary_packed(prefix_lens) + encode_delta_length_byte_array(
        ByteArrayColumn.from_list(suffixes)
    )
