"""Dictionary encoding: build/encode dictionaries and index streams.

RLE_DICTIONARY (and legacy PLAIN_DICTIONARY) data pages carry a bit-width
byte followed by an RLE/bit-packed-hybrid index stream; the dictionary page
itself is PLAIN-encoded.  Capability parity: parquet-mr's dictionary
writer/reader pair behind the reference's column readers
(``ParquetReader.java:141-168``); the dictionary *gather* is the TPU hot path
(``tpu/kernels``).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from ..parquet_thrift import Type
from .plain import ByteArrayColumn, decode_plain, encode_plain
from .rle_hybrid import decode_rle_hybrid, encode_rle_hybrid, min_bit_width


def build_dictionary(values, physical_type: int):
    """Deduplicate values in first-appearance order.

    Returns ``(dictionary, indices: uint32 ndarray)`` where dictionary is an
    ndarray or ByteArrayColumn matching the PLAIN value representation.
    First-appearance order matches what incremental writers produce and keeps
    encodings deterministic.
    """
    if physical_type == Type.BYTE_ARRAY or isinstance(values, ByteArrayColumn):
        if isinstance(values, ByteArrayColumn):
            col, vals = values, None
            n = len(col)
        else:
            vals = [bytes(v) for v in values]
            col = None
            n = len(vals)
        if n:
            # native O(n) hash dedup when the C++ runtime is loaded —
            # any value length, no padded keys, no sort
            from ...native import binding as _nat

            if _nat.available():
                if col is None:
                    col = ByteArrayColumn.from_list(vals)
                indices, uniq_ids = _nat.dedup_bytes(col.offsets, col.data)
                uniq = [
                    col.data[col.offsets[i] : col.offsets[i + 1]].tobytes()
                    for i in uniq_ids
                ]
                return ByteArrayColumn.from_list(uniq), indices
        # numpy fallback (no native runtime); max_len only matters here
        if col is not None:
            max_len = int(col.lengths().max()) if n else 0
        else:
            max_len = max(map(len, vals), default=0)
        if n and max_len <= 64:
            # vectorized dedup: each value becomes a fixed-width key of
            # (length LE32 ‖ zero-padded content) — the explicit length
            # disambiguates zero-padding ("a" vs "a\x00") — then one
            # np.unique over the void view.  Bounded to short values so
            # the (n, 4+max_len) key matrix cannot blow up on one huge
            # outlier; dictionary-worthy columns are short-string ones
            if col is None:
                col = ByteArrayColumn.from_list(vals)
            lengths = col.lengths()
            # the branch guard bounds max_len ≤ 64; min() re-states it at
            # the allocation so the (n, 4+max_len) matrix provably cannot
            # blow up on one huge outlier
            keys = np.zeros((n, 4 + min(max_len, 64)), dtype=np.uint8)
            keys[:, :4] = lengths.astype(np.uint32)[:, None].view(np.uint8).reshape(n, 4)
            keys[:, 4:] = col.padded_matrix()
            void = np.ascontiguousarray(keys).view(
                np.dtype((np.void, keys.shape[1]))
            ).reshape(-1)
            _, idx_first, inverse = np.unique(
                void, return_index=True, return_inverse=True
            )
            order = np.argsort(idx_first, kind="stable")
            rank = np.empty_like(order)
            rank[order] = np.arange(len(order))
            indices = rank[inverse.reshape(-1)].astype(np.uint32)
            uniq_rows = keys[np.sort(idx_first)]
            uniq_lens = (
                uniq_rows[:, :4].copy().view(np.uint32).reshape(-1)
            )
            uniq = [
                uniq_rows[i, 4 : 4 + int(uniq_lens[i])].tobytes()
                for i in range(len(uniq_rows))
            ]
            return ByteArrayColumn.from_list(uniq), indices
        if vals is None:
            vals = col.to_list()
        seen = {}
        indices = np.empty(len(vals), dtype=np.uint32)
        uniq = []
        for i, v in enumerate(vals):
            j = seen.get(v)
            if j is None:
                j = len(uniq)
                seen[v] = j
                uniq.append(v)
            indices[i] = j
        return ByteArrayColumn.from_list(uniq), indices
    arr = np.asarray(values)
    from ...native import binding as _nat

    if _nat.available() and len(arr):
        # the byte-slice hash dedup handles fixed-width values too:
        # synthetic offsets stride the flattened little-endian bytes
        flat = np.ascontiguousarray(arr)
        width = flat.itemsize * (
            flat.shape[1] if flat.ndim == 2 else 1
        )
        offsets = np.arange(len(arr) + 1, dtype=np.int64) * width
        indices, uniq_ids = _nat.dedup_bytes(
            offsets, flat.view(np.uint8).reshape(-1)
        )
        return arr[uniq_ids], indices
    # Both paths dedup fixed-width values by their raw BITS — floats
    # keep -0.0 distinct from 0.0 and distinct NaN payloads apart, so
    # the decoded column is bit-exact and the file does not depend on
    # whether the native runtime was present at write time.
    if physical_type == Type.FIXED_LEN_BYTE_ARRAY or physical_type == Type.INT96:
        # (n, width) uint8 rows
        uniq, inverse = np.unique(arr, axis=0, return_inverse=True)
        # np.unique sorts; remap to first-appearance order
        first_pos = np.full(len(uniq), len(arr), dtype=np.int64)
        np.minimum.at(first_pos, inverse, np.arange(len(arr)))
        order = np.argsort(first_pos, kind="stable")
        rank = np.empty_like(order)
        rank[order] = np.arange(len(order))
        return uniq[order], rank[inverse].astype(np.uint32)
    key = (
        arr.view(f"u{arr.itemsize}") if arr.dtype.kind == "f" else arr
    )
    _, idx_first, inverse = np.unique(
        key, return_index=True, return_inverse=True
    )
    order = np.argsort(idx_first, kind="stable")
    rank = np.empty_like(order)
    rank[order] = np.arange(len(order))
    return arr[idx_first[order]], rank[inverse.reshape(-1)].astype(np.uint32)


def encode_dictionary_page(dictionary, physical_type: int, type_length=None) -> bytes:
    return encode_plain(dictionary, physical_type, type_length)


def decode_dictionary_page(data, num_values: int, physical_type: int, type_length=None):
    values, _ = decode_plain(data, num_values, physical_type, type_length)
    return values


def encode_dict_indices(indices: np.ndarray, dict_size: int) -> bytes:
    """Index stream for a data page: 1-byte bit width + hybrid runs."""
    bw = max(min_bit_width(max(dict_size - 1, 0)), 1)
    return bytes([bw]) + encode_rle_hybrid(indices, bw)


def decode_dict_indices(data, num_values: int, pos: int = 0) -> Tuple[np.ndarray, int]:
    bw = data[pos]
    if bw > 32:
        raise ValueError(f"dictionary index bit width {bw} out of range")
    values, end = decode_rle_hybrid(data, num_values, bw, pos + 1)
    return values, end


def gather(dictionary, indices: np.ndarray):
    """CPU reference of the TPU dictionary-gather kernel."""
    if isinstance(dictionary, ByteArrayColumn):
        lengths = dictionary.lengths()
        out_lengths = lengths[indices]
        offsets = np.zeros(len(indices) + 1, dtype=np.int64)
        np.cumsum(out_lengths, out=offsets[1:])
        total = int(offsets[-1])
        if total == 0:
            return ByteArrayColumn(offsets, np.zeros(0, np.uint8))
        starts = dictionary.offsets[:-1][indices]
        src = np.repeat(starts - offsets[:-1], out_lengths) + np.arange(total)
        return ByteArrayColumn(offsets, dictionary.data[src])
    return np.asarray(dictionary)[indices]
