"""RLE / bit-packed hybrid encoding (Parquet spec §RLE).

This single encoding carries definition levels, repetition levels, boolean
values (v2 pages), and dictionary indices — it is the highest-leverage codec
in the format.  Capability parity: parquet-mr's RunLengthBitPackingHybrid
decoder/encoder, consumed by the reference through ``ColumnReader`` getters
(``ParquetReader.java:141-168``).

Wire format::

    run        := rle-run | bit-packed-run
    rle-run    := varint(count << 1) value:ceil(bw/8) bytes LE
    bitpacked  := varint((groups << 1) | 1) groups*bw bytes   # 8 values/group,
                                                              # LSB-first packing

Framings (handled by callers, helpers here):
  * v1 data-page levels:  4-byte LE length prefix, then runs
  * v2 data-page levels:  raw runs (length known from the page header)
  * dictionary indices:   1-byte bit width, then runs

The decoder is two-phase by design: a **run-table parse** (sequential, tiny —
one entry per run) followed by a **vectorized expansion** (np.repeat /
unpackbits).  The same split feeds the TPU path: the host parses run tables,
the device expands them (see ``tpu/kernels/rle_expand.py``).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from ...errors import checked_alloc_size

try:  # native run-table parser (optional fast path)
    from ...native import binding as _native
except Exception:  # pragma: no cover
    _native = None


def _read_varint(buf, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    n = len(buf)
    while True:
        if pos >= n:
            raise ValueError("truncated varint in RLE/bit-packed stream")
        b = int(buf[pos])  # plain int: np.uint8 scalars poison later
        pos += 1           # arithmetic under NEP-50 promotion rules

        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long in RLE/bit-packed stream")


def _write_varint(out: bytearray, n: int) -> None:
    while True:
        if n < 0x80:
            out.append(n)
            return
        out.append((n & 0x7F) | 0x80)
        n >>= 7


def bit_unpack(packed: np.ndarray, bit_width: int, count: int) -> np.ndarray:
    """Unpack ``count`` little-endian bit-packed unsigned ints (LSB-first).

    Vectorized: unpackbits → reshape(count, bw) → weighted sum.  Exact for
    bit widths 0..64.
    """
    if bit_width == 0:
        # count may be straight off the wire (delta miniblock geometry)
        return np.zeros(checked_alloc_size(count, "bit-packed run"),
                        dtype=np.uint64)
    nbits_needed = count * bit_width
    bits = np.unpackbits(packed, bitorder="little", count=None)
    if len(bits) < nbits_needed:
        raise ValueError("bit-packed run truncated")
    bits = bits[:nbits_needed].reshape(count, bit_width).astype(np.uint64)
    weights = (np.uint64(1) << np.arange(bit_width, dtype=np.uint64))
    return bits @ weights


def bit_pack(values: np.ndarray, bit_width: int) -> bytes:
    """Pack unsigned ints into little-endian ``bit_width``-bit groups.

    ``len(values)`` must be a multiple of 8 (pad with zeros upstream).
    """
    if bit_width == 0:
        return b""
    v = np.asarray(values, dtype=np.uint64)
    bits = ((v[:, None] >> np.arange(bit_width, dtype=np.uint64)) & np.uint64(1)).astype(
        np.uint8
    )
    return np.packbits(bits.reshape(-1), bitorder="little").tobytes()


def parse_runs(data, num_values: int, bit_width: int, pos: int = 0):
    """Phase 1: sequential scan of run headers into a run table.

    Returns ``(run_table, end_pos)`` where run_table is an int64 array of
    shape (n_runs, 4): ``[kind, count, value_or_byte_offset, unused]`` with
    kind 0 = RLE (col2 = the repeated value), kind 1 = bit-packed (col2 =
    byte offset of packed data within ``data``).  This table is exactly what
    the TPU expansion kernel consumes.
    """
    if bit_width == 0:
        return np.zeros((0, 4), dtype=np.int64), pos
    if _native is not None and _native.available():
        try:
            return _native.rle_parse_runs(data, num_values, bit_width, pos)
        except ValueError:
            pass  # fall through to the pure-Python parser for its errors
    rows = []
    remaining = num_values
    value_bytes = (bit_width + 7) // 8
    end = len(data)
    while remaining > 0:
        header, pos = _read_varint(data, pos)
        if header & 1:
            groups = header >> 1
            n = groups * 8
            if pos + groups * bit_width > end:
                raise ValueError("bit-packed run overruns stream")
            rows.append((1, min(n, remaining), pos, 0))
            pos += groups * bit_width
            remaining -= n
        else:
            n = header >> 1
            if pos + value_bytes > end:
                raise ValueError("RLE run value overruns stream")
            value = int.from_bytes(data[pos : pos + value_bytes], "little")
            pos += value_bytes
            rows.append((0, min(n, remaining), value, 0))
            remaining -= n
    table = np.array(rows, dtype=np.int64).reshape(-1, 4)
    return table, pos


def parse_runs_batch(data, streams):
    """Parse several independent run streams of one buffer.

    ``streams`` is a sequence of ``(pos, num_values, bit_width)``; returns
    a list of run tables (absolute byte offsets), one per stream.  One
    native call when available; exact per-stream fallback otherwise."""
    if not streams:
        return []
    if _native is not None and _native.available():
        try:
            pos, counts, bws = (list(x) for x in zip(*streams))
            table, runs = _native.rle_parse_runs_batch(data, pos, counts, bws)
            return np.split(table, np.cumsum(runs)[:-1])
        except ValueError:
            pass  # let the per-stream parser produce its exact errors
    return [
        parse_runs(data, n, bw, pos=p)[0] for p, n, bw in streams
    ]


def count_equal(data, num_values: int, bit_width: int, target: int,
                pos: int = 0, run_table=None):
    """Count decoded values == target without materializing the expansion
    (the staging hot loop for definition-level non-null counting).

    Native single pass when the library is present; otherwise walks the
    (supplied or freshly parsed) run table, unpacking only bit-packed runs.
    """
    if bit_width == 0:
        return num_values if target == 0 else 0
    if _native is not None and _native.available():
        try:
            c = _native.rle_count_equal(data, num_values, bit_width, target, pos)
            if c is not None:
                return c
        except ValueError:
            pass
    if run_table is None:
        run_table, _ = parse_runs(data, num_values, bit_width, pos)
    buf = data if isinstance(data, np.ndarray) else np.frombuffer(data, np.uint8)
    total = 0
    for kind, count, v, _ in run_table:
        if kind == 0:
            if v == target:
                total += int(count)
        else:
            nbytes = ((int(count) + 7) // 8) * bit_width
            vals = bit_unpack(buf[v : v + nbytes], bit_width, int(count))
            total += int(np.count_nonzero(vals == target))
    return total


# host-expansion odometer: how many times expand_runs actually ran in
# this process.  The device scan path decodes v2 uncompressed-levels
# pages' def-level and dictionary-index runs ON DEVICE (tpu/bitops.py
# plan5), so tests pin "zero host expansions on that path" against this
# counter rather than inferring it from timings (docs/multichip.md).
_expand_calls = 0


def expand_calls() -> int:
    """Process-wide count of :func:`expand_runs` invocations."""
    return _expand_calls


def expand_runs(data, run_table: np.ndarray, num_values: int, bit_width: int) -> np.ndarray:
    """Phase 2: vectorized expansion of a run table to values (uint32)."""
    global _expand_calls
    _expand_calls += 1
    # num_values is a page-header field; run counts come from the parsed
    # table (clamped to remaining values at parse time — the min() below
    # re-states that bound where the allocation happens)
    nv = checked_alloc_size(num_values, "RLE expansion")
    if bit_width == 0:
        return np.zeros(nv, dtype=np.uint32)
    out_parts = []
    buf = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    for kind, count, v, _ in run_table:
        cnt = min(int(count), nv)
        if kind == 0:
            out_parts.append(np.full(cnt, v, dtype=np.uint32))
        else:
            nbytes = ((cnt + 7) // 8) * bit_width
            packed = buf[v : v + nbytes]
            out_parts.append(bit_unpack(packed, bit_width, cnt).astype(np.uint32))
    if not out_parts:
        return np.zeros(nv, dtype=np.uint32)
    out = np.concatenate(out_parts)
    if len(out) < nv:
        raise ValueError(f"RLE stream ended early: {len(out)} < {num_values}")
    return out[:nv]


def decode_rle_hybrid(data, num_values: int, bit_width: int, pos: int = 0):
    """Decode ``num_values`` from an unframed run stream.

    Returns ``(values: uint32 ndarray, end_pos)``.
    """
    table, end = parse_runs(data, num_values, bit_width, pos)
    return expand_runs(data, table, num_values, bit_width), end


def decode_length_prefixed(data, num_values: int, bit_width: int, pos: int = 0):
    """v1 level framing: u32 LE byte length, then runs."""
    ln = int.from_bytes(data[pos : pos + 4], "little")
    values, _ = decode_rle_hybrid(data, num_values, bit_width, pos + 4)
    return values, pos + 4 + ln


def decode_bit_packed_legacy(data, num_values: int, bit_width: int, pos: int = 0):
    """Deprecated BIT_PACKED level encoding (format spec: "bit-packed only",
    packed **from the most significant bit**, no length prefix).

    Only ever appears for def/rep levels in very old v1 files; size is
    exactly ``ceil(num_values * bit_width / 8)`` bytes.
    Returns ``(values: uint32 ndarray, end_pos)``.
    """
    if bit_width == 0:
        return np.zeros(checked_alloc_size(num_values, "BIT_PACKED levels"),
                        dtype=np.uint32), pos
    nbytes = (num_values * bit_width + 7) // 8
    buf = np.frombuffer(data, np.uint8) if not isinstance(data, np.ndarray) else data
    chunk = np.asarray(buf[pos : pos + nbytes], dtype=np.uint8)
    if len(chunk) < nbytes:
        raise ValueError("BIT_PACKED level section truncated")
    # MSB-first: explode each byte high bit first, regroup, weigh MSB-first
    bits = (
        (chunk[:, None] >> np.arange(7, -1, -1, dtype=np.uint8)) & np.uint8(1)
    ).reshape(-1)
    bits = bits[: num_values * bit_width].reshape(num_values, bit_width)
    weights = (1 << np.arange(bit_width - 1, -1, -1)).astype(np.uint32)
    return (bits.astype(np.uint32) * weights).sum(axis=1, dtype=np.uint32), pos + nbytes


def encode_rle_hybrid(values: np.ndarray, bit_width: int) -> bytes:
    """Encode values as an unframed hybrid run stream.

    Strategy mirrors parquet-mr's writer: emit an RLE run for ≥8-long
    repeats, otherwise accumulate bit-packed groups of 8 (padding the
    tail group with zeros; ≤63 groups per bit-packed header, like
    parquet-mr's 504-value bound).

    The Python loop below runs per LONG run only — spans of short runs
    between them (the whole stream, for high-entropy dictionary
    indices) are appended as array slices and bit-packed vectorized,
    which is what makes the write path's index encoding O(runs) Python
    work instead of O(values).
    """
    v = np.asarray(values, dtype=np.uint64)
    n = len(v)
    out = bytearray()
    if n == 0 or bit_width == 0:
        return bytes(out)
    value_bytes = (bit_width + 7) // 8

    # Find run boundaries.
    change = np.nonzero(np.diff(v))[0] + 1
    starts = np.concatenate(([0], change))
    ends = np.concatenate((change, [n]))

    pending: list = []  # array segments queued for bit-packed emission
    pend_n = 0

    def flush_bitpacked(allow_pad: bool):
        """Emit queued segments as bit-packed groups, ≤504 values per
        header.  Mid-stream the group count must cover *real* values
        only (the decoder materializes groups*8 values), so a non-group
        tail stays queued unless this is the stream's final flush.
        Each group of 8 packs to exactly ``bit_width`` bytes, so the
        whole buffer packs in ONE bit_pack call and the ≤63-group
        chunks are byte-aligned slices of it — identical bytes to
        per-chunk packing without the per-chunk call overhead."""
        nonlocal pend_n
        if not pend_n:
            return
        arr = (
            np.concatenate(pending) if len(pending) > 1 else pending[0]
        )
        pending.clear()
        emit_n = len(arr) if allow_pad else (len(arr) // 8) * 8
        # pack in macro-blocks (a multiple of 504 AND 8) so the win
        # over per-chunk packing keeps, while bit_pack's (block, bw)
        # uint64 intermediates stay a few MB instead of scaling with
        # the whole span
        BLOCK = 504 * 128
        base = 0
        while base < emit_n:
            block_n = min(BLOCK, emit_n - base)
            padded = arr[base : base + block_n]
            pad = (-block_n) % 8
            if pad:
                padded = np.concatenate(
                    [padded, np.zeros(pad, dtype=np.uint64)]
                )
            packed = bit_pack(padded, bit_width)
            pos = 0
            byte_pos = 0
            while pos < block_n:
                take = min(504, block_n - pos)
                groups = (take + 7) // 8
                _write_varint(out, (groups << 1) | 1)
                out.extend(packed[byte_pos : byte_pos + groups * bit_width])
                pos += take
                byte_pos += groups * bit_width
            base += block_n
        leftover = arr[emit_n:]
        pend_n = len(leftover)
        if pend_n:
            pending.append(leftover)

    long_runs = np.nonzero(ends - starts >= 8)[0]
    prev_end = 0
    for li in long_runs:
        s, e = int(starts[li]), int(ends[li])
        if s > prev_end:
            pending.append(v[prev_end:s])
            pend_n += s - prev_end
        run_len = e - s
        # Top up the pending group to an 8-boundary with this run's head.
        fill = (-pend_n) % 8
        if fill:
            pending.append(np.full(fill, v[s], dtype=np.uint64))
            pend_n += fill
            run_len -= fill
        flush_bitpacked(allow_pad=False)
        if run_len >= 8:
            _write_varint(out, run_len << 1)
            out.extend(int(v[s]).to_bytes(value_bytes, "little"))
        elif run_len:
            # invariant: run_len < 8 here (>= 8 took the RLE branch above
            # after the fill top-up) — assert keeps it loud, the size is
            # in-memory run geometry, not a parsed field
            assert run_len < 8, run_len
            pending.append(
                np.full(run_len, v[s], dtype=np.uint64)  # floorlint: disable=FL-ALLOC001
            )
            pend_n += run_len
        prev_end = e
    if prev_end < n:
        pending.append(v[prev_end:])
        pend_n += n - prev_end
    flush_bitpacked(allow_pad=True)
    return bytes(out)


def encode_length_prefixed(values: np.ndarray, bit_width: int) -> bytes:
    payload = encode_rle_hybrid(values, bit_width)
    return len(payload).to_bytes(4, "little") + payload


def min_bit_width(max_value: int) -> int:
    return int(max_value).bit_length()
