"""Split-block Bloom filters (SBBF) — the parquet-format bloom filter the
reference's engine exposes through parquet-mr 1.12's column metadata
(``bloom_filter_offset``/``length``, ColumnMetaData fields 14/15; the
facade itself never surfaces them, but "same capabilities" includes the
format surface — SURVEY.md §2.3).

From-scratch implementation of both halves:

* **XXH64** (seed 0) over the value's plain-encoded bytes — scalar pure
  Python for arbitrary byte strings plus a fully vectorized NumPy form
  for fixed-width (≤ 8 byte) value arrays, which is the TPU-framework
  stance: hash a whole column in a handful of array ops, not a Python
  loop per value.
* **SBBF bitset**: 256-bit blocks of eight 32-bit words; each key sets
  one salted bit per word.  Block choice is fastrange on the hash's top
  32 bits; bit choice is ``(x * SALT[i]) >> 27`` on the low 32 bits.

Wire layout (read/written here, validated against pyarrow-written
files): a compact-Thrift ``BloomFilterHeader`` followed immediately by
the raw bitset bytes.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from ..errors import checked_alloc_size
from .parquet_thrift import Type
from .thrift import CompactReader, CompactWriter, T_I32, ThriftStruct

# -- thrift wire structures (parquet.thrift BloomFilterHeader) --------------


class SplitBlockAlgorithm(ThriftStruct):
    FIELDS: dict = {}


class BloomFilterAlgorithm(ThriftStruct):
    """Union: only BLOCK exists today."""

    FIELDS = {1: ("BLOCK", SplitBlockAlgorithm)}


class XxHash(ThriftStruct):
    FIELDS: dict = {}


class BloomFilterHash(ThriftStruct):
    """Union: only XXHASH exists today."""

    FIELDS = {1: ("XXHASH", XxHash)}


class Uncompressed(ThriftStruct):
    FIELDS: dict = {}


class BloomFilterCompression(ThriftStruct):
    """Union: only UNCOMPRESSED exists today."""

    FIELDS = {1: ("UNCOMPRESSED", Uncompressed)}


class BloomFilterHeader(ThriftStruct):
    FIELDS = {
        1: ("numBytes", T_I32),
        2: ("algorithm", BloomFilterAlgorithm),
        3: ("hash", BloomFilterHash),
        4: ("compression", BloomFilterCompression),
    }


# -- XXH64 ------------------------------------------------------------------

_P1 = 0x9E3779B185EBCA87
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9
_P4 = 0x85EBCA77C2B2AE63
_P5 = 0x27D4EB2F165667C5
_M64 = (1 << 64) - 1


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _M64


def xxh64(data: bytes, seed: int = 0) -> int:
    """Reference scalar XXH64 (any length), used for BYTE_ARRAY values."""
    n = len(data)
    pos = 0
    if n >= 32:
        v1 = (seed + _P1 + _P2) & _M64
        v2 = (seed + _P2) & _M64
        v3 = seed
        v4 = (seed - _P1) & _M64
        while pos + 32 <= n:
            lane = int.from_bytes(data[pos : pos + 8], "little")
            v1 = (_rotl((v1 + lane * _P2) & _M64, 31) * _P1) & _M64
            lane = int.from_bytes(data[pos + 8 : pos + 16], "little")
            v2 = (_rotl((v2 + lane * _P2) & _M64, 31) * _P1) & _M64
            lane = int.from_bytes(data[pos + 16 : pos + 24], "little")
            v3 = (_rotl((v3 + lane * _P2) & _M64, 31) * _P1) & _M64
            lane = int.from_bytes(data[pos + 24 : pos + 32], "little")
            v4 = (_rotl((v4 + lane * _P2) & _M64, 31) * _P1) & _M64
            pos += 32
        acc = (_rotl(v1, 1) + _rotl(v2, 7) + _rotl(v3, 12) + _rotl(v4, 18)) & _M64
        for v in (v1, v2, v3, v4):
            acc ^= (_rotl((v * _P2) & _M64, 31) * _P1) & _M64
            acc = (acc * _P1 + _P4) & _M64
    else:
        acc = (seed + _P5) & _M64
    acc = (acc + n) & _M64
    while pos + 8 <= n:
        lane = int.from_bytes(data[pos : pos + 8], "little")
        acc ^= (_rotl((lane * _P2) & _M64, 31) * _P1) & _M64
        acc = (_rotl(acc, 27) * _P1 + _P4) & _M64
        pos += 8
    if pos + 4 <= n:
        lane = int.from_bytes(data[pos : pos + 4], "little")
        acc ^= (lane * _P1) & _M64
        acc = (_rotl(acc, 23) * _P2 + _P3) & _M64
        pos += 4
    while pos < n:
        acc ^= (data[pos] * _P5) & _M64
        acc = (_rotl(acc, 11) * _P1) & _M64
        pos += 1
    acc ^= acc >> 33
    acc = (acc * _P2) & _M64
    acc ^= acc >> 29
    acc = (acc * _P3) & _M64
    acc ^= acc >> 32
    return acc


def _rotl_np(x: np.ndarray, r: int) -> np.ndarray:
    return (x << np.uint64(r)) | (x >> np.uint64(64 - r))


def _avalanche_np(acc: np.ndarray) -> np.ndarray:
    acc = acc ^ (acc >> np.uint64(33))
    acc = acc * np.uint64(_P2)
    acc = acc ^ (acc >> np.uint64(29))
    acc = acc * np.uint64(_P3)
    acc = acc ^ (acc >> np.uint64(32))
    return acc


def xxh64_fixed(rows: np.ndarray) -> np.ndarray:
    """Vectorized XXH64 (seed 0) of N fixed-width values ≤ 8 bytes.

    ``rows`` is uint8[N, W] with W in {1..8} — the plain-encoded bytes of
    each value.  One pass of NumPy uint64 ops per the short-input branch
    of the spec (W < 32 skips the stripe loop).  Bit-exact vs :func:`xxh64`
    (property-tested)."""
    n, w = rows.shape
    if not 1 <= w <= 8:
        raise ValueError(f"xxh64_fixed supports widths 1..8, got {w}")
    acc = np.full(n, (_P5 + w) & _M64, dtype=np.uint64)
    with np.errstate(over="ignore"):
        if w == 8:
            lane = rows.view(np.uint64).reshape(n)
            k = _rotl_np(lane * np.uint64(_P2), 31) * np.uint64(_P1)
            acc = acc ^ k
            acc = _rotl_np(acc, 27) * np.uint64(_P1) + np.uint64(_P4)
        elif w == 4:
            lane = rows.view(np.uint32).reshape(n).astype(np.uint64)
            acc = acc ^ (lane * np.uint64(_P1))
            acc = _rotl_np(acc, 23) * np.uint64(_P2) + np.uint64(_P3)
        else:
            pos = 0
            if w >= 4:
                lane = (
                    rows[:, :4].copy().view(np.uint32).reshape(n).astype(np.uint64)
                )
                acc = acc ^ (lane * np.uint64(_P1))
                acc = _rotl_np(acc, 23) * np.uint64(_P2) + np.uint64(_P3)
                pos = 4
            for j in range(pos, w):
                acc = acc ^ (rows[:, j].astype(np.uint64) * np.uint64(_P5))
                acc = _rotl_np(acc, 11) * np.uint64(_P1)
        return _avalanche_np(acc)


# -- value hashing per physical type ---------------------------------------


def hash_values(physical_type: int, values) -> np.ndarray:
    """XXH64 of each value's plain-encoded bytes → uint64[N].

    BYTE_ARRAY hashes the raw bytes (no length prefix); fixed types hash
    their little-endian plain encoding exactly as stored (spec behavior —
    ±0.0 are distinct encodings; writers insert both and equality probes
    check both, see ``zero_variant_hashes``).  BOOLEAN is rejected (a
    1-bit domain never benefits — parquet-mr refuses it too)."""
    from .encodings.plain import ByteArrayColumn

    if physical_type == Type.BOOLEAN:
        raise ValueError("bloom filters are not supported for BOOLEAN")
    if isinstance(values, ByteArrayColumn) or (
        isinstance(values, np.ndarray) and values.dtype.kind in "OSU"
    ) or isinstance(values, (list, tuple)):
        # numpy 'S' items iterate as padding-stripped bytes and 'U' items
        # as str — both take the same per-item encoding as lists, never a
        # raw fixed-width buffer view (which would hash the padding)
        if isinstance(values, ByteArrayColumn):
            items = values.to_list()
        else:
            items = list(values)
        out = np.empty(len(items), np.uint64)
        for i, b in enumerate(items):
            if isinstance(b, str):
                b = b.encode("utf-8")
            out[i] = xxh64(bytes(b))
        return out
    arr = np.asarray(values)
    if arr.ndim == 2:  # FLBA / INT96 rows
        w = arr.shape[1]
        if w <= 8:
            return xxh64_fixed(np.ascontiguousarray(arr, dtype=np.uint8))
        return np.array([xxh64(r.tobytes()) for r in arr], np.uint64)
    if arr.dtype == np.bool_:
        raise ValueError("bloom filters are not supported for BOOLEAN")
    rows = np.ascontiguousarray(arr).view(np.uint8).reshape(len(arr), arr.dtype.itemsize)
    return xxh64_fixed(rows)


def probe_hashes(physical_type: int, values) -> np.ndarray:
    """Hashes to test when PROBING a filter for equality: the values'
    own hashes, plus both zero encodings for any float zero (a foreign
    writer inserted only the stored bit pattern — matching either is
    "maybe present").  Keeps the ±0.0 encoding rules in this module,
    mirroring :func:`zero_variant_hashes` on the insert side."""
    h = hash_values(physical_type, values)
    zv = zero_variant_hashes(physical_type, values)
    return h if zv is None else np.concatenate([h, zv])


def zero_variant_hashes(physical_type: int, values) -> Optional[np.ndarray]:
    """Hashes of the *other* zero encoding for any ±0.0 present in a float
    column, or None.  −0.0 == +0.0 numerically but their plain encodings
    differ; a filter must contain both so a spec-following reader probing
    either bit pattern never gets a false negative."""
    arr = np.asarray(values) if not isinstance(values, np.ndarray) else values
    if getattr(arr, "dtype", None) is None or arr.dtype.kind != "f":
        return None
    if not (arr == 0.0).any():
        return None
    both = np.array([0.0, -0.0], dtype=arr.dtype)
    return hash_values(physical_type, both)


# -- the split-block filter -------------------------------------------------

_SALT = np.array(
    [0x47B6137B, 0x44974D91, 0x8824AD5B, 0xA2B7289D,
     0x705495C7, 0x2DF1424B, 0x9EFC4947, 0x5C6BFB31],
    dtype=np.uint32,
)

MIN_BYTES = 32
MAX_BYTES = 128 << 20


def optimal_num_bytes(ndv: int, fpp: float = 0.01) -> int:
    """parquet-mr's sizing rule: bits = -8·ndv / ln(1 − fpp^(1/8)),
    rounded up to a power of two within [32 B, 128 MiB]."""
    if not 0.0 < fpp < 1.0:
        raise ValueError(f"fpp must be in (0, 1), got {fpp}")
    ndv = max(int(ndv), 1)
    bits = -8.0 * ndv / math.log(1.0 - fpp ** 0.125)
    nbytes = int(bits / 8.0)
    nbytes = 1 << max(nbytes - 1, 0).bit_length()
    return min(max(nbytes, MIN_BYTES), MAX_BYTES)


class SplitBlockBloomFilter:
    """A bitset of 256-bit blocks; supports vectorized insert/check."""

    def __init__(self, num_bytes: int = MIN_BYTES,
                 bitset: Optional[np.ndarray] = None):
        if bitset is not None:
            if bitset.dtype != np.uint32 or bitset.ndim != 2 or bitset.shape[1] != 8:
                raise ValueError("bitset must be uint32[nblocks, 8]")
            self.bitset = bitset
        else:
            if num_bytes % 32 or num_bytes < MIN_BYTES:
                raise ValueError(f"num_bytes must be a multiple of 32 ≥ 32, got {num_bytes}")
            # cap at the format's 128 MiB ceiling before sizing the bitset
            # (the parsed path — from_bytes — caps its numBytes the same
            # way before its frombuffer)
            nb = checked_alloc_size(num_bytes, "bloom filter bitset",
                                    cap=MAX_BYTES + 1)
            self.bitset = np.zeros((nb // 32, 8), dtype=np.uint32)

    @property
    def num_bytes(self) -> int:
        return int(self.bitset.size * 4)

    def _block_and_mask(self, hashes: np.ndarray):
        h = np.asarray(hashes, dtype=np.uint64)
        z = np.uint64(self.bitset.shape[0])
        block = ((h >> np.uint64(32)) * z) >> np.uint64(32)  # fastrange
        x = h.astype(np.uint32)  # low 32 bits
        with np.errstate(over="ignore"):
            bit = (x[:, None] * _SALT[None, :]) >> np.uint32(27)
        mask = np.uint32(1) << bit
        return block.astype(np.int64), mask

    def insert_hashes(self, hashes: np.ndarray) -> None:
        block, mask = self._block_and_mask(hashes)
        idx = block[:, None] * 8 + np.arange(8, dtype=np.int64)[None, :]
        flat = self.bitset.reshape(-1)
        np.bitwise_or.at(flat, idx.reshape(-1), mask.reshape(-1))

    def check_hashes(self, hashes: np.ndarray) -> np.ndarray:
        """bool[N]: False = definitely absent, True = maybe present."""
        block, mask = self._block_and_mask(hashes)
        words = self.bitset[block]  # (N, 8)
        return np.all((words & mask) == mask, axis=1)

    def check_hash(self, h: int) -> bool:
        return bool(self.check_hashes(np.array([h], np.uint64))[0])

    # -- wire form ----------------------------------------------------------

    def to_bytes(self) -> bytes:
        w = CompactWriter()
        BloomFilterHeader(
            numBytes=self.num_bytes,
            algorithm=BloomFilterAlgorithm(BLOCK=SplitBlockAlgorithm()),
            hash=BloomFilterHash(XXHASH=XxHash()),
            compression=BloomFilterCompression(UNCOMPRESSED=Uncompressed()),
        ).write(w)
        # little-endian words, blocks in order — the spec's byte layout
        return w.getvalue() + self.bitset.astype("<u4").tobytes()

    @classmethod
    def from_bytes(cls, data, pos: int = 0) -> "SplitBlockBloomFilter":
        reader = CompactReader(data, pos)
        header = BloomFilterHeader.read(reader)
        if header.numBytes is None or header.numBytes <= 0:
            raise ValueError("bloom filter header missing numBytes")
        if header.numBytes % 32 or header.numBytes < MIN_BYTES:
            raise ValueError(
                f"invalid bloom filter size {header.numBytes} "
                "(must be a multiple of 32 ≥ 32)"
            )
        if header.algorithm is not None and header.algorithm.BLOCK is None:
            raise ValueError("unsupported bloom filter algorithm")
        if header.compression is not None and header.compression.UNCOMPRESSED is None:
            raise ValueError("unsupported bloom filter compression")
        if header.hash is not None and header.hash.XXHASH is None:
            raise ValueError("unsupported bloom filter hash")
        start = reader.pos
        # numBytes is a parsed header field: cap it at the format's
        # 128 MiB ceiling before it drives the frombuffer count (a corrupt
        # header must surface as taxonomy, not a bare numpy ValueError)
        nb = checked_alloc_size(int(header.numBytes), "bloom filter bitset",
                                cap=MAX_BYTES + 1)
        raw = np.frombuffer(data, np.uint8, count=nb, offset=start)
        bitset = raw.view("<u4").reshape(-1, 8).copy()
        return cls(bitset=bitset)
