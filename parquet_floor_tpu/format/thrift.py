"""Thrift compact-protocol reader/writer, implemented from scratch.

Parquet serializes its footer and page headers with the Thrift *compact*
protocol.  The reference library delegates this to parquet-mr's vendored
thrift runtime (see SURVEY.md §2.3; exercised via
``ParquetFileReader.open/getFooter`` at reference ``ParquetReader.java:114-120``).
Here we implement the wire protocol directly: ULEB128 varints, zigzag
integers, field-id delta encoding, struct/list/map containers, and the
compact double representation.

The protocol surface implemented is exactly what the Parquet format needs
(plus maps/doubles for completeness).  Structures themselves are declared
in :mod:`parquet_floor_tpu.format.parquet_thrift`.
"""

from __future__ import annotations

import struct
from typing import Optional

from ..errors import ParquetError

# Compact-protocol type ids (wire values).
CT_STOP = 0x00
CT_BOOLEAN_TRUE = 0x01
CT_BOOLEAN_FALSE = 0x02
CT_BYTE = 0x03
CT_I16 = 0x04
CT_I32 = 0x05
CT_I64 = 0x06
CT_DOUBLE = 0x07
CT_BINARY = 0x08  # also STRING
CT_LIST = 0x09
CT_SET = 0x0A
CT_MAP = 0x0B
CT_STRUCT = 0x0C


class ThriftDecodeError(ParquetError, ValueError):
    """Raised when bytes do not parse as valid compact-protocol Thrift.

    Part of the :mod:`parquet_floor_tpu.errors` taxonomy (and still a
    ``ValueError`` for pre-taxonomy callers); the footer/page layers wrap
    or annotate it with file/column context."""


def zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n < 0 else n << 1


def zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


class CompactReader:
    """Cursor over a bytes-like object, decoding compact-protocol values."""

    __slots__ = ("buf", "pos", "end")

    def __init__(self, buf, pos: int = 0, end: Optional[int] = None):
        self.buf = buf
        self.pos = pos
        self.end = len(buf) if end is None else end

    def read_byte(self) -> int:
        if self.pos >= self.end:
            raise ThriftDecodeError("unexpected end of thrift data")
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def read_varint(self) -> int:
        """ULEB128 unsigned varint."""
        result = 0
        shift = 0
        while True:
            b = self.read_byte()
            result |= (b & 0x7F) << shift
            if not (b & 0x80):
                return result
            shift += 7
            if shift > 70:
                raise ThriftDecodeError("varint too long")

    def read_zigzag(self) -> int:
        return zigzag_decode(self.read_varint())

    def read_bytes(self, n: int) -> bytes:
        if self.pos + n > self.end:
            raise ThriftDecodeError("unexpected end of thrift data")
        out = bytes(self.buf[self.pos : self.pos + n])
        self.pos += n
        return out

    def read_binary(self) -> bytes:
        return self.read_bytes(self.read_varint())

    def read_double(self) -> float:
        # Compact protocol stores doubles little-endian.
        return struct.unpack("<d", self.read_bytes(8))[0]

    def skip(self, ctype: int, in_container: bool = False) -> None:
        """Skip a value of the given compact type (for unknown fields).

        Booleans are encoded in the field header at field position (zero
        payload bytes) but occupy one byte as container elements.
        """
        if ctype in (CT_BOOLEAN_TRUE, CT_BOOLEAN_FALSE):
            if in_container:
                self.read_byte()
            return
        if ctype == CT_BYTE:
            self.read_byte()
        elif ctype in (CT_I16, CT_I32, CT_I64):
            self.read_varint()
        elif ctype == CT_DOUBLE:
            self.read_bytes(8)
        elif ctype == CT_BINARY:
            self.read_bytes(self.read_varint())
        elif ctype in (CT_LIST, CT_SET):
            size, elem_type = self.read_list_header()
            for _ in range(size):
                self.skip(elem_type, in_container=True)
        elif ctype == CT_MAP:
            size, ktype, vtype = self.read_map_header()
            for _ in range(size):
                self.skip(ktype, in_container=True)
                self.skip(vtype, in_container=True)
        elif ctype == CT_STRUCT:
            self.skip_struct()
        else:
            raise ThriftDecodeError(f"cannot skip unknown compact type {ctype}")

    def skip_struct(self) -> None:
        last_fid = 0
        while True:
            fid, ctype, last_fid = self.read_field_header(last_fid)
            if ctype == CT_STOP:
                return
            self.skip(ctype)

    def read_field_header(self, last_fid: int):
        """Returns (field_id, compact_type, new_last_fid); type CT_STOP ends."""
        b = self.read_byte()
        if b == CT_STOP:
            return 0, CT_STOP, last_fid
        delta = (b & 0xF0) >> 4
        ctype = b & 0x0F
        if delta == 0:
            fid = zigzag_decode(self.read_varint())
        else:
            fid = last_fid + delta
        return fid, ctype, fid

    def read_list_header(self):
        b = self.read_byte()
        size = (b & 0xF0) >> 4
        elem_type = b & 0x0F
        if size == 0x0F:
            size = self.read_varint()
        return size, elem_type

    def read_map_header(self):
        size = self.read_varint()
        if size == 0:
            return 0, 0, 0
        b = self.read_byte()
        return size, (b & 0xF0) >> 4, b & 0x0F


class CompactWriter:
    """Appends compact-protocol values to an internal bytearray."""

    __slots__ = ("out",)

    def __init__(self):
        self.out = bytearray()

    def getvalue(self) -> bytes:
        return bytes(self.out)

    def write_byte(self, b: int) -> None:
        self.out.append(b & 0xFF)

    def write_varint(self, n: int) -> None:
        if n < 0:
            raise ValueError("varint must be non-negative")
        while True:
            if n < 0x80:
                self.out.append(n)
                return
            self.out.append((n & 0x7F) | 0x80)
            n >>= 7

    def write_zigzag(self, n: int) -> None:
        self.write_varint(zigzag_encode(n))

    def write_binary(self, data: bytes) -> None:
        self.write_varint(len(data))
        self.out += data

    def write_double(self, value: float) -> None:
        self.out += struct.pack("<d", value)

    def write_field_header(self, fid: int, ctype: int, last_fid: int) -> int:
        delta = fid - last_fid
        if 0 < delta <= 15:
            self.write_byte((delta << 4) | ctype)
        else:
            self.write_byte(ctype)
            self.write_zigzag(fid)
        return fid

    def write_stop(self) -> None:
        self.write_byte(CT_STOP)

    def write_list_header(self, size: int, elem_type: int) -> None:
        if size < 15:
            self.write_byte((size << 4) | elem_type)
        else:
            self.write_byte(0xF0 | elem_type)
            self.write_varint(size)

    def write_map_header(self, size: int, ktype: int, vtype: int) -> None:
        self.write_varint(size)
        if size > 0:
            self.write_byte((ktype << 4) | vtype)


# ---------------------------------------------------------------------------
# Declarative struct layer
# ---------------------------------------------------------------------------
#
# Parquet's metadata structures are declared as ThriftStruct subclasses with a
# FIELDS table: {field_id: (name, field_type)} where field_type is one of the
# T_* singletons below, a ThriftStruct subclass, or a container wrapper.


class TType:
    """Scalar thrift field type descriptor."""

    __slots__ = ("name", "compact_type")

    def __init__(self, name: str, compact_type: int):
        self.name = name
        self.compact_type = compact_type

    def __repr__(self):
        return f"T_{self.name}"


T_BOOL = TType("BOOL", CT_BOOLEAN_TRUE)  # compact type resolved at write time
T_BYTE = TType("BYTE", CT_BYTE)
T_I16 = TType("I16", CT_I16)
T_I32 = TType("I32", CT_I32)
T_I64 = TType("I64", CT_I64)
T_DOUBLE = TType("DOUBLE", CT_DOUBLE)
T_BINARY = TType("BINARY", CT_BINARY)
T_STRING = TType("STRING", CT_BINARY)  # decoded as utf-8 str


class TList:
    __slots__ = ("elem",)

    def __init__(self, elem):
        self.elem = elem


def _compact_type_of(ftype) -> int:
    if isinstance(ftype, TType):
        return ftype.compact_type
    if isinstance(ftype, TList):
        return CT_LIST
    if isinstance(ftype, type) and issubclass(ftype, ThriftStruct):
        return CT_STRUCT
    raise TypeError(f"bad thrift field type {ftype!r}")


def _read_value(reader: CompactReader, ftype, ctype: int,
                in_container: bool = False):
    if isinstance(ftype, TType):
        if ftype is T_BOOL:
            # at field position the value lives in the header ctype; as a
            # container element it occupies one payload byte (same split
            # CompactReader.skip makes)
            if not in_container and ctype in (CT_BOOLEAN_TRUE, CT_BOOLEAN_FALSE):
                return ctype == CT_BOOLEAN_TRUE
            return reader.read_byte() == CT_BOOLEAN_TRUE
        if ftype is T_BYTE:
            b = reader.read_byte()
            return b - 256 if b >= 128 else b
        if ftype in (T_I16, T_I32, T_I64):
            return reader.read_zigzag()
        if ftype is T_DOUBLE:
            return reader.read_double()
        if ftype is T_BINARY:
            return reader.read_binary()
        if ftype is T_STRING:
            return reader.read_binary().decode("utf-8", errors="replace")
        raise ThriftDecodeError(f"unhandled scalar type {ftype}")
    if isinstance(ftype, TList):
        size, elem_ctype = reader.read_list_header()
        return [
            _read_value(reader, ftype.elem, elem_ctype, in_container=True)
            for _ in range(size)
        ]
    if isinstance(ftype, type) and issubclass(ftype, ThriftStruct):
        return ftype.read(reader)
    raise ThriftDecodeError(f"unhandled field type {ftype!r}")


def _write_value(writer: CompactWriter, ftype, value) -> None:
    if isinstance(ftype, TType):
        if ftype is T_BOOL:
            # Only reached inside containers; bools in fields are headers.
            writer.write_byte(CT_BOOLEAN_TRUE if value else CT_BOOLEAN_FALSE)
        elif ftype is T_BYTE:
            writer.write_byte(value & 0xFF)
        elif ftype in (T_I16, T_I32, T_I64):
            writer.write_zigzag(int(value))
        elif ftype is T_DOUBLE:
            writer.write_double(value)
        elif ftype is T_BINARY:
            writer.write_binary(bytes(value))
        elif ftype is T_STRING:
            writer.write_binary(value.encode("utf-8") if isinstance(value, str) else bytes(value))
        else:
            raise TypeError(f"unhandled scalar type {ftype}")
    elif isinstance(ftype, TList):
        writer.write_list_header(len(value), _compact_type_of(ftype.elem))
        for v in value:
            _write_value(writer, ftype.elem, v)
    elif isinstance(ftype, type) and issubclass(ftype, ThriftStruct):
        value.write(writer)
    else:
        raise TypeError(f"unhandled field type {ftype!r}")


class ThriftStruct:
    """Base for declaratively-specified thrift structs.

    Subclasses define ``FIELDS = {fid: (attr_name, field_type)}``.  Unknown
    fields encountered while reading are skipped (forward compatibility, the
    same stance parquet-mr's generated code takes).  Attributes default to
    ``None`` and only non-None attributes are written.
    """

    FIELDS: dict = {}

    def __init__(self, **kwargs):
        for name, _ in self.FIELDS.values():
            setattr(self, name, kwargs.pop(name, None))
        if kwargs:
            raise TypeError(f"unknown fields for {type(self).__name__}: {sorted(kwargs)}")

    @classmethod
    def read(cls, reader: CompactReader):
        obj = cls()
        last_fid = 0
        fields = cls.FIELDS
        while True:
            fid, ctype, last_fid = reader.read_field_header(last_fid)
            if ctype == CT_STOP:
                return obj
            spec = fields.get(fid)
            if spec is None:
                reader.skip(ctype)
                continue
            name, ftype = spec
            setattr(obj, name, _read_value(reader, ftype, ctype))

    @classmethod
    def from_bytes(cls, data, pos: int = 0):
        """Parse from a buffer; returns (obj, end_pos)."""
        reader = CompactReader(data, pos)
        obj = cls.read(reader)
        return obj, reader.pos

    def write(self, writer: CompactWriter) -> None:
        last_fid = 0
        for fid in sorted(self.FIELDS):
            name, ftype = self.FIELDS[fid]
            value = getattr(self, name)
            if value is None:
                continue
            if ftype is T_BOOL:
                ctype = CT_BOOLEAN_TRUE if value else CT_BOOLEAN_FALSE
                last_fid = writer.write_field_header(fid, ctype, last_fid)
                continue
            last_fid = writer.write_field_header(fid, _compact_type_of(ftype), last_fid)
            _write_value(writer, ftype, value)
        writer.write_stop()

    def to_bytes(self) -> bytes:
        w = CompactWriter()
        self.write(w)
        return w.getvalue()

    def __repr__(self):
        parts = []
        for name, _ in self.FIELDS.values():
            v = getattr(self, name)
            if v is not None:
                parts.append(f"{name}={v!r}")
        return f"{type(self).__name__}({', '.join(parts)})"

    def __eq__(self, other):
        if type(self) is not type(other):
            return NotImplemented
        return all(
            getattr(self, name) == getattr(other, name) for name, _ in self.FIELDS.values()
        )

    def __hash__(self):
        return object.__hash__(self)
