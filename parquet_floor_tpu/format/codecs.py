"""Compression codec dispatch (read: any codec in the footer; write: any
supported codec, SNAPPY pinned as the API default for parity with reference
``ParquetWriter.java:65``).

Replaces the reference's ``io.compress`` shim framework + JNI codec seam
(SURVEY.md §2.2/§2.4): here codecs are plain functions ``bytes -> bytes``
selected by the footer's codec id.  Snappy is first-party (C++ fast path via
ctypes when built, pure-Python fallback — both from scratch); GZIP rides
stdlib zlib; ZSTD is first-party too (from-scratch RFC 8878 decoder +
store-mode encoder in native/src/pftpu_zstd.cc), with the optional
``zstandard`` wheel preferred when installed.
"""

from __future__ import annotations

import gzip as _gzip
import io
import zlib
from typing import Callable, Dict, Optional, Tuple

from ..errors import UnsupportedFeatureError
from . import snappy as _snappy_py
from .parquet_thrift import CompressionCodec

try:  # optional wheel; gated per environment policy
    import zstandard as _zstd
except ImportError:  # pragma: no cover
    _zstd = None

# C++ fast path (built from parquet_floor_tpu/native); optional.
try:
    from ..native import binding as _native
except Exception:  # pragma: no cover - native lib is optional
    _native = None


class UnsupportedCodec(UnsupportedFeatureError):
    """A codec named by the footer has no implementation in this
    environment (taxonomy: an :class:`UnsupportedFeatureError`, not
    corruption — the file may be fine)."""


def _snappy_compress(data: bytes) -> bytes:
    if _native is not None and _native.available():
        return _native.snappy_compress(data)
    return _snappy_py.compress(data)


def _snappy_decompress(data: bytes, uncompressed_size: Optional[int] = None) -> bytes:
    if _native is not None and _native.available():
        return _native.snappy_decompress(data, uncompressed_size)
    return _snappy_py.decompress(data)


def _gzip_compress(data: bytes, level: Optional[int] = None) -> bytes:
    buf = io.BytesIO()
    with _gzip.GzipFile(
        fileobj=buf, mode="wb", mtime=0,
        compresslevel=9 if level is None else level,
    ) as f:
        f.write(data)
    return buf.getvalue()


def _gzip_decompress(data: bytes, uncompressed_size=None) -> bytes:
    # Accept both gzip-framed and raw zlib streams (readers must be liberal).
    try:
        return _gzip.decompress(data)
    except OSError:
        return zlib.decompress(data)


def _zstd_compress(data: bytes, level: Optional[int] = None) -> bytes:
    # Prefer the optional wheel (real entropy coding); else the first-party
    # native store-mode encoder (valid frames, raw blocks).
    if _zstd is not None:
        return _zstd.ZstdCompressor(
            level=3 if level is None else level
        ).compress(data)
    if level is not None:
        # the store-mode fallback has no levels: writing essentially
        # uncompressed frames after an explicit level request would be
        # a silent lie — refuse loudly
        raise UnsupportedCodec(
            "ZSTD codec_level needs the 'zstandard' wheel (the built-in "
            "native encoder is store-mode and has no levels)"
        )
    if _native is not None and _native.available():
        return _native.zstd_compress(data)
    raise UnsupportedCodec("ZSTD write needs the native library or 'zstandard'")


def _zstd_decompress(data: bytes, uncompressed_size=None) -> bytes:
    # Prefer the wheel (vectorized libzstd) when installed; else the
    # first-party RFC 8878 decoder (native/src/pftpu_zstd.cc).
    if _zstd is not None:
        d = _zstd.ZstdDecompressor()
        if uncompressed_size:
            return d.decompress(data, max_output_size=uncompressed_size)
        return d.decompress(data)
    if _native is not None and _native.available() and uncompressed_size is not None:
        return _native.zstd_decompress(data, uncompressed_size)
    if _native is not None and _native.available():
        # size unknown: grow until the frame fits (frames carry FCS usually,
        # but the C ABI wants a caller buffer; double until it decodes)
        cap = max(len(data) * 4, 1 << 16)
        while cap <= 1 << 31:
            try:
                return _native.zstd_decompress_unsized(data, cap)
            except ValueError as e:
                if "grow" not in str(e):
                    raise
                cap *= 2
        raise ValueError("zstd frame too large")
    raise UnsupportedCodec("ZSTD read needs the native library or 'zstandard'")


def _lz4_raw_decompress(data: bytes, uncompressed_size=None) -> bytes:
    """LZ4 raw block decode: native single pass when built, else Python."""
    if (
        _native is not None
        and _native.available()
        and uncompressed_size is not None
    ):
        return _native.lz4_decompress(bytes(data), uncompressed_size)
    out = bytearray()
    pos = 0
    n = len(data)
    while pos < n:
        token = data[pos]
        pos += 1
        lit_len = token >> 4
        if lit_len == 15:
            while True:
                b = data[pos]
                pos += 1
                lit_len += b
                if b != 255:
                    break
        out += data[pos : pos + lit_len]
        pos += lit_len
        if pos >= n:
            break  # last block ends with literals
        offset = int.from_bytes(data[pos : pos + 2], "little")
        pos += 2
        if offset == 0:
            raise ValueError("LZ4: zero offset")
        mlen = token & 0xF
        if mlen == 15:
            while True:
                b = data[pos]
                pos += 1
                mlen += b
                if b != 255:
                    break
        mlen += 4
        src = len(out) - offset
        if src < 0:
            raise ValueError("LZ4: offset out of range")
        for _ in range(mlen):
            out.append(out[src])
            src += 1
    return bytes(out)


def _lz4_raw_compress(data: bytes) -> bytes:
    """Valid LZ4 raw block: literals-only (correct, not space-optimal)."""
    out = bytearray()
    n = len(data)
    lit_len = n
    token_lit = 15 if lit_len >= 15 else lit_len
    out.append(token_lit << 4)
    if lit_len >= 15:
        rem = lit_len - 15
        while rem >= 255:
            out.append(255)
            rem -= 255
        out.append(rem)
    out += data
    return bytes(out)


def _lz4_block_capped(data: bytes, cap: int) -> bytes:
    """Decode one inner LZ4 block of unknown size ≤ cap (single pass)."""
    if _native is not None and _native.available():
        return _native.lz4_decompress_capped(bytes(data), cap)
    out = _lz4_raw_decompress(data, None)
    if len(out) > cap:
        raise ValueError("LZ4 block exceeds record length")
    return out


def _lz4_hadoop_decompress(data: bytes, uncompressed_size=None) -> bytes:
    """Parquet legacy LZ4: Hadoop framing — repeated
    [uncompressed_len u32be][compressed_len u32be][raw LZ4 block] records
    (each record may itself hold several inner blocks).  Some writers emit
    a bare raw block instead; be liberal and fall back to raw decode.
    """
    n = len(data)
    if n >= 8:
        out = bytearray()
        pos = 0
        ok = True
        while pos < n and ok:
            if pos + 4 > n:
                ok = False
                break
            ulen = int.from_bytes(data[pos : pos + 4], "big")
            pos += 4
            if ulen > (1 << 31):
                ok = False
                break
            # a record holds one or more [clen][block] inner records (the
            # Hadoop BlockCompressorStream splits input larger than its
            # codec buffer) — keep reading blocks until ulen bytes emerge
            produced = 0
            while produced < ulen:
                if pos + 4 > n:
                    ok = False
                    break
                clen = int.from_bytes(data[pos : pos + 4], "big")
                pos += 4
                if clen <= 0 or pos + clen > n:
                    ok = False
                    break
                try:
                    block = _lz4_block_capped(
                        data[pos : pos + clen], ulen - produced
                    )
                except (ValueError, IndexError):
                    # a bare raw block whose first bytes merely looked
                    # like a frame header: whole-buffer raw fallback
                    ok = False
                    break
                pos += clen
                produced += len(block)
                out += block
            if produced > ulen:
                ok = False
        if ok and (uncompressed_size is None or len(out) == uncompressed_size):
            return bytes(out)
    return _lz4_raw_decompress(data, uncompressed_size)


def _lz4_hadoop_compress(data: bytes) -> bytes:
    block = _lz4_raw_compress(data)
    return (
        len(data).to_bytes(4, "big") + len(block).to_bytes(4, "big") + block
    )


def _brotli_decompress(data: bytes, uncompressed_size=None,
                       max_output: int = 1 << 28) -> bytes:
    """BROTLI via the system library (format/brotli_codec.py) — the same
    native-library codec seam the reference's JNI codecs use.  The page
    path always passes the header's exact ``uncompressed_size``;
    ``max_output`` bounds the no-hint growth ladder for direct callers
    (forwarded so the registry path can raise it too)."""
    from . import brotli_codec

    if not brotli_codec.available():
        raise UnsupportedCodec(_codec_guidance(CompressionCodec.BROTLI))
    return brotli_codec.decompress(data, uncompressed_size, max_output)


def _brotli_compress(data: bytes, level: Optional[int] = None) -> bytes:
    from . import brotli_codec

    if not brotli_codec.encoder_available():
        raise UnsupportedCodec(_codec_guidance(CompressionCodec.BROTLI))
    return brotli_codec.compress(
        data, quality=5 if level is None else level
    )


def _lzo_decompress(data: bytes, uncompressed_size=None) -> bytes:
    """LZO via the system liblzo2 (format/lzo_codec.py) when present —
    the reference's reflective-codec-class architecture: without an LZO
    implementation on the "classpath" the footer codec fails at runtime
    there too (``ReflectionUtils.java:10-21``)."""
    from . import lzo_codec

    if not lzo_codec.available():
        raise UnsupportedCodec(_codec_guidance(CompressionCodec.LZO))
    return lzo_codec.hadoop_decompress(data, uncompressed_size)


def _lzo_compress(data: bytes) -> bytes:
    from . import lzo_codec

    if not lzo_codec.available():
        raise UnsupportedCodec(_codec_guidance(CompressionCodec.LZO))
    return lzo_codec.hadoop_compress(data)


_COMPRESSORS: Dict[int, Callable[[bytes], bytes]] = {
    CompressionCodec.UNCOMPRESSED: lambda d: d,
    CompressionCodec.SNAPPY: _snappy_compress,
    CompressionCodec.GZIP: _gzip_compress,
    CompressionCodec.ZSTD: _zstd_compress,
    CompressionCodec.LZ4_RAW: _lz4_raw_compress,
    CompressionCodec.LZ4: _lz4_hadoop_compress,
    CompressionCodec.BROTLI: _brotli_compress,
    CompressionCodec.LZO: _lzo_compress,
}

_DECOMPRESSORS: Dict[int, Callable[..., bytes]] = {
    CompressionCodec.UNCOMPRESSED: lambda d, s=None: bytes(d),
    CompressionCodec.SNAPPY: _snappy_decompress,
    CompressionCodec.GZIP: _gzip_decompress,
    CompressionCodec.ZSTD: _zstd_decompress,
    CompressionCodec.LZ4_RAW: _lz4_raw_decompress,
    CompressionCodec.LZ4: _lz4_hadoop_decompress,
    CompressionCodec.BROTLI: _brotli_decompress,
    CompressionCodec.LZO: _lzo_decompress,
}


def register_codec(
    codec: int,
    compressor: Optional[Callable[[bytes], bytes]] = None,
    decompressor: Optional[Callable[[bytes, Optional[int]], bytes]] = None,
) -> None:
    """User-pluggable codec seam — the open dispatch the reference gets
    from ``ReflectionUtils.newInstance`` instantiating any codec class the
    footer names (``ReflectionUtils.java:10-21``).  Register either side:

        register_codec(CompressionCodec.BROTLI,
                       compressor=brotli.compress,
                       decompressor=lambda d, n: brotli.decompress(d))

    ``decompressor`` receives ``(data, uncompressed_size_or_None)`` and
    must return exactly ``uncompressed_size`` bytes when given one (the
    footer's page header size is enforced after the call).  Registration
    overrides a built-in codec; pass None to leave a side unchanged.
    """
    if compressor is not None:
        _COMPRESSORS[codec] = compressor
    if decompressor is not None:
        _DECOMPRESSORS[codec] = decompressor


def _codec_guidance(codec: int) -> str:
    name = CompressionCodec.name(codec)
    if codec == CompressionCodec.BROTLI:
        return (
            f"{name}: the system Brotli library (libbrotlidec/"
            "libbrotlienc) was not found; install the 'brotli' runtime "
            "package, or plug a Python implementation in with "
            "register_codec(CompressionCodec.BROTLI, brotli.compress, "
            "lambda d, n: brotli.decompress(d))"
        )
    if codec == CompressionCodec.LZO:
        return (
            f"{name}: the system LZO library (liblzo2) was not found "
            "and none is vendored (GPL-licensed upstream); install "
            "liblzo2, or provide an implementation with register_codec("
            "CompressionCodec.LZO, ...)"
        )
    return (
        f"codec {name} is not supported; third-party codecs can be "
        "plugged in with register_codec()"
    )


# Builtin compressors that honor a level argument; a register_codec
# override replaces the _COMPRESSORS entry and therefore wins (its
# plugin signature has no level — levels are ignored for plugins).
_LEVEL_RANGES = {
    CompressionCodec.ZSTD: (1, 22),
    # 1..9 like parquet-mr: level 0 is stored-mode deflate, which would
    # silently write uncompressed bytes under CompressionCodec.GZIP
    CompressionCodec.GZIP: (1, 9),
    CompressionCodec.BROTLI: (0, 11),
}


def _builtin_level_fn(codec: int):
    """The builtin level-aware compressor for ``codec`` IF it is still
    the registered one (an override must win, as in decompress_into)."""
    builtin = {
        CompressionCodec.ZSTD: _zstd_compress,
        CompressionCodec.GZIP: _gzip_compress,
        CompressionCodec.BROTLI: _brotli_compress,
    }.get(codec)
    return builtin if _COMPRESSORS.get(codec) is builtin else None


def validate_level(codec: int, level: Optional[int]) -> None:
    """Fail-fast check for a requested compression level (the writer
    calls this before any bytes hit the sink).  Level-less codecs and
    register_codec plugins accept (and ignore) any level."""
    if level is None:
        return
    fn = _builtin_level_fn(codec)
    if fn is None:
        return  # level-less builtin or plugin override: level is ignored
    lo, hi = _LEVEL_RANGES[codec]
    if not (lo <= int(level) <= hi):
        raise ValueError(
            f"codec_level {level} out of range for "
            f"{CompressionCodec.name(codec)} (expected {lo}..{hi})"
        )
    if codec == CompressionCodec.ZSTD and _zstd is None:
        raise UnsupportedCodec(
            "ZSTD codec_level needs the 'zstandard' wheel (the built-in "
            "native encoder is store-mode and has no levels)"
        )


def compress(codec: int, data: bytes, level: Optional[int] = None) -> bytes:
    """Compress ``data`` with ``codec``.  ``level`` is the optional
    compression-level knob (parquet-mr's per-codec level config):
    honored by the BUILT-IN ZSTD (1..22, needs the zstandard wheel —
    the store-mode fallback refuses an explicit level), GZIP (1..9),
    and BROTLI (quality 0..11); silently ignored by level-less codecs
    (Snappy, LZ4) and by ``register_codec`` plugins (an override always
    wins over the level fast path)."""
    data = bytes(data)
    fn = _COMPRESSORS.get(codec)
    if fn is None:
        raise UnsupportedCodec(_codec_guidance(codec))
    if level is not None and _builtin_level_fn(codec) is fn:
        return fn(data, level)
    return fn(data)


def decompress(codec: int, data: bytes, uncompressed_size: Optional[int] = None) -> bytes:
    fn = _DECOMPRESSORS.get(codec)
    if fn is None:
        raise UnsupportedCodec(_codec_guidance(codec))
    out = fn(bytes(data), uncompressed_size)
    if uncompressed_size is not None and len(out) != uncompressed_size:
        raise ValueError(
            f"{CompressionCodec.name(codec)}: decompressed {len(out)} bytes, "
            f"footer said {uncompressed_size}"
        )
    return out


def decompress_into(
    codec: int, data, out_arr, offset: int, out_size: int
) -> None:
    """Decompress ``data`` directly into ``out_arr[offset:offset+out_size]``
    (C-contiguous uint8 ndarray).  Native codecs write in place; others
    decompress to bytes and copy — one copy either way, never two."""
    import numpy as np

    if codec == CompressionCodec.UNCOMPRESSED:
        out_arr[offset : offset + out_size] = np.frombuffer(
            data, dtype=np.uint8, count=out_size
        )
        return
    if _native is not None and _native.available():
        # the in-place native shortcuts apply only while the built-in
        # decoder is live — a register_codec override must win here too
        if (
            codec == CompressionCodec.SNAPPY
            and _DECOMPRESSORS.get(codec) is _snappy_decompress
        ):
            _native.snappy_decompress_into(bytes(data), out_arr, offset, out_size)
            return
        if (
            codec == CompressionCodec.ZSTD
            and _DECOMPRESSORS.get(codec) is _zstd_decompress
            and _zstd is None
        ):
            # first-party RFC 8878 decoder: in-place, but ~6× slower than
            # libzstd — only when the wheel is absent (the bytes+copy path
            # below then routes through the wheel, one extra memcpy)
            _native.zstd_decompress_into(bytes(data), out_arr, offset, out_size)
            return
    out = decompress(codec, data, out_size)
    out_arr[offset : offset + out_size] = np.frombuffer(out, dtype=np.uint8)


def supported_codecs() -> Tuple[int, ...]:
    base = [
        CompressionCodec.UNCOMPRESSED,
        CompressionCodec.SNAPPY,
        CompressionCodec.GZIP,
        CompressionCodec.LZ4_RAW,
        CompressionCodec.LZ4,
    ]
    zstd_builtin = _DECOMPRESSORS.get(CompressionCodec.ZSTD) is _zstd_decompress
    if (
        not zstd_builtin  # user-registered implementation
        or _zstd is not None
        or (_native is not None and _native.available())
    ):
        base.append(CompressionCodec.ZSTD)
    brotli_builtin = (
        _DECOMPRESSORS.get(CompressionCodec.BROTLI) is _brotli_decompress
    )
    if not brotli_builtin:
        base.append(CompressionCodec.BROTLI)
    else:
        from . import brotli_codec

        if brotli_codec.available():
            base.append(CompressionCodec.BROTLI)
    # user-registered codecs: the list means "readable" (decompressor
    # present), matching the backend gates above — a compressor-only
    # registration does not make a footer naming that codec readable
    for codec in _DECOMPRESSORS:
        if codec not in base and codec not in (
            CompressionCodec.ZSTD, CompressionCodec.BROTLI
        ):
            base.append(codec)
    return tuple(base)
