"""Persistent AOT executable cache for the fused decode programs.

First-touch XLA compilation dominates cold-start decode by orders of
magnitude (BENCH_r05: steady-state 0.28 ms/group vs ~14 s first-group
wall).  The programs themselves are deterministic functions of the file
*shape signature* — schema kinds, encodings, bucketed arena/slab shapes,
``out_perm`` presence — so a second process decoding a repeated schema
recompiles executables the first process already built.  This module
makes that compile a one-time cost per (signature, toolchain) pair:

* **Key**: sha256 over a format version, the jax/jaxlib versions, the
  backend platform + device kind (+ target device id) + ``jax_enable_x64``,
  the fused program tuple (``_ColSpec``\\ s are NamedTuples of plain
  values — their ``repr`` is the full static signature), the arena part
  count, every input aval ``(shape, dtype)``, and whether the program
  fuses an output permutation.  Two files differing in ANY of those get
  distinct keys — sharing an executable across them would be wrong, so
  the key is the correctness boundary, not a heuristic.
* **Entries**: one file per key under the cache dir
  (``PFTPU_EXEC_CACHE``), containing a magic + self-describing JSON
  header (versions, backend — validated on load as defense in depth
  beyond the hash) and the pickled
  ``jax.experimental.serialize_executable.serialize`` payload.  Writes
  go through a temp file + ``os.replace``, so concurrent processes
  racing on one key each land a complete entry and readers never see a
  partial one.
* **Failure domain**: a corrupt, truncated, version-mismatched, or
  runtime-incompatible entry falls through to a fresh ``lower().compile()``
  — never to wrong results (the recompiled executable is the same XLA
  program; outputs are bit-identical either way).  Backends whose
  executables cannot serialize simply skip the store and behave like an
  uncached process.

Observability (all registered in ``trace.names``):
``engine.exec_cache_hits`` / ``engine.exec_cache_misses`` count key
RESOLUTIONS (first time a program is needed in this process: a disk
load is a hit, a compile is a miss — in-memory reuse after that counts
as neither), ``engine.compile_ms`` accumulates compile wall, and the
``engine.exec_cache`` decision records each resolution's action.

The cache is OFF unless ``PFTPU_EXEC_CACHE`` names a directory (or a
:class:`ExecutableCache` is installed via :func:`activate`); when off,
:func:`dispatch` is exactly the plain jit call.  Docs: ``docs/perf.md``.
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import pickle
import tempfile
import threading
import time
from typing import Optional

from ..utils import trace

_FORMAT = 1
_MAGIC = b"PFEXEC1\n"
_MAX_MEMORY = 128   # loaded executables kept per process (programs are
#                     few: shape buckets converge by design)
_TMP_GRACE_S = 3600  # orphaned publish temp files older than this are
#                      swept by the GC (no live writer holds one that long)


def _env_signature() -> dict:
    """Everything about the runtime that an executable is compiled
    against.  Part of the key hash AND the entry header (the header
    check guards against hash collisions and hand-edited entries)."""
    import jax
    import jaxlib

    dev = jax.devices()[0]
    return {
        "format": _FORMAT,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", ""),
        "x64": bool(jax.config.jax_enable_x64),
    }


_compile_lock = threading.Lock()      # guards the per-key lock table
_compile_locks: dict = {}             # key -> threading.Lock (capped)
_MAX_KEY_LOCKS = 256                  # programs are few; this never trims
#                                       a lock someone still holds (locks
#                                       are only dropped when un-held)
_flag_lock = threading.Lock()         # guards the refcounted flag flip
_flag_depth = 0
_flag_prev = False


def _key_compile_lock(key: str) -> threading.Lock:
    """One lock PER CACHE KEY, so k mesh devices compiling k distinct
    entries proceed concurrently while two threads racing the SAME
    program still serialize (exactly one compiles; the loser finds the
    published entry)."""
    with _compile_lock:
        lk = _compile_locks.get(key)
        if lk is None:
            if len(_compile_locks) >= _MAX_KEY_LOCKS:
                for k in list(_compile_locks):
                    if not _compile_locks[k].locked():
                        del _compile_locks[k]
                        if len(_compile_locks) < _MAX_KEY_LOCKS:
                            break
            lk = _compile_locks[key] = threading.Lock()
        return lk

# Interpreter-exit protocol for in-flight preloads: a DAEMON thread
# reaped mid-XLA-deserialize aborts the whole process ("terminate
# called without an active exception"), and a plain non-daemon thread
# would stall exit through every remaining entry (threading joins
# non-daemon threads BEFORE atexit handlers run, so an atexit stop flag
# fires too late).  Instead: daemon threads + a stop event raised from
# ``threading._register_atexit`` — those callbacks run at the START of
# threading's shutdown, before any join and before teardown reaps
# daemons — then an explicit join, so exit waits at most ONE entry's
# deserialize.  (Fallback for interpreters without the private hook:
# plain atexit, which for daemon threads still runs before teardown.)
_preload_stop = threading.Event()
_preload_threads: list = []
_preload_reg_lock = threading.Lock()
_preload_registered = False


def _stop_preloads() -> None:
    _preload_stop.set()
    for t in _preload_threads:
        t.join()


def _register_preload_shutdown() -> None:
    global _preload_registered
    with _preload_reg_lock:
        if _preload_registered:
            return
        _preload_registered = True
    reg = getattr(threading, "_register_atexit", None)
    if reg is not None:
        reg(_stop_preloads)
    else:  # pragma: no cover - older interpreters
        atexit.register(_stop_preloads)


def _compile_fresh(jitfn, static_args, args, key: str = ""):
    """``lower().compile()`` with jax's OWN persistent compilation
    cache bypassed.  An executable jax's cache deserialized cannot be
    re-serialized faithfully on XLA:CPU (the payload loads with
    "Symbols not found"), so an entry built from one poisons every
    later process — this cache must only ever serialize executables it
    freshly compiled.

    The flag flip is process-global, but compiles must NOT serialize
    process-wide: a k-device mesh warms k per-device entries
    concurrently (docs/multichip.md).  So the suspension is
    REFCOUNTED — the first compile in flight flips the flag off, the
    last one restores it — and mutual exclusion is per cache KEY
    (``_key_compile_lock``), so distinct programs (or one program's
    distinct per-device entries) compile in parallel while a same-key
    race still resolves to one compile.  A concurrent unrelated
    jax compile merely skips jax's cache while any of ours is in
    flight — slower, never wrong."""
    import jax

    global _flag_depth, _flag_prev
    with _key_compile_lock(key):
        with _flag_lock:
            if _flag_depth == 0:
                _flag_prev = bool(jax.config.jax_enable_compilation_cache)
                if _flag_prev:
                    jax.config.update("jax_enable_compilation_cache", False)
            _flag_depth += 1
        try:
            return jitfn.lower(*static_args, *args).compile()
        finally:
            with _flag_lock:
                _flag_depth -= 1
                if _flag_depth == 0 and _flag_prev:
                    jax.config.update("jax_enable_compilation_cache", True)


class _Entry:
    """One resolved executable.  ``trusted`` flips after the first
    successful call — a freshly DESERIALIZED executable gets one guarded
    invocation, so an entry that loads but cannot run on this runtime
    (driver/topology drift the header could not see) falls back to a
    fresh compile instead of poisoning the decode path.  ``preloaded``
    marks entries the eager PRELOAD deserialized ahead of use — their
    first resolution still counts as a cache hit (the accounting must
    not depend on who paid the deserialize wall)."""

    __slots__ = ("loaded", "trusted", "preloaded")

    def __init__(self, loaded, trusted: bool, preloaded: bool = False):
        self.loaded = loaded
        self.trusted = trusted
        self.preloaded = preloaded


class ExecutableCache:
    """Disk + memory cache of AOT-compiled fused decode executables.

    ``max_bytes`` (default from ``PFTPU_EXEC_CACHE_MAX_BYTES``; 0/None =
    unbounded) bounds the DIRECTORY: after each publish, entries are
    evicted least-recently-USED first (mtime order — loads touch their
    entry's mtime) until the total fits.  This is how stale-toolchain
    entries die: a jax upgrade changes every key, the old entries stop
    being touched, and the next publishes age them out.  The
    just-published entry is never evicted, even when it alone exceeds
    the cap (a cache that evicts its only usable entry would thrash)."""

    def __init__(self, path: str, max_bytes: Optional[int] = None):
        self.path = path
        if max_bytes is None:
            env = os.environ.get("PFTPU_EXEC_CACHE_MAX_BYTES")
            max_bytes = int(env) if env else 0
        if max_bytes < 0:
            raise ValueError(f"max_bytes must be >= 0, got {max_bytes}")
        self.max_bytes = int(max_bytes) or None
        self._lock = threading.Lock()
        self._mem: dict = {}         # key hex → _Entry
        self._key_cache: dict = {}   # signature tuple → key hex
        self._env = None             # computed lazily (needs a backend)
        self._preload_done = False
        self._hwm: Optional[dict] = None  # pushdown HWM sidecar (lazy)

    # -- pushdown capacity HWM sidecar ---------------------------------------
    #
    # ComputeRequest sizes its compact output from a scan-wide selection
    # high-water mark; the FIRST group of every process otherwise runs
    # at an initial-capacity guess and may pay a counted re-dispatch.
    # Persisting the HWM next to the executables (same lifetime, same
    # toolchain-agnostic keying by request signature) lets a warm
    # process skip the guess entirely (docs/pushdown.md).  Everything is
    # best-effort: a missing/corrupt/read-only sidecar degrades to the
    # in-process guess, never to an error on the scan path.

    _HWM_FILE = "pushdown_hwm.json"
    _HWM_MAX_ENTRIES = 512

    def _read_hwm_file(self) -> dict:
        """Parse the sidecar off disk (no lock held — file I/O must not
        stall other resolutions, the FL-LOCK002 contract).  The entry
        cap applies HERE too, so an oversized file left by an older
        build cannot grow unbounded through the merge-and-rewrite."""
        try:
            with open(os.path.join(self.path, self._HWM_FILE),
                      "rb") as fh:
                data = json.loads(fh.read())
            out = {
                str(k): int(v) for k, v in data.items()
                if isinstance(v, int) and v >= 0
            } if isinstance(data, dict) else {}
        except (OSError, ValueError):
            return {}
        if len(out) > self._HWM_MAX_ENTRIES:
            for k in list(out)[: len(out) - self._HWM_MAX_ENTRIES]:
                del out[k]
        return out

    def _hwm_map(self) -> dict:
        with self._lock:
            if self._hwm is not None:
                return self._hwm
        data = self._read_hwm_file()  # outside the lock (I/O)
        with self._lock:
            if self._hwm is None:
                self._hwm = data
            return self._hwm

    def load_hwm(self, key: str) -> Optional[int]:
        """Persisted selection HWM for one pushdown-request key, or
        None (first sight of this predicate on this cache dir)."""
        hwm = self._hwm_map()
        with self._lock:
            return hwm.get(key)

    def store_hwm(self, key: str, count: int) -> None:
        """Raise the persisted HWM for ``key`` (monotone — a smaller
        observation never shrinks it) and publish atomically."""
        hwm = self._hwm_map()
        with self._lock:
            if hwm.get(key, -1) >= count:
                return
            hwm[key] = int(count)
            if len(hwm) > self._HWM_MAX_ENTRIES:
                # drop arbitrary overflow (dict order = insertion): the
                # sidecar is a warm-start hint, not a database
                for k in list(hwm)[: len(hwm) - self._HWM_MAX_ENTRIES]:
                    del hwm[k]
            payload = dict(hwm)
        try:
            os.makedirs(self.path, exist_ok=True)
            # merge-with-disk under max(): concurrent processes each
            # publish their own maxima; last writer keeps both
            try:
                with open(os.path.join(self.path, self._HWM_FILE),
                          "rb") as fh:
                    disk = json.loads(fh.read())
                if isinstance(disk, dict):
                    for k, v in disk.items():
                        if isinstance(v, int) and \
                                v > payload.get(str(k), -1):
                            payload[str(k)] = v
            except (OSError, ValueError):
                pass
            if len(payload) > self._HWM_MAX_ENTRIES:
                # the cap must survive the merge: without re-trimming,
                # disk entries resurrect every pruned key and the file
                # grows forever (the just-stored key is kept)
                for k in list(payload):
                    if len(payload) <= self._HWM_MAX_ENTRIES:
                        break
                    if k != key:
                        del payload[k]
            fd, tmp = tempfile.mkstemp(
                dir=self.path, prefix=".hwm.", suffix=".tmp"
            )
            try:
                with os.fdopen(fd, "w") as fh:
                    json.dump(payload, fh)
                os.replace(tmp, os.path.join(self.path, self._HWM_FILE))
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
        except MemoryError:
            raise
        except Exception:
            pass  # best-effort by contract (docstring above)

    # -- keying --------------------------------------------------------------

    def _key(self, sig: tuple) -> str:
        with self._lock:
            k = self._key_cache.get(sig)
        if k is not None:
            return k
        if self._env is None:
            self._env = _env_signature()
        h = hashlib.sha256()
        h.update(json.dumps(self._env, sort_keys=True).encode())
        h.update(repr(sig).encode())
        k = h.hexdigest()
        with self._lock:
            if len(self._key_cache) > 4 * _MAX_MEMORY:
                self._key_cache.clear()
            self._key_cache[sig] = k
        return k

    def _entry_path(self, key: str) -> str:
        return os.path.join(self.path, f"{key}.pfexec")

    # -- disk ----------------------------------------------------------------

    def _load_disk(self, key: str):
        """Deserialize one entry, or None on miss/corruption/mismatch.
        Unreadable entries are removed so they cannot re-trip every
        process (best-effort: a concurrent writer may already have
        replaced them)."""
        p = self._entry_path(key)
        try:
            with open(p, "rb") as fh:
                blob = fh.read()
        except OSError:
            return None
        try:
            # touch: the GC evicts by mtime, so a load must refresh its
            # entry's recency or a hot executable ages out like a cold one
            os.utime(p, None)
        except OSError:
            pass
        try:
            if blob[: len(_MAGIC)] != _MAGIC:
                raise ValueError("bad magic")
            off = len(_MAGIC)
            hlen = int.from_bytes(blob[off : off + 4], "little")
            off += 4
            header = json.loads(blob[off : off + hlen])
            off += hlen
            if self._env is None:
                self._env = _env_signature()
            if header != self._env:
                raise ValueError(
                    f"header mismatch: entry {header}, runtime {self._env}"
                )
            from jax.experimental import serialize_executable as _se

            payload = pickle.loads(blob[off:])
            return _se.deserialize_and_load(*payload)
        except (OSError, MemoryError):
            raise
        except Exception as e:
            trace.decision("engine.exec_cache", {
                "action": "corrupt_entry",
                "key": key[:12],
                "error": str(e)[:200],
            })
            try:
                os.remove(p)
            except OSError:
                pass
            return None

    def _store_disk(self, key: str, compiled) -> None:
        """Serialize + atomically publish one entry (best-effort: an
        unsupported backend or a full disk degrades to uncached, never
        to an error on the decode path)."""
        try:
            from jax.experimental import serialize_executable as _se

            payload = pickle.dumps(_se.serialize(compiled))
            if self._env is None:
                self._env = _env_signature()
            header = json.dumps(self._env, sort_keys=True).encode()
            os.makedirs(self.path, exist_ok=True)
            fd, tmp = tempfile.mkstemp(
                dir=self.path, prefix=f".{key[:12]}.", suffix=".tmp"
            )
            try:
                with os.fdopen(fd, "wb") as fh:
                    fh.write(_MAGIC)
                    fh.write(len(header).to_bytes(4, "little"))
                    fh.write(header)
                    fh.write(payload)
                os.replace(tmp, self._entry_path(key))
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
            self._gc(keep=self._entry_path(key))
        except MemoryError:
            raise
        except Exception as e:
            # OSError included ON PURPOSE: a full disk or read-only
            # cache dir degrades to uncached (the compiled executable
            # still runs this process's decode), it must never fail a
            # decode that already compiled successfully
            trace.decision("engine.exec_cache", {
                "action": "store_failed",
                "key": key[:12],
                "error": str(e)[:200],
            })

    def _gc(self, keep: str) -> None:
        """Size-bounded directory GC at publish time (docstring policy:
        LRU by mtime, ``keep`` immune).  Best-effort everywhere — a
        racing process replacing or already-removing an entry must never
        fail THIS process's publish."""
        if not self.max_bytes:
            return
        try:
            names = os.listdir(self.path)
        except OSError:
            return
        entries = []
        total = 0
        now = time.time()
        for n in names:
            p = os.path.join(self.path, n)
            if n.endswith(".tmp"):
                # a crashed publish (killed between mkstemp and the
                # os.replace) orphans its temp file forever: sweep any
                # old enough that no live writer can still own it —
                # otherwise the directory's REAL usage exceeds the cap
                # unboundedly as crashes accumulate
                try:
                    if now - os.stat(p).st_mtime > _TMP_GRACE_S:
                        os.remove(p)
                except OSError:
                    pass
                continue
            if not n.endswith(".pfexec"):
                continue
            try:
                st = os.stat(p)
            except OSError:
                continue
            entries.append((st.st_mtime, st.st_size, p))
            total += st.st_size
        if total <= self.max_bytes:
            return
        evicted = 0
        freed = 0
        for _mtime, size, p in sorted(entries):
            if total <= self.max_bytes:
                break
            if p == keep:
                continue
            try:
                os.remove(p)
            except OSError:
                continue
            total -= size
            freed += size
            evicted += 1
        if evicted:
            trace.decision("engine.exec_cache", {
                "action": "gc",
                "evicted": evicted,
                "freed_bytes": freed,
                "max_bytes": self.max_bytes,
            })

    # -- preload -------------------------------------------------------------

    def preload(self, limit: int = _MAX_MEMORY) -> int:
        """Eagerly deserialize up to ``limit`` disk entries into memory
        (most recently used first — mtime order), so the ~0.2-0.3 s/entry
        deserialize wall is paid BEFORE the first decode needs the
        executable.  The engine calls this on a background thread at
        reader construction (``preload_async``), hiding the wall behind
        file opens; the first dispatch that finds a preloaded entry
        still counts an ``engine.exec_cache_hits`` resolution, so
        cold/warm accounting is preload-agnostic.  Idempotent per cache
        object; returns the number of entries loaded this call."""
        with self._lock:
            if self._preload_done:
                return 0
            self._preload_done = True
        t0 = time.perf_counter()
        try:
            names = [
                n for n in os.listdir(self.path) if n.endswith(".pfexec")
            ]
        except OSError:
            return 0

        def mtime(n: str) -> float:
            try:
                return os.stat(os.path.join(self.path, n)).st_mtime
            except OSError:
                return 0.0

        names.sort(key=mtime, reverse=True)
        loaded = 0
        for n in names[: max(int(limit), 0)]:
            if _preload_stop.is_set():
                break  # interpreter exiting: stop at an entry boundary
            key = n[: -len(".pfexec")]
            with self._lock:
                if key in self._mem or len(self._mem) >= _MAX_MEMORY:
                    continue
            exe = self._load_disk(key)
            if exe is None:
                continue
            with self._lock:
                if key not in self._mem and len(self._mem) < _MAX_MEMORY:
                    self._mem[key] = _Entry(exe, trusted=False,
                                            preloaded=True)
                    loaded += 1
        trace.decision("engine.exec_cache", {
            "action": "preload",
            "entries": loaded,
            "wall_ms": round((time.perf_counter() - t0) * 1e3, 1),
        })
        return loaded

    # -- resolution ----------------------------------------------------------

    def _compile(self, jitfn, static_args, args, key: str, why: str):
        t0 = time.perf_counter()
        compiled = _compile_fresh(jitfn, static_args, args, key)
        dt_ms = (time.perf_counter() - t0) * 1e3
        trace.count("engine.compile_ms", int(round(dt_ms)))
        trace.decision("engine.exec_cache", {
            "action": why,
            "key": key[:12],
            "compile_ms": round(dt_ms, 1),
        })
        self._store_disk(key, compiled)
        return compiled

    def call(self, jitfn, static_args: tuple, args: list, device=None):
        """Run ``jitfn(*static_args, *args)`` through the cache: memory,
        then disk, then a fresh AOT compile (stored for the next
        process).  ``device`` is the reader's target device (None =
        default) — part of the key, because an executable is bound to
        the device its inputs live on: two readers pinned to different
        devices must never share one.  Outputs are bit-identical on
        every path — it is the same XLA program either way."""
        aval_sig = tuple((tuple(a.shape), str(a.dtype)) for a in args)
        dev_tag = "default" if device is None else (
            f"{getattr(device, 'platform', '')}:{getattr(device, 'id', '')}"
        )
        sig = (static_args, aval_sig, dev_tag)
        key = self._key(sig)
        with self._lock:
            entry = self._mem.get(key)
            preload_hit = entry is not None and entry.preloaded
            if preload_hit:
                entry.preloaded = False
        if preload_hit:
            # first resolution of a PRELOADED entry: same accounting as
            # a direct disk hit — preload only moved the deserialize
            # wall, never the hit/miss truth
            trace.count("engine.exec_cache_hits")
            trace.decision("engine.exec_cache", {
                "action": "hit", "key": key[:12], "via": "preload",
            })
        if entry is None:
            loaded = self._load_disk(key)
            if loaded is not None:
                trace.count("engine.exec_cache_hits")
                trace.decision("engine.exec_cache", {
                    "action": "hit", "key": key[:12],
                })
                entry = _Entry(loaded, trusted=False)
            else:
                trace.count("engine.exec_cache_misses")
                entry = _Entry(
                    self._compile(jitfn, static_args, args, key, "miss"),
                    trusted=True,
                )
            with self._lock:
                if len(self._mem) >= _MAX_MEMORY:
                    self._mem.pop(next(iter(self._mem)))
                self._mem[key] = entry
        if entry.trusted:
            return entry.loaded(*args)
        # first invocation of a deserialized executable: guarded, so an
        # entry the header check could not reject (runtime drift) falls
        # back to a fresh compile — a genuine input error will re-raise
        # identically from the recompiled executable below
        try:
            out = entry.loaded(*args)
        except (OSError, MemoryError):
            raise
        except Exception as e:
            trace.decision("engine.exec_cache", {
                "action": "load_unusable",
                "key": key[:12],
                "error": str(e)[:200],
            })
            try:
                os.remove(self._entry_path(key))
            except OSError:
                pass
            entry = _Entry(
                self._compile(
                    jitfn, static_args, args, key, "recompile"
                ),
                trusted=True,
            )
            with self._lock:
                self._mem[key] = entry
            return entry.loaded(*args)
        entry.trusted = True
        return out


# ---------------------------------------------------------------------------
# The active cache (env-configured; tests may install one explicitly)
# ---------------------------------------------------------------------------

_caches: dict = {}       # dir → ExecutableCache (one per distinct dir)
_forced: Optional[ExecutableCache] = None
_lock = threading.Lock()


def activate(cache: Optional[ExecutableCache]) -> None:
    """Install ``cache`` as the process-wide active cache regardless of
    the environment (None restores env-driven resolution) — the test
    hook; production configuration is the ``PFTPU_EXEC_CACHE`` dir."""
    global _forced
    _forced = cache


def active() -> Optional[ExecutableCache]:
    """The cache :func:`dispatch` will use right now, or None (off)."""
    if _forced is not None:
        return _forced
    path = os.environ.get("PFTPU_EXEC_CACHE")
    if not path:
        return None
    with _lock:
        c = _caches.get(path)
        if c is None:
            c = _caches[path] = ExecutableCache(path)
        return c


def dispatch(jitfn, static_args: tuple, args: list, device=None):
    """The engine's one fused-launch entry point: the plain jit call
    when the cache is off, :meth:`ExecutableCache.call` when on."""
    cache = active()
    if cache is None:
        return jitfn(*static_args, *args)
    return cache.call(jitfn, static_args, args, device=device)


def preload_async() -> Optional[threading.Thread]:
    """Kick the active ENV-configured cache's :meth:`preload` onto a
    daemon thread (the engine calls this at reader construction, so the
    deserialize wall hides behind footer opens).  A test-forced cache
    (:func:`activate`) is never auto-preloaded — tests call
    ``preload()`` synchronously to stay deterministic.  Disable with
    ``PFTPU_EXEC_CACHE_PRELOAD=0``.  Returns the thread, or None when
    there is nothing to do."""
    if _forced is not None:
        return None
    if os.environ.get("PFTPU_EXEC_CACHE_PRELOAD", "1") == "0":
        return None
    cache = active()
    if cache is None:
        return None
    with cache._lock:
        if cache._preload_done:
            return None
    _register_preload_shutdown()
    t = threading.Thread(
        target=cache.preload, name="pftpu-exec-preload", daemon=True
    )
    _preload_threads.append(t)
    t.start()
    return t
