"""Device pushdown compute — the fused decode executable's compute tail.

The engine decodes a row group into device columns in ONE fused launch
(``tpu.engine``).  This module extends that launch with a compute tail,
so a selective or aggregating scan ships **results, not columns**:

* **Fused predicate evaluation** — a ``batch.predicate`` tree compiles
  (via its :func:`~parquet_floor_tpu.batch.predicate.tree` export) into
  device ops over the decoded columns.  Dictionary-encoded columns are
  evaluated on their *index streams* against a host-precomputed
  per-group dictionary-match mask (one bool per dictionary entry — this
  is also how string order comparisons work on device: the comparison
  runs on host, over distinct values, once per group); plain / BSS /
  delta / host-fallback columns compare post-decode.  Null cells never
  match (pyarrow ``filter`` drop semantics); the host twin is
  ``batch.predicate.eval_mask`` and the two are pinned identical by the
  differential suite.
* **Fused compaction** — ``mode="compact"`` gathers only the surviving
  rows into capacity-bounded outputs inside the same launch, so D2H
  ships ~selected rows instead of the whole group.  The capacity is a
  static shape chosen from a selection high-water mark shared across
  the scan (:class:`ComputeRequest`); a group whose survivors exceed it
  re-dispatches once with a grown capacity
  (``engine.pushdown_overflows``) — never a wrong result.
* **Partial aggregates** — count/sum/min/max over the selected rows,
  optionally grouped by a dictionary column's index stream, emitted as
  tiny per-group states (O(dictionary) values) that
  ``batch.aggregate.AggPartial.combine`` folds across row groups and
  files.  Semantics are pinned to ``pyarrow.compute``
  (``batch/aggregate.py`` docstring).

Everything static about the tail — the predicate tree, mode, capacity,
aggregate list, group capacity — rides the fused program's jit static
arguments, so it is part of the persistent executable-cache key
(``tpu.exec_cache``): same file + different predicate = different cache
entry, and a repeated pushdown program skips XLA compilation across
processes exactly like a plain decode.  Docs: ``docs/pushdown.md``.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..batch import predicate as _pred
from ..batch.aggregate import (
    ALL,
    Aggregate,
    AggPartial,
    neutral_max,
    neutral_min,
)
from ..errors import UnsupportedFeatureError
from ..utils import trace

_NUM_VDTYPES = ("int32", "int64", "float32", "float64", "bool")


class ComputeRequest:
    """One pushdown request, shared by every row group of a scan.

    ``predicate`` filters rows (None = select all); ``aggregate`` (a
    :class:`~parquet_floor_tpu.batch.aggregate.Aggregate`) switches the
    launch to partial-aggregate outputs; without it ``mode`` picks the
    filter output shape — ``"compact"`` (ship surviving rows only) or
    ``"mask"`` (ship full columns plus the selection mask).

    The request carries the scan-wide selection high-water mark the
    compact capacity is sized from: group 0 runs at
    ``initial_capacity`` (default ``max(n // 8, 256)`` — a filter
    passing under ~12% of rows never overflows it; a less selective
    one pays one counted re-dispatch on the first group and the HWM
    remembers), later groups at the bucketed max observed count.
    Share ONE request across a scan's readers so the HWM crosses file
    boundaries."""

    def __init__(self, predicate=None, aggregate: Optional[Aggregate] = None,
                 mode: str = "compact",
                 initial_capacity: Optional[int] = None,
                 cache_scope: Optional[str] = None,
                 exprs=None):
        if predicate is None and aggregate is None and not exprs:
            raise ValueError("ComputeRequest needs a predicate, an "
                             "aggregate, or projection exprs")
        if mode not in ("compact", "mask"):
            raise ValueError(f"bad pushdown mode {mode!r}")
        if aggregate is not None and not isinstance(aggregate, Aggregate):
            raise TypeError("aggregate must be a batch.aggregate.Aggregate")
        if exprs and aggregate is not None:
            raise ValueError(
                "projection exprs do not compose with aggregate pushdown "
                "(an aggregate launch ships states, not columns)"
            )
        if exprs:
            from ..query.expr import exprs_signature

            self.exprs = exprs_signature(exprs)
        else:
            self.exprs = ()
        self.tree = _pred.tree(predicate) if predicate is not None else None
        self.aggregate = aggregate
        self.mode = mode
        if initial_capacity is not None and initial_capacity < 1:
            raise ValueError("initial_capacity must be >= 1")
        self.initial_capacity = initial_capacity
        # dataset identity for the persisted HWM (docs/pushdown.md):
        # selectivity is a property of (predicate, DATA) — without a
        # scope, one unselective dataset would inflate every other
        # dataset's compact capacity forever.  None = no persistence.
        self.cache_scope = cache_scope
        self._lock = threading.Lock()
        self._max_seen = 0
        self._hwm_key: Optional[str] = None
        self._hwm_checked = False
        self._hwm_stored = 0

    def _hwm_cache_key(self) -> Optional[str]:
        """Stable sidecar key of this request's selection shape: the
        predicate tree + mode + DATASET scope (docs/pushdown.md — the
        persisted capacity HWM next to the exec cache).  Aggregate-only
        requests carry no compact capacity; scope-less requests don't
        persist (selectivity without a dataset identity is
        meaningless)."""
        if self.tree is None or self.mode != "compact" or \
                not self.cache_scope:
            return None
        if self._hwm_key is None:
            import hashlib

            self._hwm_key = hashlib.sha256(
                repr((self.tree, self.mode, self.cache_scope)).encode()
            ).hexdigest()[:32]
        return self._hwm_key

    def _restore_hwm(self) -> None:
        """One-time warm-start: adopt the HWM a previous process
        persisted next to the exec cache, so the first group skips the
        initial-capacity guess (and its possible re-dispatch).  An
        EXPLICIT ``initial_capacity`` wins — a caller override must
        never be silently replaced by a cached hint."""
        from . import exec_cache

        with self._lock:
            if self._hwm_checked:
                return
            self._hwm_checked = True
        if self.initial_capacity is not None:
            return
        key = self._hwm_cache_key()
        cache = exec_cache.active()
        if key is None or cache is None:
            return
        v = cache.load_hwm(key)
        if v:
            with self._lock:
                if v > self._max_seen:
                    self._max_seen = v
                    self._hwm_stored = v
            trace.decision("engine.pushdown", {
                "action": "hwm_restore", "rows": int(v),
            })

    def columns_needed(self) -> set:
        out = set()
        if self.tree is not None:
            out |= _pred.tree_columns(self.tree)
        if self.aggregate is not None:
            out |= self.aggregate.columns()
        if self.exprs:
            from ..query.expr import expr_columns

            for _name, et in self.exprs:
                out |= expr_columns(et)
        return out

    def capacity_for(self, n: int) -> int:
        from .engine import _bucket15

        self._restore_hwm()
        with self._lock:
            seen = self._max_seen
        if seen:
            return max(1, min(n, _bucket15(seen)))
        init = self.initial_capacity
        if init is None:
            init = max(n // 8, 256)
        return max(1, min(n, _bucket15(init)))

    def observe(self, count: int) -> None:
        from .engine import _bucket15

        with self._lock:
            if count > self._max_seen:
                self._max_seen = count
            # persist only when the BUCKETED capacity grows: capacity
            # is bucket-granular, so finer maxima change nothing a warm
            # start could use — this bounds the sidecar's synchronous
            # read-merge-rewrite to O(log) publishes per scan even on
            # data whose per-group selectivity rises monotonically
            publish = self._hwm_stored == 0 or (
                _bucket15(count) > _bucket15(self._hwm_stored)
            )
            if publish:
                self._hwm_stored = max(count, self._hwm_stored)
        if publish:
            from . import exec_cache

            key = self._hwm_cache_key()
            cache = exec_cache.active()
            if key is not None and cache is not None:
                cache.store_hwm(key, int(count))


class _CPlan(NamedTuple):
    """The STATIC compute tail — every field hashable, part of the jit
    static signature and therefore of the exec-cache key."""

    tree: tuple            # rewritten static tree (("true",) = select all)
    mode: str              # compact | mask | agg
    capacity: int          # compact output rows (0 otherwise)
    ship: tuple            # column names emitted (compact/mask modes)
    aggs: tuple            # ((col, op), ...) — empty without aggregate
    group: Optional[str]   # group-by column name
    gcap: int              # group scatter capacity (dict_cap)
    n_masks: int           # dictionary-match mask input arrays
    n: int                 # rows in the group
    # ((name, static expr tree), ...) — computed output columns
    # (docs/query.md); appended with a default so existing positional
    # constructions (and pickled plans) keep working
    exprs: tuple = ()


@dataclass
class BuiltCompute:
    """One staged group's compute tail: the static plan plus the
    per-group host data it references — dictionary-match masks (shipped
    as extra device inputs) and the group-by column's dictionary values
    (stay on host; ``partial_from_device`` maps slots back to keys)."""

    request: ComputeRequest
    cplan: _CPlan
    masks: List[np.ndarray] = field(default_factory=list)
    group_keys: Optional[list] = None     # slot -> key value (len num_dict)

    def with_capacity(self, capacity: int) -> "BuiltCompute":
        out = BuiltCompute(self.request, self.cplan._replace(
            capacity=int(capacity)), self.masks, self.group_keys)
        return out


@dataclass
class PushdownResult:
    """What a pushdown launch returns: compacted (or full) device
    columns for filter modes, a partial aggregate state for aggregate
    mode, and the selection accounting either way."""

    columns: dict
    num_rows: int
    num_selected: int
    mask: Optional[jax.Array] = None          # mode="mask" only
    agg: Optional[AggPartial] = None
    # computed output columns (docs/query.md): name -> (values, null
    # mask|None), row-aligned with ``columns`` (compact-trimmed in
    # compact mode, full-length in mask mode)
    exprs: Optional[dict] = None


# ---------------------------------------------------------------------------
# Host plan building (stage time)
# ---------------------------------------------------------------------------

_DICT_KINDS = ("dict", "dict_str", "dict_idx", "dict_idx_num")


def _cmp_host(vals, op: str, v):
    """Host comparison used for dictionary-match masks (full semantics,
    including string order — it runs over distinct values on host)."""
    if isinstance(vals, list):  # bytes dictionary
        vals = np.array(vals, dtype=object)
        if isinstance(v, str):
            v = v.encode("utf-8", "surrogateescape")
    try:
        return np.asarray(_pred._cmp_arrays(vals, op, v), dtype=bool)
    except TypeError:
        return np.zeros(len(vals), bool)


def _dict_values(spec, stage, arena):
    """The column's dictionary VALUES on host (numeric np array in the
    exact physical dtype, or a list of bytes for strings)."""
    from ..format.encodings.plain import decode_plain
    from ..format.parquet_thrift import Type
    from .engine import _NP_DTYPE

    off, size = stage.dict_off, stage.dict_size
    pt = stage.desc.physical_type
    if spec.kind in ("dict", "dict_idx_num"):
        dt = np.dtype(_NP_DTYPE[pt])
        num = size // dt.itemsize
        return np.frombuffer(
            bytes(arena[off : off + size]), dtype=dt, count=num
        )
    content = bytes(arena[off : off + size])
    count = int(getattr(stage, "dict_count", 0) or 0)
    col, _ = decode_plain(content, count, Type.BYTE_ARRAY)
    data = col.data.tobytes()
    offs = col.offsets
    return [data[offs[i] : offs[i + 1]] for i in range(len(col))]


def _spec_by_name(specs, name: str):
    for s in specs:
        if s.name == name:
            return s
    raise ValueError(f"pushdown references column {name!r}, which is not "
                     "in the staged program (is it in the file?)")


def _reject_lossy_double(spec) -> None:
    if spec.vdtype == "float64" and spec.f64mode in ("f32", "bits"):
        raise UnsupportedFeatureError(
            f"pushdown on DOUBLE column {spec.name!r} needs exact device "
            "float64 — use float64_policy='float64' (dictionary-encoded "
            "DOUBLE columns work under any policy: their comparisons run "
            "on the host dictionary)"
        )


def build_for_program(request: ComputeRequest, specs, stages_by_name: dict,
                      arena, num_rows: int) -> BuiltCompute:
    """Compile a :class:`ComputeRequest` against one staged program.

    Raises ``UnsupportedFeatureError`` for shapes the device tail cannot
    evaluate (repeated columns anywhere in the program; order
    comparisons on non-dictionary strings; DOUBLE under a lossy float
    policy; group-by on a non-dictionary column) — callers fall back to
    host evaluation per group, results identical by construction."""
    for s in specs:
        if s.max_rep > 0:
            raise UnsupportedFeatureError(
                "pushdown cannot run over repeated (nested) columns; "
                f"project {s.name!r} away"
            )
    built = BuiltCompute(request, _CPlan(
        ("true",), "agg" if request.aggregate is not None else request.mode,
        0, (), (), None, 0, 0, int(num_rows),
    ))

    def rewrite(t: tuple) -> tuple:
        kind = t[0]
        if kind in ("and", "or"):
            return (kind, rewrite(t[1]), rewrite(t[2]))
        if kind == "isnull":
            spec = _spec_by_name(specs, t[1])
            if spec.max_def == 0:
                return ("const", not t[2])
            return ("isnull", t[1], t[2])
        _, name, op, v = t
        spec = _spec_by_name(specs, name)
        if spec.kind in _DICT_KINDS and name in stages_by_name and \
                getattr(stages_by_name[name], "dict_off", -1) >= 0:
            dvals = _dict_values(spec, stages_by_name[name], arena)
            dmask = np.zeros(max(spec.dict_cap, 1), bool)
            m = _cmp_host(dvals, op, v)
            dmask[: len(m)] = m
            built.masks.append(dmask)
            return ("dmask", name, op, len(built.masks) - 1)
        if spec.vdtype in _NUM_VDTYPES and spec.max_len == 0:
            _reject_lossy_double(spec)
            lit = v
            if isinstance(lit, bytes):
                raise UnsupportedFeatureError(
                    f"string literal compared against numeric column "
                    f"{name!r}"
                )
            return ("num", name, op, lit)
        if spec.max_len > 0:  # device byte rows (plain_str / host_str)
            if op not in ("==", "!="):
                raise UnsupportedFeatureError(
                    f"order comparison {op!r} on non-dictionary string "
                    f"column {name!r} is host-only (dictionary-encoded "
                    "strings support it via the host dictionary mask)"
                )
            lit = (
                v.encode("utf-8", "surrogateescape")
                if isinstance(v, str) else bytes(v)
            )
            return ("str", name, op, lit)
        raise UnsupportedFeatureError(
            f"pushdown cannot evaluate column {name!r} "
            f"(kind {spec.kind!r}, vdtype {spec.vdtype!r})"
        )

    tree = rewrite(request.tree) if request.tree is not None else ("true",)
    ship: tuple = ()
    aggs: tuple = ()
    group = None
    gcap = 0
    capacity = 0
    agg = request.aggregate
    if agg is not None:
        for c, op in agg.aggs:
            spec = _spec_by_name(specs, c)
            if op != "count":
                if spec.vdtype not in ("int32", "int64", "float32",
                                       "float64") or spec.max_len > 0:
                    raise UnsupportedFeatureError(
                        f"aggregate {op!r} needs a numeric column, got "
                        f"{c!r} (vdtype {spec.vdtype!r})"
                    )
                if spec.kind in ("dict_idx", "dict_idx_num"):
                    # index-form output IS the index stream — summing it
                    # would aggregate dictionary slots, not values
                    raise UnsupportedFeatureError(
                        f"aggregate {op!r} over index-form dictionary "
                        f"column {c!r} — use dict_form='gather'"
                    )
                _reject_lossy_double(spec)
        aggs = agg.aggs
        if agg.group_by is not None:
            gspec = _spec_by_name(specs, agg.group_by)
            stage = stages_by_name.get(agg.group_by)
            if gspec.kind not in _DICT_KINDS or stage is None or \
                    getattr(stage, "dict_off", -1) < 0:
                raise UnsupportedFeatureError(
                    f"group_by column {agg.group_by!r} is not "
                    "dictionary-encoded in this row group — device "
                    "group-by runs over dictionary indices"
                )
            group = agg.group_by
            gcap = max(int(gspec.dict_cap), 1)
            dvals = _dict_values(gspec, stage, arena)
            built.group_keys = (
                [v.item() for v in dvals]
                if isinstance(dvals, np.ndarray) else list(dvals)
            )
        mode = "agg"
    else:
        mode = request.mode
        ship = tuple(s.name for s in specs)
        if mode == "compact":
            capacity = request.capacity_for(int(num_rows))
    exprs = getattr(request, "exprs", ())
    if exprs:
        _check_expr_specs(exprs, specs)
    built.cplan = _CPlan(
        tree, mode, capacity, ship, aggs, group, gcap,
        len(built.masks), int(num_rows), exprs,
    )
    return built


def _check_expr_specs(exprs, specs) -> None:
    """Plan-time validation of projection exprs against one staged
    program: inputs must be numeric non-string gather-form columns the
    device tail can evaluate EXACTLY — everything else raises
    ``UnsupportedFeatureError`` (the whole-scan host-fallback
    trigger)."""
    from ..query.expr import expr_columns

    spec_names = {s.name for s in specs}
    for out_name, et in exprs:
        if out_name in spec_names:
            raise ValueError(
                f"expression output {out_name!r} collides with a "
                "projected source column — name it something else"
            )
        for cname in sorted(expr_columns(et)):
            spec = _spec_by_name(specs, cname)
            if spec.kind in ("dict_idx", "dict_idx_num"):
                raise UnsupportedFeatureError(
                    f"expression input {cname!r} is an index-form "
                    "dictionary column (values are dictionary slots) — "
                    "use dict_form='gather'"
                )
            if spec.vdtype not in _NUM_VDTYPES or spec.max_len > 0:
                raise UnsupportedFeatureError(
                    f"expression input {cname!r} is not numeric "
                    f"(kind {spec.kind!r}, vdtype {spec.vdtype!r}) — "
                    "device expressions run over numeric columns"
                )
            _reject_lossy_double(spec)


# ---------------------------------------------------------------------------
# Device evaluation (traced inside the fused executable)
# ---------------------------------------------------------------------------
#
# ``ctx`` maps column name -> (vals, mask, lens, idx): the column's
# row-aligned decoded outputs plus, for dictionary kinds, the
# row-aligned dictionary index stream.  Everything here is pure jnp —
# it traces into the one fused launch.

def _present(ctx_entry, n: int):
    mask = ctx_entry[1]
    return jnp.ones((n,), bool) if mask is None else ~mask


def eval_selection(tree: tuple, ctx: dict, masks, n: int):
    kind = tree[0]
    if kind == "true":
        return jnp.ones((n,), bool)
    if kind == "const":
        return jnp.full((n,), bool(tree[1]))
    if kind == "and":
        return eval_selection(tree[1], ctx, masks, n) & \
            eval_selection(tree[2], ctx, masks, n)
    if kind == "or":
        return eval_selection(tree[1], ctx, masks, n) | \
            eval_selection(tree[2], ctx, masks, n)
    if kind == "isnull":
        entry = ctx[tree[1]]
        mask = entry[1]
        if mask is None:
            return jnp.full((n,), not tree[2])
        return mask if tree[2] else ~mask
    if kind == "dmask":
        _, name, _op, slot = tree
        vals, mask, lens, idx = ctx[name]
        return masks[slot][idx] & _present(ctx[name], n)
    if kind == "num":
        _, name, op, v = tree
        vals, mask, lens, idx = ctx[name]
        # _cmp_arrays is polymorphic over numpy AND jnp arrays — the ONE
        # operator dispatch shared with the host eval_mask twin
        out = _pred._cmp_arrays(vals, op, v)
        return out & _present(ctx[name], n)
    if kind == "str":
        _, name, op, lit = tree
        vals, mask, lens, idx = ctx[name]
        k = len(lit)
        if k > int(vals.shape[1]):
            eq = jnp.zeros((n,), bool)
        elif k == 0:
            eq = lens == 0
        else:
            # static literal → device constant (tuple(): trace-time only)
            litv = jnp.asarray(tuple(lit), dtype=jnp.uint8)
            eq = (lens == k) & jnp.all(
                vals[:, :k] == litv[None, :], axis=1
            )
        out = eq if op == "==" else ~eq
        return out & _present(ctx[name], n)
    raise ValueError(f"unknown pushdown leaf {kind!r}")  # pragma: no cover


def compact_indices(sel, capacity: int, n: int):
    """Indices of the selected rows, padded past the true count — the
    fused compaction gather's map (pad entries clip to the last row and
    are trimmed by ``num_selected`` on host)."""
    idx = jnp.nonzero(sel, size=capacity, fill_value=n)[0]
    return jnp.clip(idx, 0, max(n - 1, 0)).astype(jnp.int32)


def take_rows(a, sel_idx):
    return None if a is None else jnp.take(a, sel_idx, axis=0)


def eval_exprs(exprs: tuple, ctx: dict, n: int, xp=jnp):
    """Evaluate the plan's projection exprs over the decoded ``ctx``
    (docs/query.md) — pure ``xp`` ops, so inside the fused launch this
    traces into the SAME executable as the decode.  Returns one
    ``(values, null_mask|None)`` pair per expr, in plan order."""
    from ..query.expr import eval_expr

    def resolve(name):
        vals, mask, _lens, _idx = ctx[name]
        return vals, mask

    return tuple(
        eval_expr(et, resolve, n, xp) for _name, et in exprs
    )


def _acc_dtype(dtype):
    return jnp.float64 if np.dtype(dtype).kind == "f" else jnp.int64


def eval_aggregates(cplan: _CPlan, ctx: dict, sel):
    """The aggregate tail: a flat tuple of tiny arrays —
    ``(rows, *per-agg states)`` — scalars ungrouped, ``gcap + 1`` slots
    grouped (slot ``gcap`` = the null-key group; unselected rows scatter
    out of bounds and drop).  ``partial_from_device`` unpacks."""
    n = cplan.n
    outs = []
    if cplan.group is not None:
        gentry = ctx[cplan.group]
        gidx = gentry[3].astype(jnp.int32)
        gpresent = _present(gentry, n)
        gcap = cplan.gcap
        base = jnp.where(
            sel & gpresent, gidx,
            jnp.where(sel, gcap, gcap + 1),  # null key | dropped
        )
        rows = jnp.zeros(gcap + 1, jnp.int64).at[base].add(1, mode="drop")
        outs.append(rows)
        for c, op in cplan.aggs:
            entry = ctx[c]
            vals = entry[0]
            present = sel & _present(entry, n)
            nv = jnp.zeros(gcap + 1, jnp.int64).at[base].add(
                jnp.where(present, 1, 0), mode="drop"
            )
            outs.append(nv)
            if op == "count":
                continue
            if op == "sum":
                acc = _acc_dtype(vals.dtype)
                outs.append(
                    jnp.zeros(gcap + 1, acc).at[base].add(
                        jnp.where(present, vals.astype(acc), 0),
                        mode="drop",
                    )
                )
                continue
            ok = present
            if jnp.issubdtype(vals.dtype, jnp.floating):
                ok = ok & ~jnp.isnan(vals)  # pyarrow min_max skips NaN
            if op == "min":
                neut = neutral_min(np.dtype(str(vals.dtype)))
                outs.append(
                    jnp.full(gcap + 1, neut, vals.dtype).at[base].min(
                        jnp.where(ok, vals, neut), mode="drop"
                    )
                )
            else:
                neut = neutral_max(np.dtype(str(vals.dtype)))
                outs.append(
                    jnp.full(gcap + 1, neut, vals.dtype).at[base].max(
                        jnp.where(ok, vals, neut), mode="drop"
                    )
                )
        return tuple(outs)
    outs.append(jnp.sum(sel).astype(jnp.int64))
    for c, op in cplan.aggs:
        entry = ctx[c]
        vals = entry[0]
        present = sel & _present(entry, n)
        outs.append(jnp.sum(present).astype(jnp.int64))
        if op == "count":
            continue
        if op == "sum":
            acc = _acc_dtype(vals.dtype)
            outs.append(jnp.sum(jnp.where(present, vals.astype(acc), 0)))
            continue
        ok = present
        if jnp.issubdtype(vals.dtype, jnp.floating):
            ok = ok & ~jnp.isnan(vals)
        if op == "min":
            neut = neutral_min(np.dtype(str(vals.dtype)))
            outs.append(jnp.min(jnp.where(ok, vals, neut)))
        else:
            neut = neutral_max(np.dtype(str(vals.dtype)))
            outs.append(jnp.max(jnp.where(ok, vals, neut)))
    return tuple(outs)


def partial_from_device(built: BuiltCompute, fetched: list) -> AggPartial:
    """Build the host :class:`AggPartial` from one launch's fetched
    aggregate arrays (O(groups) bytes of D2H — this is the whole point)."""
    spec = built.request.aggregate
    cplan = built.cplan
    out = AggPartial(spec)
    it = iter(fetched)
    if cplan.group is None:
        rows = int(next(it))
        out.add_rows(ALL, rows)
        for i, (c, op) in enumerate(cplan.aggs):
            nv = int(next(it))
            val = None if op == "count" else next(it)
            out.add_state(ALL, i, nv, None if nv == 0 else val)
        return out
    rows_g = np.asarray(next(it))
    states = []
    for c, op in cplan.aggs:
        nv = np.asarray(next(it))
        val = None if op == "count" else np.asarray(next(it))
        states.append((nv, val))
    keys = built.group_keys or []
    for slot in range(cplan.gcap + 1):
        rows = int(rows_g[slot])
        if rows == 0:
            continue
        key = None if slot >= len(keys) else keys[slot]
        out.add_rows(key, rows)
        for i, (nv, val) in enumerate(states):
            nvs = int(nv[slot])
            out.add_state(
                key, i, nvs,
                None if (val is None or nvs == 0) else val[slot],
            )
    return out


# ---------------------------------------------------------------------------
# Fallback evaluation over already-decoded DeviceColumns (multi-launch
# chunked groups — the fused tail needs the one-launch program)
# ---------------------------------------------------------------------------

def _columns_ctx(cols: dict, request: ComputeRequest, n: int):
    """(ctx, masks) over decoded ``DeviceColumn``s: index-form
    dictionary columns evaluate via their pools exactly like the fused
    path; gather-form values compare directly."""
    masks: List[object] = []
    ctx: Dict[str, tuple] = {}
    pools: Dict[str, object] = {}
    for name, dc in cols.items():
        if dc.def_levels is not None or dc.rep_levels is not None:
            raise UnsupportedFeatureError(
                "pushdown cannot run over repeated (nested) columns; "
                f"project {name!r} away"
            )
        idx = None
        if dc.dict_ref is not None:
            idx = dc.values.astype(jnp.int32)
            pools[name] = dc.dict_ref
        ctx[name] = (dc.values, dc.mask, dc.lengths, idx)
    return ctx, masks, pools


def _pool_values(dict_ref):
    """Host values of a DeviceColumn.dict_ref pool."""
    kind = dict_ref[0]
    if kind == "host":
        return np.asarray(dict_ref[2])
    rows = np.asarray(dict_ref[2])
    lens = np.asarray(dict_ref[3])
    return [bytes(rows[i, : int(lens[i])]) for i in range(len(lens))]


def _reject_lossy_double_col(name: str, dc, arr) -> None:
    """Same exactness rule as the fused path's ``_reject_lossy_double``:
    a DOUBLE column whose comparable representation is not float64
    (f32-converted values, or int64 bit patterns under 'bits') must
    reject, never silently compare/accumulate rounded numbers."""
    from ..format.parquet_thrift import Type

    if dc.descriptor.physical_type == Type.DOUBLE and \
            str(getattr(arr, "dtype", "")) != "float64":
        raise UnsupportedFeatureError(
            f"pushdown on DOUBLE column {name!r} needs exact device "
            "float64 — use float64_policy='float64'"
        )


def eval_on_columns(cols: dict, request: ComputeRequest, num_rows: int):
    """Evaluate a request over ALREADY-DECODED device columns — the
    multi-launch (over-cap chunked) groups' path.  Same results as the
    fused tail, computed by follow-up device ops instead of inside the
    decode executable."""
    n = int(num_rows)
    ctx, masks, pools = _columns_ctx(cols, request, n)

    def rewrite(t: tuple) -> tuple:
        kind = t[0]
        if kind in ("and", "or"):
            return (kind, rewrite(t[1]), rewrite(t[2]))
        if kind == "isnull":
            if t[1] not in ctx:
                raise ValueError(f"pushdown references column {t[1]!r}, "
                                 "which was not decoded")
            return t
        _, name, op, v = t
        if name not in ctx:
            raise ValueError(f"pushdown references column {name!r}, "
                             "which was not decoded")
        vals, mask, lens, idx = ctx[name]
        if idx is not None:
            dvals = _pool_values(pools[name])
            if isinstance(dvals, np.ndarray):
                _reject_lossy_double_col(name, cols[name], dvals)
            cap = len(dvals) if isinstance(dvals, list) else dvals.shape[0]
            dmask = np.zeros(max(cap, 1), bool)
            m = _cmp_host(dvals, op, v)
            dmask[: len(m)] = m
            masks.append(jnp.asarray(dmask))
            return ("dmask", name, op, len(masks) - 1)
        if lens is not None:
            if op not in ("==", "!="):
                raise UnsupportedFeatureError(
                    f"order comparison {op!r} on gather-form string "
                    f"column {name!r} in a multi-launch group — use "
                    "dict_form='index' or the host engine"
                )
            lit = (
                v.encode("utf-8", "surrogateescape")
                if isinstance(v, str) else bytes(v)
            )
            return ("str", name, op, lit)
        if str(vals.dtype) not in _NUM_VDTYPES:
            raise UnsupportedFeatureError(
                f"pushdown cannot evaluate column {name!r} "
                f"(dtype {vals.dtype})"
            )
        if isinstance(v, bytes):
            raise UnsupportedFeatureError(
                f"string literal compared against numeric column {name!r}"
            )
        _reject_lossy_double_col(name, cols[name], vals)
        return ("num", name, op, v)

    tree = rewrite(request.tree) if request.tree is not None else ("true",)
    sel = eval_selection(tree, ctx, masks, n)
    agg = request.aggregate
    if agg is not None:
        for c, op in agg.aggs:
            if op != "count" and c in cols:
                if ctx[c][3] is not None:
                    # index-form values ARE dictionary slots — summing
                    # them would be silently wrong
                    raise UnsupportedFeatureError(
                        f"aggregate {op!r} over index-form dictionary "
                        f"column {c!r} — use dict_form='gather'"
                    )
                _reject_lossy_double_col(c, cols[c], ctx[c][0])
        group = None
        gcap = 0
        group_keys = None
        if agg.group_by is not None:
            gname = agg.group_by
            if gname not in ctx or ctx[gname][3] is None:
                raise UnsupportedFeatureError(
                    f"group_by column {gname!r} is not index-form "
                    "dictionary-encoded in this (multi-launch) group"
                )
            dvals = _pool_values(pools[gname])
            group_keys = (
                [v.item() for v in dvals]
                if isinstance(dvals, np.ndarray) else list(dvals)
            )
            group = gname
            gcap = max(len(group_keys), 1)
        cplan = _CPlan(tree, "agg", 0, (), agg.aggs, group, gcap,
                       len(masks), n)
        built = BuiltCompute(request, cplan, [], group_keys)
        fetched = [np.asarray(a) for a in eval_aggregates(cplan, ctx, sel)]
        return PushdownResult(
            {}, n, int(fetched[0].sum() if group else fetched[0]),
            agg=partial_from_device(built, fetched),
        )
    count = int(jnp.sum(sel))
    request.observe(count)
    exprs = getattr(request, "exprs", ())
    ex_pairs = None
    if exprs:
        for _name, et in exprs:
            from ..query.expr import expr_columns

            for cname in sorted(expr_columns(et)):
                if cname not in ctx:
                    raise ValueError(
                        f"expression references column {cname!r}, "
                        "which was not decoded"
                    )
                vals, _mask, lens, idx = ctx[cname]
                if idx is not None:
                    raise UnsupportedFeatureError(
                        f"expression input {cname!r} is an index-form "
                        "dictionary column in this (multi-launch) "
                        "group — use dict_form='gather'"
                    )
                if lens is not None or \
                        str(vals.dtype) not in _NUM_VDTYPES:
                    raise UnsupportedFeatureError(
                        f"expression input {cname!r} is not numeric "
                        f"(dtype {getattr(vals, 'dtype', None)})"
                    )
                _reject_lossy_double_col(cname, cols[cname], vals)
        ex_pairs = eval_exprs(exprs, ctx, n)
    if request.mode == "mask":
        ex_dict = None
        if ex_pairs is not None:
            ex_dict = {
                name: pair for (name, _et), pair in zip(exprs, ex_pairs)
            }
        return PushdownResult(dict(cols), n, count, mask=sel,
                              exprs=ex_dict)
    sel_idx = compact_indices(sel, max(count, 1), n)
    out = {}
    for name, dc in cols.items():
        from .engine import DeviceColumn

        nd = DeviceColumn(
            dc.descriptor,
            take_rows(dc.values, sel_idx)[:count],
            None if dc.mask is None else take_rows(dc.mask, sel_idx)[:count],
            None if dc.lengths is None
            else take_rows(dc.lengths, sel_idx)[:count],
        )
        nd.dict_ref = dc.dict_ref
        out[name] = nd
    ex_dict = None
    if ex_pairs is not None:
        ex_dict = {
            name: (
                take_rows(vals, sel_idx)[:count],
                None if mask is None
                else take_rows(mask, sel_idx)[:count],
            )
            for (name, _et), (vals, mask) in zip(exprs, ex_pairs)
        }
    return PushdownResult(out, n, count, exprs=ex_dict)
