"""Pallas TPU kernel: fused RLE/bit-packed hybrid run expansion.

The jnp reference (``tpu/bitops.py:rle_expand``) costs one
``searchsorted`` (log R gathers per element) plus a 5-byte gather per
element for bit-packed runs — all through HBM between HLO ops.  This kernel
replaces the per-element gathers with run-local vectorized extraction:

* grid over output tiles; a host-built *span table* tells each tile which
  runs intersect it (``tile_lo``/``tile_hi``), so the kernel loop is
  O(runs-in-tile), not O(R);
* RLE runs broadcast their value into the masked tile range (VPU select);
* bit-packed runs exploit the format's byte-aligned packed streams
  (Parquet RLE spec: packed groups start on a byte boundary): the whole
  values buffer stays in HBM, the per-run window is DMA'd into VMEM,
  exploded to a bit matrix, dynamically shifted, regrouped to (TILE, bw)
  and contracted with power-of-two weights — an int matmul the MXU eats.

Replaces the reference's per-cell ValuesReader pull loop
(``ParquetReader.java:141-168``, ``ParquetReader.java:196-203``) — the
same seam SURVEY.md §2.4(2) maps to Pallas kernels.

Correctness contract: identical output to ``bitops.rle_expand`` for every
valid run table (property-tested in interpret mode on CPU).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Output tile: (SUB, LANE) int32 = 2048 values per grid step.
_SUB, _LANE = 16, 128
TILE = _SUB * _LANE


def _tile_window_bytes(bit_width: int) -> int:
    """VMEM window per bit-packed run segment: one tile's worth of packed
    bits plus slack for the byte-misaligned start and the trailing read."""
    return TILE * bit_width // 8 + 16


def _rle_expand_kernel(
    # scalar prefetch (SMEM)
    tile_lo_ref, tile_hi_ref, run_out_end_ref, run_kind_ref,
    run_value_ref, run_byte_ref,
    # tensor inputs
    data_hbm,           # uint8[B] in ANY/HBM: the raw values buffer
    # outputs
    out_ref,            # int32[SUB, LANE] tile in VMEM
    # scratch
    win_ref,            # uint8[1, W] VMEM window for packed bytes
    sem,                # DMA semaphore
    *, bit_width: int,
):
    t = pl.program_id(0)
    tile_start = t * TILE
    lo = tile_lo_ref[t]
    hi = tile_hi_ref[t]

    # Element index within this tile (flattened (SUB, LANE) order).
    flat = (
        jax.lax.broadcasted_iota(jnp.int32, (_SUB, _LANE), 0) * _LANE
        + jax.lax.broadcasted_iota(jnp.int32, (_SUB, _LANE), 1)
    )
    gidx = tile_start + flat  # global output index per element

    W = _tile_window_bytes(bit_width)
    bits_per_byte = 8
    # Weights for the (TILE, bw) x (bw,) contraction.
    weights = (
        jnp.int32(1) << jax.lax.broadcasted_iota(jnp.int32, (bit_width, 1), 0)
    )  # (bw, 1)

    def body(r, acc):
        r_end = run_out_end_ref[r]
        r_start = jnp.where(r == 0, 0, run_out_end_ref[jnp.maximum(r - 1, 0)])
        in_run = (gidx >= r_start) & (gidx < r_end)

        kind = run_kind_ref[r]
        rle_fill = jnp.where(in_run, run_value_ref[r], acc)

        # --- bit-packed branch -------------------------------------------
        # Within-run index of the tile's element 0 (may be negative when the
        # run starts mid-tile; the buffer carries FRONT_PAD leading bytes so
        # the DMA window can begin before the run base, and out-of-run
        # elements decode garbage that ``in_run`` masks away).
        w_base = tile_start - r_start
        bit0 = w_base * bit_width                 # signed, rel. to packed base
        byte_off = run_byte_ref[r] + (bit0 >> 3)  # arithmetic shift = floor
        shift = bit0 & 7                          # floor-mod residual (0..7)

        def packed_branch(acc_in):
            copy = pltpu.make_async_copy(
                data_hbm.at[pl.ds(byte_off, W)],
                win_ref.at[0, :],
                sem,
            )
            copy.start()
            copy.wait()
            # Explode window to bits: (W, 8) LSB-first -> flat (1, W*8).
            wb = win_ref[0, :].reshape(W, 1)
            bits = (
                (wb >> jax.lax.broadcasted_iota(jnp.uint8, (W, bits_per_byte), 1))
                & 1
            ).astype(jnp.int32).reshape(1, W * bits_per_byte)
            # Drop the residual shift, regroup to (TILE, bw).
            usable = bits[:, :].reshape(W * bits_per_byte)
            seg = jax.lax.dynamic_slice(usable, (shift,), (TILE * bit_width,))
            fields = seg.reshape(TILE, bit_width)
            vals_flat = jax.lax.dot_general(
                fields, weights,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            ).reshape(_SUB, _LANE)
            # vals_flat[i] is the value for within-tile element i only when
            # the element belongs to this run (its packed index = w0 + (its
            # global index - tile_start)); elements before the run's start in
            # this tile would need negative packed indices — they're masked.
            return jnp.where(in_run, vals_flat, acc_in)

        acc_out = jax.lax.cond(
            kind == 1, packed_branch, lambda a: rle_fill, acc
        )
        return acc_out

    result = jax.lax.fori_loop(lo, hi, body, jnp.zeros((_SUB, _LANE), jnp.int32))
    out_ref[:, :] = result


@functools.partial(
    jax.jit,
    static_argnames=("num_values", "bit_width", "interpret"),
)
def rle_expand_pallas(
    data_u8: jax.Array,
    run_out_end: jax.Array,
    run_kind: jax.Array,
    run_value: jax.Array,
    run_bitbase: jax.Array,
    tile_lo: jax.Array,
    tile_hi: jax.Array,
    num_values: int,
    bit_width: int,
    interpret: bool = False,
) -> jax.Array:
    """Pallas twin of ``bitops.rle_expand`` (+ host-built tile spans).

    ``run_bitbase`` is in bits (byte-aligned by the format); converted to
    bytes here.  Output is int32[num_values].
    """
    if bit_width == 0:
        return jnp.zeros(num_values, dtype=jnp.int32)
    n_tiles = pl.cdiv(num_values, TILE)
    padded = n_tiles * TILE
    W = _tile_window_bytes(bit_width)

    # FRONT_PAD: a run starting mid-tile makes the window begin up to
    # (TILE-1)*bw/8 bytes before the run base; pad the front so byte
    # offsets never underflow.  Tail: every DMA starts at byte_off ≤
    # run_byte + run_len*bw/8 ≤ len(buf) (parse guarantees packed data is
    # in-bounds) and reads W bytes, so W+16 beyond the buffer suffices.
    front = TILE * bit_width // 8 + 8
    data_u8 = jnp.pad(data_u8, (front, W + 16))

    run_byte = (run_bitbase // 8).astype(jnp.int32) + front

    kernel = functools.partial(_rle_expand_kernel, bit_width=bit_width)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(n_tiles,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (_SUB, _LANE), lambda t, *_: (t, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((1, W), jnp.uint8),
            pltpu.SemaphoreType.DMA,
        ],
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n_tiles * _SUB, _LANE), jnp.int32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(
        tile_lo.astype(jnp.int32),
        tile_hi.astype(jnp.int32),
        run_out_end.astype(jnp.int32),
        run_kind.astype(jnp.int32),
        run_value.astype(jnp.int32),
        run_byte,
        data_u8,
    )
    return out.reshape(-1)[:num_values]


def tile_spans(run_out_end: np.ndarray, num_values: int) -> tuple:
    """Host-side: for each output tile, the [lo, hi) run-index span that
    intersects it.  O(T log R) searchsorted — tiny."""
    n_tiles = -(-num_values // TILE)
    starts = np.arange(n_tiles, dtype=np.int64) * TILE
    ends = np.minimum(starts + TILE, num_values)
    # run r covers output [out_end[r-1], out_end[r])
    lo = np.searchsorted(run_out_end, starts, side="right")
    hi = np.searchsorted(run_out_end, ends - 1, side="right") + 1
    hi = np.minimum(hi, len(run_out_end))
    return lo.astype(np.int32), hi.astype(np.int32)
