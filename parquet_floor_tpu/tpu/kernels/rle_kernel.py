"""Pallas TPU kernel: fused RLE/bit-packed hybrid run expansion.

The jnp reference (``tpu/bitops.py:rle_expand``) costs one
``searchsorted`` (log R gathers per element) plus a 5-byte gather per
element for bit-packed runs — all through HBM between HLO ops.  This kernel
replaces the per-element gathers with run-local vectorized extraction:

* grid over output tiles; a host-built *span table* tells each tile which
  runs intersect it (``tile_lo``/``tile_hi``), so the kernel loop is
  O(runs-in-tile), not O(R);
* RLE runs broadcast their value into the masked tile range (VPU select);
* bit-packed runs exploit the format's byte-aligned packed streams
  (Parquet RLE spec: packed groups start on a byte boundary): the whole
  values buffer stays in HBM, the per-run window is DMA'd into VMEM,
  exploded to a bit matrix, dynamically shifted, regrouped to (TILE, bw)
  and contracted with power-of-two weights — an int matmul the MXU eats.

Replaces the reference's per-cell ValuesReader pull loop
(``ParquetReader.java:141-168``, ``ParquetReader.java:196-203``) — the
same seam SURVEY.md §2.4(2) maps to Pallas kernels.

Correctness contract: identical output to ``bitops.rle_expand`` for every
valid run table (property-tested in interpret mode on CPU).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Output tile: (SUB, LANE) int32 = 2048 values per grid step.
_SUB, _LANE = 16, 128
TILE = _SUB * _LANE
# Widest bit width the lane-gather kernel compiles for.  Mosaic's native
# lane gather reads one 128-lane chunk at a time; wider fields are served
# by gathering from several static 128-byte chunks of the rolled window
# and selecting by chunk index (see ``_lane_chunks``).  9 ≤ bw ≤ 24 needs
# ≤ 3 chunks; bw = 32 is byte-aligned and needs 4; 26–31 gather 5 bytes
# and combine across the 32-bit word (logical shift + byte-4 splice in
# ``_lane_expand_tile``), making ``lane_compiled`` total over 1..32.
# The engine's Pallas gating and the kernel dispatch below must agree
# via ``lane_compiled``.
LANE_KERNEL_MAX_BW = 32
# Scalar-prefetch (SMEM, 1 MiB/program) budget the engine's gating must
# respect: run plans are 5·PL_MAX_RUNS int32 and tile spans 2·count/TILE.
PL_MAX_RUNS = 2048
PL_MAX_VALUES = 1 << 24
# Run-heavy streams (> PL_MAX_RUNS) switch to the HBM-plan formulation:
# scalar prefetch carries only the tile spans; each tile DMAs its own run
# window from the HBM-resident plan into an SMEM scratch of PL_RUN_WIN
# rows — sized for the TILE+1 runs a tile can intersect plus the
# 256-element window alignment — so the total run count is bounded only
# by the (generous) PL_MAX_RUNS_HBM plan-size cap.
PL_RUN_WIN = 2560
PL_MAX_RUNS_HBM = 1 << 22


def lane_compiled(bit_width: int) -> bool:
    """True when the Mosaic-compilable lane-gather kernel covers this
    width (the engine's compiled-path gate).  Total over 1..32 since
    round 3 (26–31 via the 5-byte combine)."""
    return 1 <= bit_width <= LANE_KERNEL_MAX_BW


def _lane_chunks(bit_width: int) -> int:
    """128-byte gather chunks a row's packed span needs: the farthest byte
    an element touches is ((7 + 127·bw) >> 3) + nbytes − 1 (sub-byte
    residual only when bw ∤ 8)."""
    if bit_width % 8 == 0:
        far = (127 * bit_width) // 8 + bit_width // 8 - 1
    else:
        far = (7 + 127 * bit_width) >> 3
        far += (bit_width + 14) // 8 - 1
    return far // 128 + 1


def _lane_win(bit_width: int) -> int:
    """Lane-kernel DMA window: 1024-aligned start residual + the last
    row's packed offset + its gather chunks, rounded to a 1024-multiple
    (DMA sizes must be 1024-multiples)."""
    need = 1023 + (_SUB - 1) * _LANE * bit_width // 8 + 128 * _lane_chunks(bit_width)
    return -(-need // 1024) * 1024


def _tile_window_bytes(bit_width: int) -> int:
    """VMEM window per bit-packed run segment: one tile's worth of packed
    bits plus slack for the byte-misaligned start and the trailing read."""
    return TILE * bit_width // 8 + 16


def _rle_expand_kernel(
    # scalar prefetch (SMEM)
    tile_lo_ref, tile_hi_ref, run_out_end_ref, run_kind_ref,
    run_value_ref, run_byte_ref,
    # tensor inputs
    data_hbm,           # uint8[B] in ANY/HBM: the raw values buffer
    # outputs
    out_ref,            # int32[SUB, LANE] tile in VMEM
    # scratch
    win_ref,            # uint8[1, W] VMEM window for packed bytes
    sem,                # DMA semaphore
    *, bit_width: int,
):
    t = pl.program_id(0)
    tile_start = t * TILE
    lo = tile_lo_ref[t]
    hi = tile_hi_ref[t]

    # Element index within this tile (flattened (SUB, LANE) order).
    flat = (
        jax.lax.broadcasted_iota(jnp.int32, (_SUB, _LANE), 0) * _LANE
        + jax.lax.broadcasted_iota(jnp.int32, (_SUB, _LANE), 1)
    )
    gidx = tile_start + flat  # global output index per element

    W = _tile_window_bytes(bit_width)
    bits_per_byte = 8
    # Weights for the (TILE, bw) x (bw,) contraction.
    weights = (
        jnp.int32(1) << jax.lax.broadcasted_iota(jnp.int32, (bit_width, 1), 0)
    )  # (bw, 1)

    def body(r, acc):
        # literals must be explicit int32: under jax_enable_x64 a weak
        # Python int traces as an int64 constant, and Mosaic's lowering of
        # the resulting int64→int32 convert recurses forever
        zero = jnp.int32(0)
        r_end = run_out_end_ref[r]
        r_start = jnp.where(
            r == zero, zero, run_out_end_ref[jnp.maximum(r - 1, zero)]
        )
        in_run = (gidx >= r_start) & (gidx < r_end)

        kind = run_kind_ref[r]
        rle_fill = jnp.where(in_run, run_value_ref[r], acc)

        # --- bit-packed branch -------------------------------------------
        # Within-run index of the tile's element 0 (may be negative when the
        # run starts mid-tile; the buffer carries FRONT_PAD leading bytes so
        # the DMA window can begin before the run base, and out-of-run
        # elements decode garbage that ``in_run`` masks away).
        w_base = tile_start - r_start
        bit0 = w_base * bit_width                 # signed, rel. to packed base
        # arithmetic shift = floor; force int32 — x64 mode otherwise
        # promotes through weak literals to i64, which DMA indices reject
        byte_off = (run_byte_ref[r] + (bit0 >> 3)).astype(jnp.int32)
        shift = (bit0 & 7).astype(jnp.int32)      # floor-mod residual (0..7)

        def packed_branch(acc_in):
            copy = pltpu.make_async_copy(
                data_hbm.at[pl.ds(byte_off, W)],
                win_ref.at[0, :],
                sem,
            )
            copy.start()
            copy.wait()
            # Explode window to bits, int32 and 2-D throughout (Mosaic
            # handles 32-bit vector ops; uint8 reshapes crash its compiler):
            # widen (1, W) bytes, broadcast to (8, W), shift-and-mask per
            # bit plane, transpose to byte-major (W, 8), flatten.
            w32 = win_ref[0:1, :].astype(jnp.int32)        # (1, W)
            kq = jax.lax.broadcasted_iota(jnp.int32, (bits_per_byte, W), 0)
            planes = (jnp.broadcast_to(w32, (bits_per_byte, W)) >> kq) & 1
            bits = planes.T.reshape(1, W * bits_per_byte)  # byte-major order
            # Drop the residual shift (0..7) by rotating left, then regroup
            # to (TILE, bw).  (dynamic_slice with a traced start doesn't
            # lower in Mosaic; roll does.)
            rolled = pltpu.roll(bits, -shift, axis=1)
            seg = jax.lax.slice(rolled, (0, 0), (1, TILE * bit_width))
            fields = seg.reshape(TILE, bit_width)
            vals_flat = jax.lax.dot_general(
                fields, weights,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            ).reshape(_SUB, _LANE)
            # vals_flat[i] is the value for within-tile element i only when
            # the element belongs to this run (its packed index = w0 + (its
            # global index - tile_start)); elements before the run's start in
            # this tile would need negative packed indices — they're masked.
            return jnp.where(in_run, vals_flat, acc_in)

        acc_out = jax.lax.cond(
            kind == 1, packed_branch, lambda a: rle_fill, acc
        )
        return acc_out

    result = jax.lax.fori_loop(lo, hi, body, jnp.zeros((_SUB, _LANE), jnp.int32))
    out_ref[:, :] = result


@functools.partial(
    jax.jit,
    static_argnames=("num_values", "bit_width", "interpret"),
)
def rle_expand_pallas(
    data_u8: jax.Array,
    run_out_end: jax.Array,
    run_kind: jax.Array,
    run_value: jax.Array,
    run_bytebase: jax.Array,
    tile_lo: jax.Array,
    tile_hi: jax.Array,
    num_values: int,
    bit_width: int,
    interpret: bool = False,
) -> jax.Array:
    """Pallas twin of ``bitops.rle_expand`` (+ host-built tile spans).

    Standalone convenience wrapper over :func:`rle_expand_pallas_inline`:
    pads the buffer with the lead/tail slack the inline contract requires
    and rebases the byte offsets.  Output is int32[n].
    """
    if bit_width == 0:
        return jnp.zeros(num_values, dtype=jnp.int32)
    front = ARENA_LEAD
    data_u8 = jnp.pad(data_u8, (front, ARENA_TAIL))
    run_bytebase = run_bytebase + front
    return rle_expand_pallas_inline(
        data_u8, run_out_end, run_kind, run_value, run_bytebase,
        tile_lo, tile_hi, num_values, bit_width, interpret=interpret,
    )


# Slack the arena must carry for the inline (no-copy) variant: a run
# starting mid-tile makes the DMA window begin up to (TILE−1)·bw/8 bytes
# before the run's packed base, and the lane kernel's 1024-alignment can
# pull it back up to 1023 more (lead); a window that starts at the stream
# end still reads its full span past it (tail).  Sized for bit width 32.
ARENA_LEAD = TILE * 32 // 8 + 1024 + 16   # 9232
ARENA_TAIL = max(_tile_window_bytes(32) + 32, _lane_win(32) + 32)  # 9248


def _lane_expand_tile(
    lo, hi, t, get_oe, get_kind, get_value, get_byte,
    data_hbm, out_ref, win_ref, sem, *, bit_width: int,
):
    """Shared tile body of the Mosaic-compilable lane-gather formulation.

    One 1024-aligned ``_lane_win(bw)``-byte DMA per packed run loads the
    whole tile's span into a 1-D scratch; 16 per-row uniform rolls align
    each row's window start to lane 0 (row offsets are exactly linear — a
    128-value row advances 16·bw whole bytes); each element's field then
    comes from *lane-wise* byte gathers (``take_along_axis`` along lanes —
    one of the two gather forms Mosaic lowers natively) plus shift/mask.
    A row at bw > 8 spans more than 128 bytes, so each of the field's
    ceil bytes is gathered from every static 128-byte chunk of the rolled
    window and selected by chunk index — all chunk/byte loops unroll at
    trace time (bit_width is static).  No irregular reshapes, no
    byte-granular dynamic slices, no strided rolls: every vector op is
    (16, 128)/(16, WIN) int32.

    Run parameters arrive through getter callables (``get_oe(r)`` etc.) so
    the same body serves both plan placements: scalar-prefetch SMEM refs
    (``_rle_expand_kernel_lane``) and the per-tile SMEM window DMA'd from
    an HBM-resident plan (``_rle_expand_kernel_lane_hbm`` — run counts far
    past the scalar-prefetch budget).
    """
    tile_start = t * TILE

    row_i = jax.lax.broadcasted_iota(jnp.int32, (_SUB, _LANE), 0)
    lane_i = jax.lax.broadcasted_iota(jnp.int32, (_SUB, _LANE), 1)
    gidx = tile_start + row_i * _LANE + lane_i

    win = _lane_win(bit_width)
    n_chunks = _lane_chunks(bit_width)
    aligned_fields = bit_width % 8 == 0
    # bytes each field's bits can touch (sub-byte residual only when the
    # field is not byte-aligned)
    nbytes = bit_width // 8 if aligned_fields else (bit_width + 14) // 8

    def body(r, acc):
        zero = jnp.int32(0)
        r_end = get_oe(r)
        r_start = jnp.where(r == zero, zero, get_oe(jnp.maximum(r - 1, zero)))
        in_run = (gidx >= r_start) & (gidx < r_end)
        kind = get_kind(r)
        rle_fill = jnp.where(in_run, get_value(r), acc)

        # run-relative bit position of the tile's element 0 (may be < 0;
        # ARENA_LEAD slack keeps every window in bounds)
        bit0 = (tile_start - r_start) * bit_width

        def packed_branch(acc_in):
            # ONE aligned DMA covers the whole tile's packed span: HBM
            # uint8 slice offsets must be provably 1024-divisible and
            # sizes 1024-multiples (``_lane_win`` sizes the window so the
            # residual + last row's span + its gather chunks all fit).
            byte_off0 = (get_byte(r) + (bit0 >> 3)).astype(jnp.int32)
            aligned = pl.multiple_of(byte_off0 & ~jnp.int32(1023), 1024)
            copy = pltpu.make_async_copy(
                data_hbm.at[pl.ds(aligned, win)],
                win_ref,
                sem,
            )
            copy.start()
            copy.wait()
            w1 = win_ref[:].reshape(1, win).astype(jnp.int32)
            # Row r's window begins δ_r = δ_0 + r·16·bw bytes into the
            # buffer (exactly linear: 128·bw bits is a whole byte count).
            # One uniform roll per row left-rotates by δ_r; amounts are
            # kept positive in (0, WIN] because compiled Mosaic treats
            # dynamic shifts as unsigned mod 2³² (negative breaks), and
            # its *strided* roll cannot cross vreg boundaries at all.
            delta0 = byte_off0 - aligned
            row_step = _LANE * bit_width // 8              # 16·bw
            rolled = jnp.concatenate(
                [
                    pltpu.roll(w1, win - (delta0 + rr * row_step), axis=1)
                    for rr in range(_SUB)
                ],
                axis=0,
            )
            chunks = [
                jax.lax.slice(rolled, (0, _LANE * c), (_SUB, _LANE * (c + 1)))
                for c in range(n_chunks)
            ]
            # local bit position: row windows start byte-exact, so only
            # bit0's sub-byte residual (same every row) and the lane remain
            lam = (bit0 & 7) + lane_i * bit_width          # ≤ 7 + 127·bw
            b0 = lam >> 3
            word = jnp.zeros((_SUB, _LANE), jnp.int32)
            byte4 = jnp.zeros((_SUB, _LANE), jnp.int32)
            for j in range(nbytes):
                p = b0 + jnp.int32(j)
                if n_chunks == 1:
                    # bw = 8's last element has b0 = 127 and nbytes = 1;
                    # bw ≤ 7's p ≤ 113+1 — both in bounds unclamped
                    bj = jnp.take_along_axis(
                        chunks[0], p, axis=1, mode="promise_in_bounds"
                    )
                else:
                    bj = jnp.zeros((_SUB, _LANE), jnp.int32)
                    for c in range(n_chunks):
                        q = jnp.clip(p - _LANE * c, 0, _LANE - 1)
                        g = jnp.take_along_axis(
                            chunks[c], q, axis=1, mode="promise_in_bounds"
                        )
                        bj = jnp.where((p >> 7) == c, g, bj)
                if j < 4:
                    word = word | (bj << (8 * j))
                else:
                    # 5th byte (bw 26–31, misaligned): kept separate — a
                    # << 32 would overflow the int32 accumulator
                    byte4 = bj
            if bit_width == 32:
                vals = word   # the int32 bit pattern IS the value
            elif aligned_fields:
                vals = word & ((1 << bit_width) - 1)       # residual is 0
            elif bit_width <= 25:
                # arithmetic >> is safe: sign-filled bits live at positions
                # ≥ 32−sh ≥ 25, at or above the ≤ 25-bit mask's top
                vals = (word >> (lam & 7)) & ((1 << bit_width) - 1)
            else:
                # bw 26–31: 5-byte combine across the 32-bit word — the
                # low 32−sh bits come from the word (LOGICAL shift: sign
                # fill would pollute positions inside the mask), the rest
                # from byte 4 shifted up.  sh == 0 needs no byte 4 (field
                # fits the word); mask the shift amount below 32 and
                # select, so no shift op sees an amount ≥ 32.
                sh = lam & 7
                lo_part = jax.lax.shift_right_logical(word, sh)
                hi_part = jnp.where(
                    sh == 0,
                    jnp.int32(0),
                    byte4 << ((jnp.int32(32) - sh) & jnp.int32(31)),
                )
                vals = (lo_part | hi_part) & ((1 << bit_width) - 1)
            return jnp.where(in_run, vals, acc_in)

        return jax.lax.cond(kind == 1, packed_branch, lambda a: rle_fill, acc)

    result = jax.lax.fori_loop(lo, hi, body, jnp.zeros((_SUB, _LANE), jnp.int32))
    out_ref[:, :] = result


def _rle_expand_kernel_lane(
    # scalar prefetch (SMEM)
    tile_lo_ref, tile_hi_ref, run_out_end_ref, run_kind_ref,
    run_value_ref, run_byte_ref,
    # tensor inputs
    data_hbm,           # uint8[B] in ANY/HBM
    # outputs
    out_ref,            # int32[SUB, LANE]
    # scratch
    win_ref,            # uint8[_lane_win(bw)] one aligned tile-span window
    sem,                # DMA semaphore
    *, bit_width: int,
):
    """Lane-gather kernel, plan in scalar prefetch (runs ≤ PL_MAX_RUNS)."""
    t = pl.program_id(0)
    _lane_expand_tile(
        tile_lo_ref[t], tile_hi_ref[t], t,
        lambda r: run_out_end_ref[r],
        lambda r: run_kind_ref[r],
        lambda r: run_value_ref[r],
        lambda r: run_byte_ref[r],
        data_hbm, out_ref, win_ref, sem, bit_width=bit_width,
    )


def _rle_expand_kernel_lane_hbm(
    # scalar prefetch (SMEM)
    tile_lo_ref, tile_hi_ref,
    # tensor inputs
    plan_hbm,           # int32[8, R_pad] in ANY/HBM: the 5-row plan padded
                        # to 8 rows (Mosaic tiling: dim-0 slices must align
                        # to the (8, 128) int32 tile)
    data_hbm,           # uint8[B] in ANY/HBM
    # outputs
    out_ref,            # int32[SUB, LANE]
    # scratch
    run_win,            # SMEM (8, PL_RUN_WIN) int32: this tile's run window
    win_ref,            # uint8[_lane_win(bw)] VMEM data window
    sem_run, sem,       # DMA semaphores (plan window / data window)
    *, bit_width: int,
):
    """Lane-gather kernel for run-heavy streams: the 5-row run plan stays
    in HBM and each tile DMAs only its own [lo, hi) run window into SMEM.

    Scalar prefetch then carries just the 2·n_tiles tile spans, so the
    SMEM budget no longer bounds the stream's total run count — a tile
    intersects at most TILE+1 runs (every real run owns ≥ 1 output
    element; host gating verifies the span bound including alignment
    slack), and ``PL_RUN_WIN`` covers that plus the 256-element window
    alignment the DMA needs.
    """
    t = pl.program_id(0)
    lo = tile_lo_ref[t]
    hi = tile_hi_ref[t]
    # window start: cover lo-1 (the body reads the previous run's out_end)
    # and round down to a 256-element (1024-byte) DMA-aligned offset
    win_base = pl.multiple_of(
        jnp.maximum(lo - 1, 0) & ~jnp.int32(255), 256
    )
    copy_runs = pltpu.make_async_copy(
        plan_hbm.at[:, pl.ds(win_base, PL_RUN_WIN)],
        run_win,
        sem_run,
    )
    copy_runs.start()
    copy_runs.wait()
    _lane_expand_tile(
        lo, hi, t,
        lambda r: run_win[0, r - win_base],
        lambda r: run_win[1, r - win_base],
        lambda r: run_win[2, r - win_base],
        lambda r: run_win[3, r - win_base],
        data_hbm, out_ref, win_ref, sem, bit_width=bit_width,
    )


def rle_expand_pallas_inline(
    arena_u8: jax.Array,
    run_out_end: jax.Array,
    run_kind: jax.Array,
    run_value: jax.Array,
    run_bytebase: jax.Array,
    tile_lo: jax.Array,
    tile_hi: jax.Array,
    num_values: int,
    bit_width: int,
    interpret: bool = False,
) -> jax.Array:
    """``rle_expand_pallas`` without the jit wrapper or defensive copy —
    composable inside a larger jitted program (the fused row-group decode).

    Contract: ``arena_u8`` already carries ≥ ``ARENA_LEAD`` bytes of slack
    before any packed stream and ≥ ``ARENA_TAIL`` after (the engine's
    arena builder reserves both), so DMA windows never leave the buffer.
    ``run_bytebase`` holds absolute *byte* offsets into ``arena_u8``
    (packed runs start byte-aligned per the RLE spec; int32 byte offsets
    reach 2 GiB arenas).
    """
    if bit_width == 0:
        return jnp.zeros(num_values, dtype=jnp.int32)
    n_tiles = pl.cdiv(num_values, TILE)
    run_byte = run_bytebase.astype(jnp.int32)
    if lane_compiled(bit_width):
        # lane-gather formulation: the only one Mosaic compiles today
        kernel = functools.partial(_rle_expand_kernel_lane, bit_width=bit_width)
        scratch = pltpu.VMEM((_lane_win(bit_width),), jnp.uint8)
    else:
        kernel = functools.partial(_rle_expand_kernel, bit_width=bit_width)
        scratch = pltpu.VMEM((1, _tile_window_bytes(bit_width)), jnp.uint8)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(n_tiles,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (_SUB, _LANE), lambda t, *_: (t, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            scratch,
            pltpu.SemaphoreType.DMA,
        ],
    )
    # Trace the kernel with x64 off: under jax_enable_x64 Mosaic emits
    # 64-bit memref indices (tpu.memref_slice rejects i64) and weak-literal
    # converts that recurse in lowering.  All operands are ≤32-bit anyway.
    with jax.enable_x64(False):
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n_tiles * _SUB, _LANE), jnp.int32),
            grid_spec=grid_spec,
            interpret=interpret,
        )(
            tile_lo.astype(jnp.int32),
            tile_hi.astype(jnp.int32),
            run_out_end.astype(jnp.int32),
            run_kind.astype(jnp.int32),
            run_value.astype(jnp.int32),
            run_byte,
            arena_u8,
        )
    return out.reshape(-1)[:num_values]


def rle_expand_pallas_inline_hbm(
    arena_u8: jax.Array,
    plan_flat: jax.Array,
    n_runs: int,
    tile_lo: jax.Array,
    tile_hi: jax.Array,
    num_values: int,
    bit_width: int,
    interpret: bool = False,
) -> jax.Array:
    """``rle_expand_pallas_inline`` for run-heavy streams: the 5-row plan
    (``plan_flat`` = the slab's flat 5·n_runs int32 block) stays an HBM
    tensor input and each tile DMAs its run window into SMEM, so run
    counts are not bounded by the scalar-prefetch budget (the round-2
    gate this replaces: VERDICT.md weak #1 — lineitem's ~125k-run
    dictionary-index streams stayed on the jnp fallback).

    Host gating must ensure ``lane_compiled(bit_width)``, ``n_runs ≤
    PL_MAX_RUNS_HBM``, and every tile's aligned run window fits
    ``PL_RUN_WIN`` (see ``TpuRowGroupReader._pallas_plan``).
    """
    if bit_width == 0:
        return jnp.zeros(num_values, dtype=jnp.int32)
    n_tiles = pl.cdiv(num_values, TILE)
    # re-pad rows so every aligned window [win_base, win_base+PL_RUN_WIN)
    # stays inside the row stride (win_base ≤ n_runs rounded up to 256),
    # and pad 5 rows → 8 (Mosaic's (8, 128) int32 tiling: DMA slices along
    # dim 0 must cover whole tiles)
    r_pad = -(-(n_runs + 1) // 256) * 256 + PL_RUN_WIN
    plan2d = jnp.pad(
        plan_flat.reshape(5, n_runs), ((0, 3), (0, r_pad - n_runs))
    )
    kernel = functools.partial(_rle_expand_kernel_lane_hbm, bit_width=bit_width)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),   # plan
            pl.BlockSpec(memory_space=pl.ANY),   # data
        ],
        out_specs=pl.BlockSpec(
            (_SUB, _LANE), lambda t, *_: (t, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.SMEM((8, PL_RUN_WIN), jnp.int32),
            pltpu.VMEM((_lane_win(bit_width),), jnp.uint8),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    # x64 off while tracing: see rle_expand_pallas_inline
    with jax.enable_x64(False):
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n_tiles * _SUB, _LANE), jnp.int32),
            grid_spec=grid_spec,
            interpret=interpret,
        )(
            tile_lo.astype(jnp.int32),
            tile_hi.astype(jnp.int32),
            plan2d,
            arena_u8,
        )
    return out.reshape(-1)[:num_values]


@functools.partial(
    jax.jit,
    static_argnames=("n_runs", "num_values", "bit_width", "interpret"),
)
def rle_expand_pallas_hbm(
    data_u8: jax.Array,
    plan_flat: jax.Array,
    n_runs: int,
    tile_lo: jax.Array,
    tile_hi: jax.Array,
    num_values: int,
    bit_width: int,
    interpret: bool = False,
) -> jax.Array:
    """Standalone wrapper over :func:`rle_expand_pallas_inline_hbm`: pads
    the buffer with the lead/tail slack and rebases the plan's byte-offset
    row (row 3).  ``plan_flat`` is the flat 5·n_runs int32 plan."""
    if bit_width == 0:
        return jnp.zeros(num_values, dtype=jnp.int32)
    front = ARENA_LEAD
    data_u8 = jnp.pad(data_u8, (front, ARENA_TAIL))
    plan2d = plan_flat.reshape(5, n_runs)
    plan_flat = plan2d.at[3].add(front).reshape(-1)
    return rle_expand_pallas_inline_hbm(
        data_u8, plan_flat, n_runs, tile_lo, tile_hi, num_values,
        bit_width, interpret=interpret,
    )


def max_aligned_span(tile_lo: np.ndarray, tile_hi: np.ndarray) -> int:
    """Largest aligned run window any tile needs (host gate for the HBM
    formulation): hi − align256(max(lo−1, 0))."""
    if len(tile_lo) == 0:
        return 0
    base = np.maximum(tile_lo.astype(np.int64) - 1, 0) & ~np.int64(255)
    return int(np.max(tile_hi.astype(np.int64) - base))


def tile_spans_padded(out_end_padded: np.ndarray, num_values: int) -> tuple:
    """Host-side tile spans over a *padded* plan (pad runs own no output:
    out_end == total).  Tiles past the real total get empty spans."""
    n_tiles = -(-num_values // TILE)
    starts = np.arange(n_tiles, dtype=np.int64) * TILE
    ends = np.minimum(starts + TILE, num_values)
    lo = np.searchsorted(out_end_padded, starts, side="right")
    hi = np.minimum(
        np.searchsorted(out_end_padded, ends - 1, side="right") + 1,
        len(out_end_padded),
    )
    hi = np.maximum(hi, lo)  # empty span for all-pad tiles
    return lo.astype(np.int32), hi.astype(np.int32)


def tile_spans(run_out_end: np.ndarray, num_values: int) -> tuple:
    """Host-side: for each output tile, the [lo, hi) run-index span that
    intersects it.  O(T log R) searchsorted — tiny."""
    return tile_spans_padded(run_out_end, num_values)
