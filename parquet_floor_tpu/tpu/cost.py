"""Cost-model routing for ``engine="auto"`` — pick the WINNING engine per
file, not per platform.

The reference exposes one API whose engine is invisible to the caller
(``ParquetReader.java:47-61``); the TPU build's single front door earns
that only if "auto" never routes a file through the losing engine.  Both
engines share the host read+decompress stage, so the differential is:

  host engine:   post-decompress host decode of every chunk
  device engine: ship the arena over the link + fused device decode
                 (+ for the row API: fetch decoded cells back to host)

Those costs are predictable from the footer alone (bytes, codecs,
encodings, optionality) plus a one-time cached link-bandwidth probe:

  * "view"-class chunks (PLAIN, fixed-width, required, flat) host-decode
    at memcpy speed — the device path can only lose the ship time
    (BASELINE.md config #1: 0.73x, the one sub-1x row).
  * "levels"-class chunks (PLAIN fixed-width, optional) pay native level
    decode + scatter on host.
  * "value"-class chunks (dictionary / delta / strings / boolean) pay
    per-value host work — the measured ~0.03-0.05 GB/s that the fused
    device decode beats by 15-50x (BASELINE.md configs #2-5).

Rates are differential calibration constants taken from the measured
round-3 stage tables (docs/DESIGN_DECOMPRESSION.md, BASELINE.md); they
only need to rank the two engines, not predict absolute walls.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from ..format.parquet_thrift import Encoding, Type
from ..utils import trace

# Differential host post-decompress decode rates, GB/s of decoded bytes.
HOST_VIEW_GBPS = 4.0     # PLAIN fixed-width required: frombuffer view/copy
HOST_LEVELS_GBPS = 0.4   # PLAIN fixed-width optional: level decode + scatter
HOST_VALUE_GBPS = 0.05   # dict/delta/strings/bool: per-value host decode

# Device-side differential rates/overheads.
DEV_DECODE_GBPS = 8.0    # fused decode, HBM-bandwidth-class
GROUP_OVERHEAD_S = 8e-4  # plan build + dispatch per row group

# Row-API cell materialization (the host cursor boxes each cell through
# per-cell numpy→Python dispatch; the device path converts vectorized —
# tolist once per column + pool-once-per-distinct for dictionaries).
# Calibrated from BASELINE.md's measured 76k vs 187k rows/s on 16-column
# lineitem (1.2M vs ~3M cells/s plus the fetch the device side pays).
HOST_CELL_S = 0.4e-6
DEV_CELL_S = 0.1e-6

_CLASS_GBPS = {
    "view": HOST_VIEW_GBPS,
    "levels": HOST_LEVELS_GBPS,
    "value": HOST_VALUE_GBPS,
}

_LEVEL_ENCODINGS = {Encoding.RLE, Encoding.BIT_PACKED}
_FIXED_TYPES = {
    Type.INT32, Type.INT64, Type.FLOAT, Type.DOUBLE,
    Type.FIXED_LEN_BYTE_ARRAY, Type.INT96,
}
_DICT_ENCODINGS = {Encoding.RLE_DICTIONARY, Encoding.PLAIN_DICTIONARY}

_lock = threading.Lock()
_h2d_gbps: Optional[float] = None
_d2h_model: Optional[tuple] = None  # (fixed_s, gbps)


def arena_cap() -> int:
    """The per-launch arena byte budget (PFTPU_ARENA_CAP, default
    64 MiB, ceilinged below the int32 plan limit).  Single source of
    truth: ``TpuRowGroupReader`` sizes its launches with this, and
    ``estimate`` uses it to predict which fields must row-split — and
    therefore host-fall-back when the file has nothing to split on."""
    import os

    return min(
        int(os.environ.get("PFTPU_ARENA_CAP", str(1 << 26))),
        (1 << 31) - (1 << 24),
    )


def _probe_h2d_gbps() -> float:
    """One-time host→device bandwidth probe (8 MiB device_put, best of
    2 after a warm put), cached for the process.  ~20 ms on the
    tunnelled link; the number any shipped-bytes plan is bounded by."""
    global _h2d_gbps
    with _lock:
        if _h2d_gbps is not None:
            return _h2d_gbps
    import jax
    import numpy as np

    buf = np.zeros(8 << 20, dtype=np.uint8)
    jax.block_until_ready(jax.device_put(buf))  # warm
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        jax.block_until_ready(jax.device_put(buf))
        best = min(best, time.perf_counter() - t0)
    with _lock:
        _h2d_gbps = max(buf.nbytes / best / 1e9, 1e-3)
        return _h2d_gbps


def _probe_d2h_model() -> tuple:
    """One-time device→host cost model ``(fixed_s, gbps)`` from two
    transfer sizes (64 KiB and 1 MiB).  Tunnelled links have a large
    fixed cost (~35 ms) and a slow return path (~11 MB/s — see
    BASELINE.md link characterization); locally-attached devices are
    symmetric.  Probed lazily: ONLY the rows purpose reaches here, and
    only when the pre-fetch estimate already favors the device.  That
    matters because the first D2H can shift a tunnelled link into its
    degraded mode (BASELINE.md) — acceptable here since the row path
    fetches continuously anyway (that mode IS its steady state), while
    the batch purpose never probes D2H and so never triggers it."""
    global _d2h_model
    with _lock:
        if _d2h_model is not None:
            return _d2h_model
    import jax
    import jax.numpy as jnp
    import numpy as np

    times = []
    sizes = [64 << 10, 1 << 20]
    dev_big = jax.device_put(np.zeros(sizes[-1], dtype=np.uint8))
    jax.block_until_ready(dev_big)
    np.asarray(dev_big[: 1 << 10])  # warm the fetch path
    for s in sizes:
        t0 = time.perf_counter()
        np.asarray(jnp.asarray(dev_big[:s]))
        times.append(time.perf_counter() - t0)
    dt = times[1] - times[0]
    gbps = (sizes[1] - sizes[0]) / max(dt, 1e-9) / 1e9
    fixed = max(times[0] - sizes[0] / (gbps * 1e9), 0.0)
    with _lock:
        _d2h_model = (fixed, max(min(gbps, 1e3), 1e-4))
        return _d2h_model


@dataclass
class EngineChoice:
    """The routing decision plus the estimate that produced it."""

    engine: str
    host_s: float = 0.0
    tpu_s: float = 0.0
    reason: str = ""
    bytes_by_class: Dict[str, int] = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "engine": self.engine,
            "est_host_s": round(self.host_s, 6),
            "est_tpu_s": round(self.tpu_s, 6),
            "reason": self.reason,
            **{f"{k}_bytes": v for k, v in self.bytes_by_class.items()},
        }


_FIXED_WIDTHS = {
    Type.INT32: 4, Type.INT64: 8, Type.FLOAT: 4, Type.DOUBLE: 8,
    Type.INT96: 12, Type.BOOLEAN: 1,
}


def _dense_byte_estimate(reader, meta, nbytes: int) -> int:
    """Bytes the host fallback actually SHIPS for one chunk: the
    decoded dense stream, not the encoded pages.  Fixed-width types are
    exact from the footer (num_values x width); PLAIN byte arrays are
    ~their page bytes; dictionary-encoded byte arrays expand from
    index stream + pool to gathered values — mirror the 3x ratio the
    fetch estimate uses in the other direction."""
    desc = reader.schema.column(tuple(meta.path_in_schema))
    pt = desc.physical_type
    width = _FIXED_WIDTHS.get(pt)
    if pt == Type.FIXED_LEN_BYTE_ARRAY and desc.type_length:
        width = int(desc.type_length)
    if width is not None:
        return int(meta.num_values or 0) * width
    if set(meta.encodings or []) & _DICT_ENCODINGS:
        return nbytes * 3
    return nbytes


def _field_splittable(reader, rg, chunks) -> bool:
    """Footer-cheap mirror of the engine's row-split precondition
    (``engine._read_field_row_split``): every chunk of the field has an
    OffsetIndex AND the chunks share at least one interior page
    boundary to cut on.  Only consulted for over-cap fields, so the
    (tiny) OffsetIndex reads are rare."""
    n = int(rg.num_rows or 0)
    grid = None
    for chunk in chunks:
        if chunk.offset_index_offset is None:
            return False
        oi = reader.read_offset_index(chunk)
        if oi is None or not oi.page_locations:
            return False
        starts = {int(pl.first_row_index or 0) for pl in oi.page_locations}
        grid = starts if grid is None else (grid & starts)
    return bool(grid) and any(0 < p < n for p in grid)


def classify_chunk(desc, meta) -> str:
    """Map one column chunk to its host-decode cost class from footer
    metadata alone: "view" | "levels" | "value"."""
    value_encs = set(meta.encodings or []) - _LEVEL_ENCODINGS
    pt = desc.physical_type
    if value_encs <= {Encoding.PLAIN} and pt in _FIXED_TYPES:
        if desc.max_repetition_level == 0 and desc.max_definition_level == 0:
            return "view"
        if desc.max_repetition_level == 0:
            return "levels"
    return "value"


def estimate(reader, purpose: str = "rows", columns=None) -> EngineChoice:
    """Estimate host-vs-device wall for every row group of ``reader``
    (a ``ParquetFileReader``) and return the routed choice.

    ``purpose``: "rows" adds the device path's decoded-cell fetch cost
    (device→host), which the host engine never pays; "batch" models
    decode-to-device-arrays only (consumers keep arrays on device).
    ``columns``: optional set of top-level field names — only projected
    chunks cost anything, on either engine.
    """
    by_class: Dict[str, int] = {"view": 0, "levels": 0, "value": 0}
    fetch_bytes = 0
    n_groups = 0
    n_cells = 0
    cap = arena_cap()
    unsplit_host_s = 0.0   # device-path host fallback decode (see below)
    unsplit_bytes = 0
    for rg in reader.row_groups:
        n_groups += 1
        # per-field decompressed totals + splittability: a field whose
        # chunks alone exceed the arena cap must row-split to decode on
        # device, which needs an OffsetIndex with an interior page
        # boundary shared by the field's leaves.  Without one the
        # device engine host-falls-back for that field
        # (engine._read_field_host_fallback) — charge those bytes at
        # HOST decode rates on the device side so "auto" ranks the real
        # work, not the fused decode the device never runs.
        field_bytes: Dict[str, int] = {}
        field_chunks: Dict[str, list] = {}
        chunk_rows = []
        for chunk in rg.columns or []:
            meta = chunk.meta_data
            f = meta.path_in_schema[0]
            if columns is not None and f not in columns:
                continue
            desc = reader.schema.column(tuple(meta.path_in_schema))
            nbytes = int(meta.total_uncompressed_size or 0)
            cls = classify_chunk(desc, meta)
            field_bytes[f] = field_bytes.get(f, 0) + nbytes
            field_chunks.setdefault(f, []).append(chunk)
            chunk_rows.append((meta, f, nbytes, cls))
        unsplit_fields = {
            f for f, fb in field_bytes.items()
            if fb > cap
            and not _field_splittable(reader, rg, field_chunks[f])
        }
        for meta, f, nbytes, cls in chunk_rows:
            n_cells += int(meta.num_values or 0)
            if f in unsplit_fields:
                unsplit_host_s += nbytes / (_CLASS_GBPS[cls] * 1e9)
                unsplit_bytes += _dense_byte_estimate(
                    reader, meta, nbytes
                )
            else:
                by_class[cls] += nbytes
            if set(meta.encodings or []) & _DICT_ENCODINGS:
                # index-form dictionary columns fetch the packed index
                # stream + one pool per file — far fewer bytes than the
                # gathered values (BASELINE.md "index-form dictionaries")
                fetch_bytes += nbytes // 3
            else:
                fetch_bytes += nbytes
    total = sum(by_class.values())
    host_s = (
        sum(by_class[c] / (_CLASS_GBPS[c] * 1e9) for c in _CLASS_GBPS)
        + unsplit_host_s
    )
    h2d = _probe_h2d_gbps()
    tpu_s = (
        total / (h2d * 1e9)
        + total / (DEV_DECODE_GBPS * 1e9)
        + n_groups * GROUP_OVERHEAD_S
        # unsplittable fields host-decode inside the device engine and
        # ship the DECODED dense bytes (not the encoded pages) — no
        # fused-decode term for them
        + unsplit_host_s
        + unsplit_bytes / (h2d * 1e9)
    )
    if purpose == "rows":
        # cell materialization differs per engine (see HOST_CELL_S note)
        host_s += n_cells * HOST_CELL_S
        tpu_s += n_cells * DEV_CELL_S
    if unsplit_bytes:
        by_class["unsplit"] = unsplit_bytes
    choice = EngineChoice(
        engine="tpu" if tpu_s < host_s else "host",
        host_s=host_s,
        tpu_s=tpu_s,
        bytes_by_class=by_class,
    )
    if purpose == "rows" and choice.engine == "tpu":
        # the fetch term can only make the device path worse, and the
        # D2H probe is not free — only pay it when it could flip the
        # decision
        fixed, d2h_gbps = _probe_d2h_model()
        choice.tpu_s += n_groups * fixed + fetch_bytes / (d2h_gbps * 1e9)
        if choice.tpu_s >= host_s:
            choice.engine = "host"
    choice.reason = (
        f"est host {choice.host_s * 1e3:.1f} ms vs device "
        f"{choice.tpu_s * 1e3:.1f} ms over {total + unsplit_bytes} "
        f"decoded bytes"
        + (f" ({unsplit_bytes} via host fallback)" if unsplit_bytes else "")
        + f" (link {h2d:.2f} GB/s)"
    )
    return choice


def choose_engine(reader, purpose: str = "rows", columns=None) -> EngineChoice:
    """Route ``engine="auto"`` for an open ``ParquetFileReader``.

    Platform gate first (a non-TPU default backend always routes host —
    the device engine exists to use the TPU); then the x64 environment
    gate (the device engine requires ``jax_enable_x64``; "auto" must
    degrade to host, never error); then the footer cost model.  The
    decision lands in ``utils.trace`` (``trace.decisions()``) when
    tracing is enabled."""
    from .engine import _platform_is_tpu

    if not _platform_is_tpu():
        choice = EngineChoice(engine="host", reason="default backend is not a TPU")
    else:
        import jax

        if not jax.config.jax_enable_x64:
            choice = EngineChoice(
                engine="host",
                reason="jax_enable_x64 is off (device engine needs 64-bit "
                "types; auto degrades to host rather than erroring)",
            )
        else:
            try:
                choice = estimate(reader, purpose=purpose, columns=columns)
            except Exception as e:
                # auto must never fail for routing reasons (probe or
                # footer-shape surprises): the host engine always works
                choice = EngineChoice(
                    engine="host",
                    reason=f"cost estimate failed ({e!r}); host fallback",
                )
    trace.decision("engine_auto", choice.as_dict())
    return choice
